"""Unit coverage for the shared Pallas plumbing (``ops/pallas_utils.py``)
factored out of the paged/flash/fused kernels (ISSUE 18 satellite): the
alignment, clamping, and bias-padding helpers every host wrapper now calls,
and the shared scalar-prefetch grid builder."""

import jax
import jax.numpy as jnp
import pytest

from trlx_tpu.ops import pallas_utils as pu


def test_align_rows_interpret_is_exact():
    for n in (1, 7, 8, 100, 128, 129):
        assert pu.align_rows(n, interpret=True) == n


def test_align_rows_hardware_rounds_to_lanes():
    assert pu.align_rows(1, interpret=False) == 128
    assert pu.align_rows(128, interpret=False) == 128
    assert pu.align_rows(129, interpret=False) == 256
    assert pu.align_rows(5, interpret=False, lanes=8) == 8


def test_clamp_block_table_bounds_and_dtype():
    tbl = jnp.array([[0, 3, 7, 12], [2, 99, 5, 7]], dtype=jnp.int64)
    out = pu.clamp_block_table(tbl, num_blocks=8)
    assert out.dtype == jnp.int32
    assert out.max() == 7
    # in-range ids pass through untouched
    assert (out[0, :3] == jnp.array([0, 3, 7])).all()


@pytest.mark.parametrize("ndim", [3, 4])
def test_pad_bias_to_casts_and_pads_last_axis(ndim):
    shape = (2, 1, 5) if ndim == 3 else (2, 1, 3, 5)
    bias = jnp.full(shape, -1e9, dtype=jnp.bfloat16)
    out = pu.pad_bias_to(bias, 8)
    assert out.dtype == jnp.float32
    assert out.shape == shape[:-1] + (8,)
    # original columns preserved (through the f32 cast), padding exactly 0
    assert jnp.array_equal(out[..., :5], bias.astype(jnp.float32))
    assert (out[..., 5:] == 0.0).all()
    # already-wide bias is cast but not sliced
    assert pu.pad_bias_to(bias, 4).shape == shape


def test_resolve_interpret_respects_explicit_knob():
    assert pu.resolve_interpret(True) is True
    assert pu.resolve_interpret(False) is False
    assert pu.resolve_interpret(None) == pu.default_interpret()


@pytest.mark.skipif(
    not pu.has_pallas_tpu(), reason="Mosaic backend unavailable"
)
def test_paged_pool_grid_spec_drives_fetches_through_the_table():
    """The factored grid builder must behave exactly like the inline
    PrefetchScalarGridSpec it replaced: a trivial copy kernel assembling
    pool blocks through the table reproduces the gather view."""
    from jax.experimental import pallas as pl

    B, TB, bs, KV, D = 2, 3, 2, 1, 4
    NB = 5
    S = TB * bs
    pool = jnp.arange(NB * bs * KV * D, dtype=jnp.float32).reshape(
        NB, bs, KV, D
    )
    tbl = jnp.array([[4, 0, 2], [1, 1, 3]], dtype=jnp.int32)
    q = jnp.zeros((B, 1, D), dtype=jnp.float32)
    bias = jnp.zeros((B, 1, S), dtype=jnp.float32)

    def kernel(tbl_ref, q_ref, bias_ref, k_ref, v_ref, o_ref, k_buf, v_buf):
        j = pl.program_id(1)
        k_buf[pl.ds(j * bs, bs), :, :] = k_ref[0]

        @pl.when(j == TB - 1)
        def _finish():
            # fold the assembled row into the (1, 1, D) output so every
            # landed block is observable
            o_ref[...] = jnp.sum(k_buf[0:S, :, :], axis=(0, 1))[None, None, :]

    grid_spec = pu.paged_pool_grid_spec(
        batch=B,
        table_blocks=TB,
        block_size=bs,
        kv_heads=KV,
        head_dim=D,
        q_block=(1, 1, D),
        bias_block=(1, 1, S),
        out_block=(1, 1, D),
        scratch_rows=S,
        k_dtype=pool.dtype,
        v_dtype=pool.dtype,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, 1, D), jnp.float32),
        interpret=True,
    )(tbl, q, bias, pool, pool)
    expect = pool[tbl].reshape(B, S, KV, D).sum(axis=(1, 2))
    assert jnp.array_equal(out[:, 0], expect)
