"""Pipeline abstractions + registry.

Reference: ``trlx/pipeline/__init__.py:9-97``. Instead of torch DataLoaders,
``create_loader`` returns a lightweight host-side ``BatchLoader`` producing
numpy batches (collated to fixed shapes) — the host→device boundary is the
trainer's jitted step, which donates the arrays to the mesh.

Concurrency helpers live alongside the registry: :class:`PrefetchLoader`
(background-thread batch collation) here, and the bounded rollout chunk
pipeline in :mod:`trlx_tpu.pipeline.rollout_pipeline` (device generation
overlapping host reward scoring — docs/PERFORMANCE.md).
"""

import random
import sys
from abc import abstractmethod
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

_DATAPIPELINE: Dict[str, type] = {}


def register_datapipeline(name: Any = None) -> Callable:
    """Decorator registering a pipeline class by name."""

    def register_cls(cls, registered_name: str):
        _DATAPIPELINE[registered_name.lower()] = cls
        setattr(sys.modules[__name__], registered_name, cls)
        return cls

    if isinstance(name, type):
        return register_cls(name, name.__name__)

    def wrap(cls):
        return register_cls(cls, name if isinstance(name, str) else cls.__name__)

    return wrap


def get_pipeline(name: str) -> type:
    name = name.lower()
    if name in _DATAPIPELINE:
        return _DATAPIPELINE[name]
    raise ValueError(f"Unknown pipeline '{name}'. Registered: {sorted(_DATAPIPELINE)}")


class BatchLoader:
    """Minimal host-side batch iterator over an indexable dataset.

    Supports shuffling, drop_last, and a collate function; re-iterable
    (fresh order per epoch when shuffled).
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        collate_fn: Callable[[List[Any]], Any],
        shuffle: bool = False,
        drop_last: bool = False,
        seed: int = 0,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = random.Random(seed)

    def __len__(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def advance_epoch(self) -> None:
        """Consume one epoch's worth of shuffle randomness without iterating
        — the emergency-resume fast-forward (docs/RESILIENCE.md) skips whole
        epochs but must leave later epochs' shuffle orders exactly where an
        uninterrupted run would have them."""
        if self.shuffle:
            self._rng.shuffle(list(range(len(self.dataset))))

    def __iter__(self) -> Iterator[Any]:
        order = list(range(len(self.dataset)))
        if self.shuffle:
            self._rng.shuffle(order)
        for start in range(0, len(order), self.batch_size):
            idxs = order[start : start + self.batch_size]
            if self.drop_last and len(idxs) < self.batch_size:
                return
            yield self.collate_fn([self.dataset[i] for i in idxs])


class PrefetchLoader:
    """Background-thread prefetch over any re-iterable batch loader.

    The torch ``DataLoader(num_workers, prefetch_factor)`` capability the
    reference leans on (SURVEY.md §2.4 "torch C++ data machinery"): a worker
    thread keeps up to ``depth`` collated batches ready while the device
    consumes the current one. Collation bottoms out in the native C++
    ``pad_rows`` (ctypes releases the GIL), so the overlap is real. One
    worker preserves batch order and shuffle determinism; worker exceptions
    re-raise in the consumer.
    """

    def __init__(self, loader, depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.loader = loader
        self.depth = depth

    def __len__(self) -> int:
        return len(self.loader)

    def __iter__(self) -> Iterator[Any]:
        import queue
        import threading

        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        _END, _ERR = object(), object()

        def put(item) -> bool:
            """Enqueue unless the consumer cancelled; never blocks forever."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for batch in self.loader:
                    if not put(batch):
                        return  # cancelled: stop collating, drop the epoch
                put(_END)
            except BaseException as e:  # re-raised in the consumer
                put((_ERR, e))

        t = threading.Thread(target=worker, daemon=True, name="trlx-prefetch")
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    return
                if isinstance(item, tuple) and len(item) == 2 and item[0] is _ERR:
                    raise item[1]
                yield item
        finally:
            # consumer stopped (early break, exception, or exhaustion): cancel
            # the worker between batches rather than draining a whole epoch
            stop.set()
            try:
                q.get_nowait()  # unblock a put in flight
            except queue.Empty:
                pass
            try:
                t.join(timeout=5)
            except Exception:
                # interpreter shutdown: an infinite prompt iterator holding
                # this loader is GC'd after threading's teardown — the daemon
                # worker is already dead, the join just can't say so
                pass


class BasePipeline:
    """An indexable dataset of prompts/samples."""

    def __init__(self, path: str = "dataset"):
        self.path = path

    @abstractmethod
    def __getitem__(self, index: int):
        ...

    @abstractmethod
    def __len__(self) -> int:
        ...

    @abstractmethod
    def create_loader(self, batch_size: int, shuffle: bool = False, **kwargs) -> BatchLoader:
        ...


class BaseRolloutStore:
    """A mutable store of collected experiences."""

    def __init__(self, capacity: int = -1):
        self.history: List[Any] = []
        self.capacity = capacity

    @abstractmethod
    def push(self, exps: Iterable[Any]):
        """Push experiences to the store."""
        ...

    def __getitem__(self, index: int):
        return self.history[index]

    def __len__(self) -> int:
        return len(self.history)

    @abstractmethod
    def create_loader(self, batch_size: int, shuffle: bool = False, **kwargs) -> BatchLoader:
        ...
