"""Pipeline abstractions + registry.

Reference: ``trlx/pipeline/__init__.py:9-97``. Instead of torch DataLoaders,
``create_loader`` returns a lightweight host-side ``BatchLoader`` producing
numpy batches (collated to fixed shapes) — the host→device boundary is the
trainer's jitted step, which donates the arrays to the mesh.
"""

import random
import sys
from abc import abstractmethod
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

_DATAPIPELINE: Dict[str, type] = {}


def register_datapipeline(name: Any = None) -> Callable:
    """Decorator registering a pipeline class by name."""

    def register_cls(cls, registered_name: str):
        _DATAPIPELINE[registered_name.lower()] = cls
        setattr(sys.modules[__name__], registered_name, cls)
        return cls

    if isinstance(name, type):
        return register_cls(name, name.__name__)

    def wrap(cls):
        return register_cls(cls, name if isinstance(name, str) else cls.__name__)

    return wrap


def get_pipeline(name: str) -> type:
    name = name.lower()
    if name in _DATAPIPELINE:
        return _DATAPIPELINE[name]
    raise ValueError(f"Unknown pipeline '{name}'. Registered: {sorted(_DATAPIPELINE)}")


class BatchLoader:
    """Minimal host-side batch iterator over an indexable dataset.

    Supports shuffling, drop_last, and a collate function; re-iterable
    (fresh order per epoch when shuffled).
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        collate_fn: Callable[[List[Any]], Any],
        shuffle: bool = False,
        drop_last: bool = False,
        seed: int = 0,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = random.Random(seed)

    def __len__(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self) -> Iterator[Any]:
        order = list(range(len(self.dataset)))
        if self.shuffle:
            self._rng.shuffle(order)
        for start in range(0, len(order), self.batch_size):
            idxs = order[start : start + self.batch_size]
            if self.drop_last and len(idxs) < self.batch_size:
                return
            yield self.collate_fn([self.dataset[i] for i in idxs])


class BasePipeline:
    """An indexable dataset of prompts/samples."""

    def __init__(self, path: str = "dataset"):
        self.path = path

    @abstractmethod
    def __getitem__(self, index: int):
        ...

    @abstractmethod
    def __len__(self) -> int:
        ...

    @abstractmethod
    def create_loader(self, batch_size: int, shuffle: bool = False, **kwargs) -> BatchLoader:
        ...


class BaseRolloutStore:
    """A mutable store of collected experiences."""

    def __init__(self, capacity: int = -1):
        self.history: List[Any] = []
        self.capacity = capacity

    @abstractmethod
    def push(self, exps: Iterable[Any]):
        """Push experiences to the store."""
        ...

    def __getitem__(self, index: int):
        return self.history[index]

    def __len__(self) -> int:
        return len(self.history)

    @abstractmethod
    def create_loader(self, batch_size: int, shuffle: bool = False, **kwargs) -> BatchLoader:
        ...
