"""Offline data pipelines: dialogue tokenization, prompt pipeline, SFT dialog
store, ILQL rollout storage.

Behavioral parity targets: ``trlx/pipeline/offline_pipeline.py`` —
``tokenize_dialogue:28`` (left/right truncation over interleaved
prompt/output turns), ``DialogStore:72`` (-100 loss masking of non-output
tokens), ``PromptPipeline:101``, ``ILQLRolloutStorage:143``.

TPU redesign: all collators pad to **bucketed lengths** (next multiple of
``pad_multiple``) instead of ragged per-batch maxima, so the jitted train/
rollout steps see a small, finite set of shapes (recompilation control —
SURVEY.md §7 "hard parts").
"""

import json
import os
from dataclasses import dataclass
from typing import Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from trlx_tpu.data.ilql_types import (
    ILQLBatch,
    ILQLElement,
    ILQLSeq2SeqBatch,
    ILQLSeq2SeqElement,
)
from trlx_tpu.data.tokenizer import Tokenizer
from trlx_tpu.models.sft import IGNORE_INDEX
from trlx_tpu.pipeline import (
    BasePipeline,
    BaseRolloutStore,
    BatchLoader,
    register_datapipeline,
)


def round_up(n: int, multiple: int) -> int:
    """Round ``n`` up to the next multiple (minimum one multiple)."""
    return max(multiple, ((n + multiple - 1) // multiple) * multiple)


def pad_rows(
    rows: Sequence[Sequence[int]],
    pad_value: int,
    side: str = "right",
    pad_multiple: int = 8,
    fixed_length: Optional[int] = None,
    dtype=np.int32,
) -> Tuple[np.ndarray, np.ndarray]:
    """Stack ragged rows into a [B, L] array + mask, L bucketed or fixed.

    Dispatches to the C++ host runtime (``trlx_tpu/native``) when compiled —
    collation runs once per training batch on the host critical path — with
    this numpy loop as the behaviorally-identical fallback.
    """
    longest = max((len(r) for r in rows), default=1)
    length = fixed_length if fixed_length is not None else round_up(longest, pad_multiple)

    from trlx_tpu import native

    native_out = native.pad_rows_native(rows, pad_value, side, length, dtype)
    if native_out is not None:
        return native_out

    out = np.full((len(rows), length), pad_value, dtype=dtype)
    mask = np.zeros((len(rows), length), dtype=np.int32)
    for i, row in enumerate(rows):
        row = list(row)
        if len(row) > length:
            # keep the side adjacent to the content: left-padding keeps the
            # END of the row (tokens nearest the response), right-padding
            # keeps the start
            row = row[-length:] if side == "left" else row[:length]
        if side == "left":
            out[i, length - len(row) :] = row
            mask[i, length - len(row) :] = 1
        else:
            out[i, : len(row)] = row
            mask[i, : len(row)] = 1
    return out, mask


@dataclass
class DialogMessage:
    """One turn of a dialogue; ``is_output`` marks model-generated turns."""

    is_output: bool
    tokens: Tuple[int, ...]


def tokenize_dialogue(
    dialogue: Union[str, Iterable[str]],
    tokenizer: Tokenizer,
    max_length: int = 2048,
) -> List[DialogMessage]:
    """Tokenize an interleaved (prompt_1, output_1, prompt_2, ...) dialogue.

    A bare string ``s`` is shorthand for ``(bos, s)``. The final output turn
    gets the eos token appended if absent. The whole token budget is
    ``max_length``; truncation removes tokens from the configured
    ``truncation_side`` of the *flattened* dialogue while keeping turn
    boundaries, and empty turns are dropped.
    """
    if isinstance(dialogue, str):
        bos = tokenizer.bos_token or tokenizer.eos_token
        dialogue = [bos, dialogue]
    else:
        dialogue = list(dialogue)
        if len(dialogue) % 2 != 0:
            raise ValueError(
                "Dialogue must have an even number of phrases, alternating prompt and output"
            )

    if not dialogue[-1].endswith(tokenizer.eos_token):
        dialogue = dialogue[:-1] + [dialogue[-1] + tokenizer.eos_token]

    messages = [
        DialogMessage(
            is_output=(i % 2 == 1),
            tokens=tuple(tokenizer.encode(turn, add_special_tokens=False)),
        )
        for i, turn in enumerate(dialogue)
    ]

    # Keep a token budget of max_length over the flattened sequence, dropping
    # overflow from the truncation side while preserving turn order.
    total = sum(len(m.tokens) for m in messages)
    overflow = max(0, total - max_length)
    if overflow:
        if tokenizer.truncation_side == "left":
            trimmed = []
            for m in messages:
                if overflow >= len(m.tokens):
                    overflow -= len(m.tokens)
                    trimmed.append(DialogMessage(m.is_output, ()))
                else:
                    trimmed.append(DialogMessage(m.is_output, m.tokens[overflow:] if overflow else m.tokens))
                    overflow = 0
            messages = trimmed
        else:
            trimmed = []
            for m in reversed(messages):
                if overflow >= len(m.tokens):
                    overflow -= len(m.tokens)
                    trimmed.append(DialogMessage(m.is_output, ()))
                else:
                    trimmed.append(DialogMessage(m.is_output, m.tokens[: len(m.tokens) - overflow] if overflow else m.tokens))
                    overflow = 0
            messages = list(reversed(trimmed))

    return [m for m in messages if len(m.tokens) > 0]


class DialogStore(BaseRolloutStore):
    """SFT store: flattened dialogs with labels masked (``IGNORE_INDEX``) on
    non-output tokens."""

    def __init__(self, dialogs: List[List[DialogMessage]], tokenizer: Tokenizer):
        super().__init__()
        self.tokenizer = tokenizer
        self.history = []
        for d in dialogs:
            input_ids = np.array([t for m in d for t in m.tokens], dtype=np.int32)
            labels = np.array(
                [t if m.is_output else IGNORE_INDEX for m in d for t in m.tokens],
                dtype=np.int32,
            )
            self.history.append({"input_ids": input_ids, "labels": labels})

    def push(self, exps):
        self.history.extend(exps)

    def create_loader(
        self,
        batch_size: int,
        shuffle: bool = False,
        pad_multiple: int = 8,
        fixed_length: Optional[int] = None,
        seed: int = 0,
    ) -> BatchLoader:
        pad_id = self.tokenizer.pad_token_id

        def collate(elems: List[dict]) -> dict:
            input_ids, mask = pad_rows(
                [e["input_ids"] for e in elems], pad_id, "right", pad_multiple, fixed_length
            )
            labels, _ = pad_rows(
                [e["labels"] for e in elems], IGNORE_INDEX, "right", pad_multiple, fixed_length
            )
            return {"input_ids": input_ids, "attention_mask": mask, "labels": labels}

        return BatchLoader(self, batch_size, collate, shuffle=shuffle, seed=seed)


@register_datapipeline
class PromptPipeline(BasePipeline):
    """Tokenizes and right/left-truncates prompts to ``max_prompt_length``."""

    def __init__(self, prompts: List[str], max_prompt_length: int, tokenizer: Tokenizer):
        super().__init__()
        self.tokenizer = tokenizer
        out = tokenizer(
            prompts, truncation=True, max_length=max_prompt_length, add_special_tokens=False
        )
        self.prompts = [
            {"input_ids": np.asarray(ids, dtype=np.int32), "text": text}
            for ids, text in zip(out["input_ids"], prompts)
        ]

    def __getitem__(self, ix: int):
        return self.prompts[ix]

    def __len__(self) -> int:
        return len(self.prompts)

    def create_loader(
        self,
        batch_size: int,
        shuffle: bool = False,
        pad_multiple: int = 8,
        fixed_length: Optional[int] = None,
        seed: int = 0,
    ) -> BatchLoader:
        pad_id = self.tokenizer.pad_token_id

        def collate(elems: List[dict]) -> dict:
            # left-pad prompts: generation appends to the right
            ids, mask = pad_rows(
                [e["input_ids"] for e in elems], pad_id, "left", pad_multiple, fixed_length
            )
            return {
                "input_ids": ids,
                "attention_mask": mask,
                "text": [e["text"] for e in elems],
            }

        return BatchLoader(self, batch_size, collate, shuffle=shuffle, seed=seed)


def ilql_collate(
    elems: List[ILQLElement], pad_multiple: int = 8, fixed_length: Optional[int] = None
) -> ILQLBatch:
    input_ids, _ = pad_rows([e.input_ids for e in elems], 0, "right", pad_multiple, fixed_length)
    attn, _ = pad_rows([e.attention_mask for e in elems], 0, "right", pad_multiple, fixed_length)
    # actions/states lengths bucket to their own (smaller) maxima
    rewards, _ = pad_rows([e.rewards for e in elems], 0.0, "right", pad_multiple, None, np.float32)
    a_len = rewards.shape[1]
    actions_ixs, _ = pad_rows([e.actions_ixs for e in elems], 0, "right", 1, a_len)
    states_ixs, _ = pad_rows([e.states_ixs for e in elems], 0, "right", 1, a_len + 1)
    dones, _ = pad_rows([e.dones for e in elems], 0, "right", 1, a_len + 1)
    return ILQLBatch(input_ids, attn, rewards, states_ixs, actions_ixs, dones)


class ILQLRolloutStorage(BaseRolloutStore):
    """Rollout storage for offline ILQL training."""

    def __init__(self, input_ids, attention_mask, rewards, states_ixs, actions_ixs, dones):
        super().__init__()
        self.history = [
            ILQLElement(*row)
            for row in zip(input_ids, attention_mask, rewards, states_ixs, actions_ixs, dones)
        ]

    def push(self, exps):
        self.history.extend(exps)

    def create_loader(
        self,
        batch_size: int,
        shuffle: bool = True,
        pad_multiple: int = 8,
        fixed_length: Optional[int] = None,
        drop_last: bool = True,
        seed: int = 0,
    ) -> BatchLoader:
        return BatchLoader(
            self,
            batch_size,
            lambda elems: ilql_collate(elems, pad_multiple, fixed_length),
            shuffle=shuffle,
            drop_last=drop_last,
            seed=seed,
        )


def ilql_seq2seq_collate(
    elems: List[ILQLSeq2SeqElement], pad_multiple: int = 8, fixed_length: Optional[int] = None
) -> ILQLSeq2SeqBatch:
    input_ids, _ = pad_rows([e.input_ids for e in elems], 0, "right", pad_multiple, fixed_length)
    attn, _ = pad_rows([e.attention_mask for e in elems], 0, "right", pad_multiple, fixed_length)
    dec_ids, _ = pad_rows([e.decoder_input_ids for e in elems], 0, "right", pad_multiple, fixed_length)
    rewards, _ = pad_rows([e.rewards for e in elems], 0.0, "right", pad_multiple, None, np.float32)
    a_len = rewards.shape[1]
    actions_ixs, _ = pad_rows([e.actions_ixs for e in elems], 0, "right", 1, a_len)
    states_ixs, _ = pad_rows([e.states_ixs for e in elems], 0, "right", 1, a_len + 1)
    dones, _ = pad_rows([e.dones for e in elems], 0, "right", 1, a_len + 1)
    return ILQLSeq2SeqBatch(input_ids, attn, dec_ids, rewards, states_ixs, actions_ixs, dones)


class ILQLSeq2SeqRolloutStorage(BaseRolloutStore):
    """Rollout storage for offline seq2seq ILQL training."""

    def __init__(self, input_ids, attention_mask, decoder_input_ids, rewards, states_ixs, actions_ixs, dones):
        super().__init__()
        self.history = [
            ILQLSeq2SeqElement(*row)
            for row in zip(
                input_ids, attention_mask, decoder_input_ids, rewards, states_ixs, actions_ixs, dones
            )
        ]

    def push(self, exps):
        self.history.extend(exps)

    def create_loader(
        self,
        batch_size: int,
        shuffle: bool = True,
        pad_multiple: int = 8,
        fixed_length: Optional[int] = None,
        drop_last: bool = True,
        seed: int = 0,
    ) -> BatchLoader:
        return BatchLoader(
            self,
            batch_size,
            lambda elems: ilql_seq2seq_collate(elems, pad_multiple, fixed_length),
            shuffle=shuffle,
            drop_last=drop_last,
            seed=seed,
        )
