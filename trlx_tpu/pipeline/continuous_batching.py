"""Compatibility shim: the continuous-batching engine moved to
``trlx_tpu/engine/core.py`` when the unified generation Engine subsumed
the three generation paths (serial generate, the rollout pipeline, slot
refill) behind one interface with dense and paged KV backends.

``ContinuousBatchingEngine`` remains the historical name for the
dense-backend engine; new code should import
:class:`trlx_tpu.engine.ContinuousEngine` directly.
"""

from trlx_tpu.engine.core import (
    CompletedSequence,
    ContinuousEngine,
    EngineStats,
)

ContinuousBatchingEngine = ContinuousEngine

__all__ = ["CompletedSequence", "ContinuousBatchingEngine", "EngineStats"]
