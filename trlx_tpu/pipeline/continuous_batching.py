"""Continuous-batching rollout engine: host orchestration of slot-refill
decode (the device half lives in ``trlx_tpu/ops/slot_refill.py``).

The engine owns a prompt queue and ``B`` device slots. Each :meth:`step`:

1. **refills** freed slots from the queue — one on-demand prefill program
   writes fresh prompts into the freed KV-cache rows (skipped when nothing
   is free or the queue is empty);
2. runs one fixed-size **decode segment** (one compiled program, static
   shapes, reused for the whole collection);
3. **harvests** finished slots — each completed sequence ships immediately
   as an individual :class:`CompletedSequence` (device→host copies started
   asynchronously), freeing its slot for the next refill.

So the device batch stays full until the prompt queue is empty, instead of
every chunk draining at the pace of its longest row (PipelineRL,
arXiv:2509.19128; OPPO, arXiv:2509.25762).

Determinism: prompts are assigned to slots in submission order (queue FIFO,
freed slots filled lowest-index first) and harvested in slot order at each
segment boundary — the completion stream is a deterministic function of the
sampled lengths. Each prompt carries its own RNG key chain, so its tokens /
logprobs / values are bit-identical to plain ``generate`` on that prompt
regardless of which slot it lands in (``tests/test_continuous_batching.py``).

Utilization accounting (docs/PERFORMANCE.md): every decode step costs ``B``
slot-steps on device; only live (unfinished, occupied) slots produce real
tokens. ``slot_utilization`` = live ÷ total slot-steps — the number the
refill loop exists to keep high; ``padded_decode_frac`` = its complement,
the waste the serial chunked path pays on heterogeneous response lengths.

Thread affinity: the engine is single-threaded by design — only the
trainer's main thread calls ``enqueue_prompts``/``step``; the rollout
pipeline worker sees nothing but the harvested numpy copies. If shared
mutable state is ever introduced here, annotate it ``# guarded-by:
<lock>`` so graftlint's lock-discipline pass (docs/STATIC_ANALYSIS.md)
enforces the locking, as in ``rollout_pipeline.py``.
"""

import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

__all__ = ["CompletedSequence", "ContinuousBatchingEngine", "EngineStats"]


@dataclass
class CompletedSequence:
    """One finished rollout, harvested from its slot."""

    index: int  # global submission index (queue order)
    prompt_ids: np.ndarray  # [P] left-padded prompt
    prompt_mask: np.ndarray  # [P]
    tokens: np.ndarray  # [N] response tokens (pad after eos)
    logprobs: np.ndarray  # [N] behavior logprobs
    values: np.ndarray  # [N] value-head outputs (0 if no head)
    mask: np.ndarray  # [N] 1 on real response tokens (incl. eos)
    meta: Any = None  # caller payload (e.g. GRPO group id)


@dataclass
class _Request:
    index: int
    input_ids: np.ndarray  # [P]
    attention_mask: np.ndarray  # [P]
    key: np.ndarray  # [2] per-row RNG chain start
    meta: Any = None


@dataclass
class EngineStats:
    """Aggregate slot accounting over one engine lifetime."""

    segments: int = 0
    decode_steps: int = 0  # device decode steps executed
    slot_steps: int = 0  # decode_steps × B
    live_slot_steps: int = 0  # slot-steps spent on live rows
    refill_prefills: int = 0  # refill-program invocations
    refilled_rows: int = 0  # prompts placed into slots
    harvested: int = 0
    decode_s: float = 0.0  # wall time inside decode segments
    refill_s: float = 0.0  # wall time inside refill prefills

    @property
    def slot_utilization(self) -> float:
        if self.slot_steps == 0:
            return 0.0
        return self.live_slot_steps / self.slot_steps

    @property
    def padded_decode_frac(self) -> float:
        if self.slot_steps == 0:
            return 0.0
        return 1.0 - self.slot_utilization

    def metrics(self) -> Dict[str, float]:
        """The observability-layer gauges (registered in
        ``tests/test_metric_names.py``; see docs/OBSERVABILITY.md)."""
        stats: Dict[str, float] = {}
        stats["throughput/slot_utilization"] = self.slot_utilization
        stats["rollout/padded_decode_frac"] = self.padded_decode_frac
        stats["rollout/refill_prefills"] = float(self.refill_prefills)
        stats["rollout/refilled_rows"] = float(self.refilled_rows)
        stats["rollout/segments"] = float(self.segments)
        return stats


class ContinuousBatchingEngine:
    """Slot-refill decode over a fixed ``[B]`` slot batch.

    ``fns`` are the compiled programs from
    :func:`trlx_tpu.ops.slot_refill.make_slot_refill_fns`; ``span`` is an
    optional ``Observability.span``-shaped callable — each segment runs
    under a fenced ``rollout/segment`` span so the trace shows device-true
    decode time per segment.
    """

    def __init__(
        self,
        fns: Any,  # SlotRefillFns
        params: Any,
        pad_token_id: int,
        span: Optional[Callable[..., Any]] = None,
        prewarm: bool = True,
    ):
        import jax.numpy as jnp  # deferred: host module, device state here only

        self._jnp = jnp
        self.fns = fns
        self.params = params
        self.pad_token_id = int(pad_token_id)
        self._span = span
        self.state = fns.init_state()
        self.B = fns.batch_size
        self.P = fns.prompt_len
        self.N = fns.max_new_tokens
        self._queue: deque = deque()
        self._slots: List[Optional[_Request]] = [None] * self.B
        self._submitted = 0
        self.stats = EngineStats()
        if prewarm:
            # once per SlotRefillFns (the fns — and their compiled bucket
            # programs — outlive this engine via the trainer's program
            # cache; later engines skip straight through)
            self.state = self.fns.prewarm(self.params, self.state)

    # -- feeding ---------------------------------------------------------

    def enqueue_prompts(
        self,
        input_ids: np.ndarray,  # [b, p] left-padded, p <= P
        attention_mask: np.ndarray,  # [b, p]
        keys: np.ndarray,  # [b, 2] per-row RNG chain starts
        metas: Optional[List[Any]] = None,
    ) -> None:
        """Queue a prompt batch. Rows narrower than the engine width are
        left-padded to ``P`` (bit-stream-neutral only when the caller also
        runs its reference ``generate`` at width ``P``); wider rows are an
        error — the KV cache was sized for ``P``."""
        input_ids = np.asarray(input_ids, np.int32)
        attention_mask = np.asarray(attention_mask, np.int32)
        b, p = input_ids.shape
        if p > self.P:
            raise ValueError(
                f"prompt width {p} exceeds the engine's padded width {self.P}; "
                "size the engine from the widest prompt chunk (or pin the "
                "prompt loader's width with fixed_length)"
            )
        if p < self.P:
            pad = self.P - p
            input_ids = np.concatenate(
                [np.full((b, pad), self.pad_token_id, np.int32), input_ids], axis=1
            )
            attention_mask = np.concatenate(
                [np.zeros((b, pad), np.int32), attention_mask], axis=1
            )
        keys = np.asarray(keys)
        for i in range(b):
            self._queue.append(
                _Request(
                    index=self._submitted,
                    input_ids=input_ids[i],
                    attention_mask=attention_mask[i],
                    key=keys[i],
                    meta=metas[i] if metas is not None else None,
                )
            )
            self._submitted += 1

    # -- state -----------------------------------------------------------

    @property
    def pending(self) -> int:
        """Prompts queued but not yet in a slot."""
        return len(self._queue)

    @property
    def live(self) -> int:
        """Slots currently holding an unharvested sequence."""
        return sum(1 for r in self._slots if r is not None)

    @property
    def busy(self) -> bool:
        return self.live > 0 or self.pending > 0

    # -- the slot-refill state machine -----------------------------------

    def _refill(self) -> None:
        free = [s for s in range(self.B) if self._slots[s] is None]
        if not free or not self._queue:
            return
        rows: List[_Request] = []
        slots: List[int] = []
        for slot in free:
            if not self._queue:
                break
            req = self._queue.popleft()
            self._slots[slot] = req
            rows.append(req)
            slots.append(slot)
        t0 = time.perf_counter()
        # gather-prefill-scatter: only the fresh rows run the prefill
        # (bucketed to a power of two inside refill_rows)
        self.state = self.fns.refill_rows(
            self.params,
            self.state,
            np.stack([r.input_ids for r in rows]),
            np.stack([r.attention_mask for r in rows]),
            np.asarray(slots, np.int32),
            np.stack([r.key for r in rows]),
        )
        self.stats.refill_s += time.perf_counter() - t0
        self.stats.refill_prefills += 1
        self.stats.refilled_rows += len(rows)

    def _harvest(self) -> List[CompletedSequence]:
        done = np.asarray(self.state.done)
        finished = [
            s for s in range(self.B) if self._slots[s] is not None and done[s]
        ]
        if not finished:
            return []
        idx = self._jnp.asarray(np.asarray(finished, np.int32))
        rows = {
            name: getattr(self.state, name)[idx]
            for name in ("tokens", "logprobs", "values", "mask")
        }
        # ship immediately: start the device→host copies without blocking —
        # by the time the consumer reads them they have usually landed
        for leaf in rows.values():
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        host = {k: np.asarray(v) for k, v in rows.items()}
        completed = []
        for j, slot in enumerate(finished):  # slot order: deterministic
            req = self._slots[slot]
            self._slots[slot] = None
            completed.append(
                CompletedSequence(
                    index=req.index,
                    prompt_ids=req.input_ids,
                    prompt_mask=req.attention_mask,
                    tokens=host["tokens"][j],
                    logprobs=host["logprobs"][j],
                    values=host["values"][j],
                    mask=host["mask"][j],
                    meta=req.meta,
                )
            )
        self.stats.harvested += len(completed)
        return completed

    def step(self) -> List[CompletedSequence]:
        """One refill → segment → harvest turn; returns newly completed
        sequences (possibly empty while long rows keep decoding)."""
        self._refill()
        if self.live == 0:
            return []
        if self._span is not None:
            with self._span(
                "rollout/segment", live=self.live, pending=self.pending
            ) as sp:
                self.state, live_steps, steps = self.fns.decode_segment(
                    self.params, self.state
                )
                sp.fence((self.state.done, self.state.tokens))
            self.stats.decode_s += sp.duration
        else:
            t0 = time.perf_counter()
            self.state, live_steps, steps = self.fns.decode_segment(
                self.params, self.state
            )
            # fetching the step counters below blocks on the segment anyway
        steps = int(np.asarray(steps))
        live_steps = int(np.asarray(live_steps))
        if self._span is None:
            self.stats.decode_s += time.perf_counter() - t0
        self.stats.segments += 1
        self.stats.decode_steps += steps
        self.stats.slot_steps += steps * self.B
        self.stats.live_slot_steps += live_steps
        return self._harvest()

    def run(self) -> List[CompletedSequence]:
        """Drain queue + slots to completion (small-scale convenience; the
        trainers interleave :meth:`step` with downstream scoring instead)."""
        out: List[CompletedSequence] = []
        while self.busy:
            out.extend(self.step())
        return out
