"""DPO preference-pair storage: tokenized (prompt, chosen, rejected) triples
with completion masks, plus precomputed frozen-reference logprob sums.

Reuses the SFT tokenization contract (``tokenize_dialogue`` — same eos/
truncation semantics as the reference's offline pipeline,
``trlx/pipeline/offline_pipeline.py:28-69``): each half of the pair is the
dialogue ``[prompt, completion]`` and only completion tokens carry loss.
"""

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from trlx_tpu.pipeline import BaseRolloutStore, BatchLoader
from trlx_tpu.pipeline.offline_pipeline import pad_rows, tokenize_dialogue


def _flatten(messages) -> Dict[str, Any]:
    tokens, out_mask = [], []
    for m in messages:
        tokens.extend(m.tokens)
        out_mask.extend([1 if m.is_output else 0] * len(m.tokens))
    return {"tokens": tokens, "out_mask": out_mask}


class DPOStore(BaseRolloutStore):
    """Preference pairs, tokenized once up front (offline, like ILQL's
    stores); ``ref_chosen_logps``/``ref_rejected_logps`` are filled in by the
    trainer's one-time frozen-reference pass before learning starts."""

    def __init__(self, triples: Sequence[Sequence[str]], tokenizer, max_length: int):
        super().__init__()
        self.pad_token_id = tokenizer.pad_token_id
        self.history: List[Dict[str, Any]] = []
        for triple in triples:
            if len(triple) != 3:
                raise ValueError(
                    "DPO samples must be (prompt, chosen, rejected) triples; "
                    f"got a sample of length {len(triple)}"
                )
            prompt, chosen, rejected = triple
            self.history.append(
                {
                    "chosen": _flatten(tokenize_dialogue([prompt, chosen], tokenizer, max_length)),
                    "rejected": _flatten(tokenize_dialogue([prompt, rejected], tokenizer, max_length)),
                    "ref_chosen_logp": None,
                    "ref_rejected_logp": None,
                }
            )

    def push(self, exps):
        self.history += exps

    def collate(self, elems: List[Dict[str, Any]], pad_multiple: int = 8) -> Dict[str, np.ndarray]:
        # pairs interleave on the batch dim — (c0, r0, c1, r1, ...) — so any
        # contiguous even-sized slice (gradient-accumulation microbatches,
        # data-sharded shards) still holds whole pairs
        rows, masks, refs = [], [], []
        for e in elems:
            rows += [e["chosen"]["tokens"], e["rejected"]["tokens"]]
            masks += [e["chosen"]["out_mask"], e["rejected"]["out_mask"]]
            refs += [e["ref_chosen_logp"], e["ref_rejected_logp"]]
        ids, attn = pad_rows(rows, self.pad_token_id, "right", pad_multiple)
        out, _ = pad_rows(masks, 0, "right", 1, ids.shape[1])
        batch = {
            "input_ids": ids,  # [2B, T]: one forward scores both halves
            "attention_mask": attn,
            "out_mask": out,
        }
        if all(r is not None for r in refs):
            batch["ref_logps"] = np.asarray(refs, np.float32)
        return batch

    def create_loader(
        self,
        batch_size: int,
        shuffle: bool = False,
        pad_multiple: int = 8,
        drop_last: bool = True,
        seed: int = 0,
    ) -> BatchLoader:
        return BatchLoader(
            self,
            batch_size,
            lambda elems: self.collate(elems, pad_multiple),
            shuffle=shuffle,
            drop_last=drop_last,
            seed=seed,
        )
