"""PPO rollout storage.

Behavioral parity target: ``trlx/pipeline/ppo_pipeline.py:13-80`` — a replay
buffer of per-sample experiences with a left-pad-queries / right-pad-responses
collator and JSON rollout export. Collation pads to bucketed lengths (static
shapes for the jitted train step).
"""

import json
import os
from typing import List, Optional

import numpy as np

from trlx_tpu.data.ppo_types import PPORLBatch, PPORLElement
from trlx_tpu.pipeline import BaseRolloutStore, BatchLoader
from trlx_tpu.pipeline.offline_pipeline import pad_rows


class PPORolloutStorage(BaseRolloutStore):
    """Replay buffer of :class:`PPORLElement` used during PPO learning."""

    def __init__(self, pad_token_id: int):
        super().__init__()
        self.pad_token_id = pad_token_id
        self.history: List[PPORLElement] = []

    def push(self, exps: List[PPORLElement]):
        self.history += exps

    def clear_history(self):
        self.history = []

    def export_history(self, location: str):
        """Append rollouts as JSON (for algorithm-distillation datasets).

        Files are named by export ordinal, not wall clock: a timestamped
        name is nondeterministic (two runs disagree byte-for-byte on the
        dataset layout) and same-second exports silently OVERWRITE each
        other — the ordinal is derived from the directory state, so every
        export lands in a fresh file and reruns produce identical names."""
        assert os.path.exists(location)
        fpath = os.path.join(location, f"epoch-{self._next_export_index(location):06d}.json")

        def exp_to_dict(exp: PPORLElement) -> dict:
            return {
                "query_tensor": np.asarray(exp.query_tensor).tolist(),
                "response_tensor": np.asarray(exp.response_tensor).tolist(),
                "logprobs": np.asarray(exp.logprobs).tolist(),
                "values": np.asarray(exp.values).tolist(),
                "rewards": np.asarray(exp.rewards).tolist(),
            }

        with open(fpath, "w") as f:
            json.dump([exp_to_dict(exp) for exp in self.history], f)

    @staticmethod
    def _next_export_index(location: str) -> int:
        """Smallest ordinal above every ``epoch-*.json`` already present
        (sorted scan: never dependent on filesystem enumeration order)."""
        taken = []
        for name in sorted(os.listdir(location)):
            if not (name.startswith("epoch-") and name.endswith(".json")):
                continue
            try:
                taken.append(int(name[len("epoch-"):-len(".json")]))
            except ValueError:
                continue  # legacy timestamped exports don't block ordinals
        return max(taken) + 1 if taken else 0

    def collate(
        self,
        elems: List[PPORLElement],
        pad_multiple: int = 8,
        query_length: Optional[int] = None,
        response_length: Optional[int] = None,
    ) -> PPORLBatch:
        queries, query_mask = pad_rows(
            [e.query_tensor for e in elems], self.pad_token_id, "left", pad_multiple, query_length
        )
        responses, response_mask = pad_rows(
            [e.response_tensor for e in elems], self.pad_token_id, "right", pad_multiple, response_length
        )
        r_len = responses.shape[1]
        logprobs, _ = pad_rows([e.logprobs for e in elems], 0.0, "right", 1, r_len, np.float32)
        values, _ = pad_rows([e.values for e in elems], 0.0, "right", 1, r_len, np.float32)
        rewards, _ = pad_rows([e.rewards for e in elems], 0.0, "right", 1, r_len, np.float32)
        # async-collection behavior logprobs ride only when EVERY element
        # carries them (mixed stores train without the IW correction)
        behavior = None
        if all(e.behavior_logprobs is not None for e in elems):
            behavior, _ = pad_rows(
                [e.behavior_logprobs for e in elems], 0.0, "right", 1, r_len, np.float32
            )
        return PPORLBatch(
            query_tensors=queries,
            response_tensors=responses,
            logprobs=logprobs,
            values=values,
            rewards=rewards,
            query_mask=query_mask,
            response_mask=response_mask,
            behavior_logprobs=behavior,
        )

    def create_loader(
        self,
        batch_size: int,
        shuffle: bool = False,
        pad_multiple: int = 8,
        query_length: Optional[int] = None,
        response_length: Optional[int] = None,
        drop_last: bool = True,
        seed: int = 0,
    ) -> BatchLoader:
        return BatchLoader(
            self,
            batch_size,
            lambda elems: self.collate(elems, pad_multiple, query_length, response_length),
            shuffle=shuffle,
            drop_last=drop_last,
            seed=seed,
        )
