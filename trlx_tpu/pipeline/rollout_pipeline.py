"""Bounded-depth software pipeline for experience collection.

PPO rollout collection is the wall-clock hot loop (SURVEY §3.2), and the
per-chunk schedule is inherently two-sided: *device* work (KV-cache
generation, the scoring forward) that the main thread dispatches, and *host*
work (string decode, ``reward_fn``, device→host fetches) that needs nothing
from the device beyond the landed arrays. Serially, the device idles while
the host scores chunk *k*; pipelined, the main thread dispatches chunk
*k+1*'s generation while a background worker drains chunk *k*'s host work.
Within one ``make_experience`` call the policy params never change, so the
overlap is exactly equivalent to the serial schedule, not approximate
(OPPO, arxiv 2509.25762; PipelineRL, arxiv 2509.19128).

:class:`RolloutPipeline` is the chunk state machine behind that overlap:

- **one** worker thread executes submitted ``work()`` closures FIFO, so
  completion order equals submission order by construction;
- a chunk is *in flight* from ``submit()`` until its ``finalize`` callback
  returns; ``submit()`` blocks while ``depth`` chunks are in flight
  (bounded memory: at most ``depth`` chunks of host arrays coexist);
- ``finalize(result)`` runs on the **submitting** thread, in submission
  order — the home for sequential dependencies (PPO's running-moments
  update) that must see chunks in the same order as the serial path;
- worker exceptions propagate to the submitting thread on the next
  ``submit()``/``drain()`` (original traceback preserved), after which the
  pipeline cancels remaining work and joins the worker — no leaked threads,
  no silently dropped chunks;
- overlap accounting: ``host_work_s`` (time inside ``work()`` calls) and
  ``wait_s`` (time the submitting thread blocked on the pipeline) feed
  ``throughput/rollout_overlap_frac`` = host work hidden behind device work
  ÷ total rollout time (see docs/PERFORMANCE.md).

Chunk states: SUBMITTED → RUNNING → DONE → FINALIZED (or CANCELLED after an
error). The single worker + FIFO queues make the machine simple enough to
be obviously deterministic; depth only bounds *concurrency*, never order.
"""

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

__all__ = ["RolloutPipeline", "PipelineStats"]

_END = object()  # worker shutdown sentinel


@dataclass
class PipelineStats:
    """Aggregate timing of one pipeline lifetime (all fields in seconds)."""

    depth: int = 0
    chunks: int = 0
    # total time inside work() on the worker thread
    host_work_s: float = 0.0
    # time the submitting thread spent blocked waiting for the worker
    # (submit backpressure + drain) — host work NOT hidden behind device work
    wait_s: float = 0.0
    # per-chunk host-work durations, submission order
    chunk_host_s: List[float] = field(default_factory=list)

    @property
    def overlap_s(self) -> float:
        """Host work genuinely hidden behind the submitting thread's device
        work: everything the worker did minus what the submitter waited for."""
        return max(0.0, self.host_work_s - self.wait_s)

    def overlap_frac(self, total_s: float) -> float:
        """``overlap_s`` as a fraction of a caller-supplied total rollout
        wall time (the ``throughput/rollout_overlap_frac`` gauge)."""
        if total_s <= 0.0:
            return 0.0
        return min(1.0, self.overlap_s / total_s)


class _Chunk:
    __slots__ = ("index", "work", "result", "error")

    def __init__(self, index: int, work: Callable[[], Any]):
        self.index = index
        self.work = work
        self.result: Any = None
        self.error: Optional[BaseException] = None


class RolloutPipeline:
    """Single-worker, bounded in-flight chunk pipeline with ordered drain.

    Usage::

        pipe = RolloutPipeline(depth=2, finalize=fold_into_store,
                               tracer=obs.tracer)
        with pipe:
            while more_chunks:
                dev = dispatch_device_work()          # main thread
                pipe.submit(lambda d=dev: host_work(d))  # worker thread
        # __exit__ drains: every finalize has run, worker joined

    ``finalize`` is optional; without it ``submit``/``drain`` simply retire
    completed chunks. ``tracer`` (a :class:`trlx_tpu.observability.Tracer`)
    is optional; with it, the time the submitting thread blocks on the
    pipeline is recorded as ``rollout/device_idle`` spans — the device-idle
    accounting visible in the Perfetto export.
    """

    def __init__(
        self,
        depth: int = 2,
        finalize: Optional[Callable[[Any], Any]] = None,
        name: str = "rollout",
        tracer: Any = None,
    ):
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        self.depth = depth
        self.name = name
        # the worker writes host_work_s/chunk_host_s while the submitting
        # thread writes wait_s/chunks and reads the aggregate (overlap_frac
        # mid-run): all stats mutations take the lock. Enforced statically
        # by graftlint's lock-discipline pass (docs/STATIC_ANALYSIS.md).
        self._stats_lock = threading.Lock()
        self.stats = PipelineStats(depth=depth)  # guarded-by: _stats_lock
        self._finalize = finalize
        self._tracer = tracer
        self._todo: "queue.Queue" = queue.Queue()
        self._done: "queue.Queue" = queue.Queue()
        self._cancel = threading.Event()
        self._in_flight = 0
        self._submitted = 0
        self._finalized = 0
        self._failed: Optional[_Chunk] = None
        self._closed = False
        self._worker = threading.Thread(
            target=self._worker_loop, name=f"trlx-{name}-pipeline", daemon=True
        )
        self._worker.start()

    # -- worker side ----------------------------------------------------

    def _worker_loop(self) -> None:
        if self._tracer is not None and hasattr(self._tracer, "alias_current_thread"):
            # one stable named track per role across pipeline incarnations
            # (a fresh worker thread per make_experience call would otherwise
            # scatter the trace over one near-empty row per collection cycle)
            self._tracer.alias_current_thread(f"{self.name} pipeline worker")
        while True:
            chunk = self._todo.get()
            if chunk is _END:
                return
            if self._cancel.is_set():
                # an earlier chunk failed (or the consumer bailed): retire
                # without executing so a blocked submit/drain still wakes
                chunk.error = _Cancelled()
                self._done.put(chunk)
                continue
            t0 = time.perf_counter()
            try:
                chunk.result = chunk.work()
            except BaseException as e:  # propagated to the submitting thread
                chunk.error = e
                self._cancel.set()
            finally:
                dt = time.perf_counter() - t0
                with self._stats_lock:
                    self.stats.host_work_s += dt
                    self.stats.chunk_host_s.append(dt)
            self._done.put(chunk)

    # -- submitting-thread side -----------------------------------------

    def _retire_one(self, block: bool) -> bool:
        """Finalize the next completed chunk (submission order == completion
        order: one FIFO worker). Returns False when nothing was retired."""
        try:
            if block:
                t0 = time.perf_counter()
                if self._tracer is not None:
                    with self._tracer.span(f"{self.name}/device_idle"):
                        chunk = self._done.get()
                else:
                    chunk = self._done.get()
                with self._stats_lock:
                    self.stats.wait_s += time.perf_counter() - t0
            else:
                chunk = self._done.get_nowait()
        except queue.Empty:
            return False
        self._in_flight -= 1
        if chunk.error is not None:
            if not isinstance(chunk.error, _Cancelled):
                self._failed = self._failed or chunk
            return True
        # gate on _failed only (NOT the async _cancel flag): chunks that
        # completed before the failure point retire in order ahead of the
        # failed chunk (FIFO worker), and must finalize deterministically —
        # racing on _cancel would drop a completed prefix chunk or not
        # depending on when the worker flips the flag
        if self._failed is None:
            try:
                if self._finalize is not None:
                    self._finalize(chunk.result)
                self._finalized += 1
                with self._stats_lock:
                    self.stats.chunks += 1
            except BaseException:
                self._cancel.set()
                raise
        return True

    def _raise_failed(self) -> None:
        if self._failed is not None:
            err = self._failed.error
            self._failed = None
            self.close()
            raise err

    def submit(self, work: Callable[[], Any]) -> None:
        """Enqueue one chunk's host work; blocks (finalizing completed chunks
        in order) while ``depth`` chunks are already in flight. Raises a prior
        chunk's worker/finalize exception instead of accepting new work."""
        if self._closed:
            raise RuntimeError("submit() on a closed RolloutPipeline")
        # retire everything already completed (keeps the caller's view of
        # finalized results fresh), then block down below the depth bound
        while self._retire_one(block=False):
            pass
        while self._in_flight >= self.depth:
            self._retire_one(block=True)
        self._raise_failed()
        self._in_flight += 1
        self._submitted += 1
        self._todo.put(_Chunk(self._submitted - 1, work))

    def drain(self) -> None:
        """Block until every submitted chunk is finalized (or a failure is
        raised). Safe to call repeatedly; ``__exit__`` calls it on success."""
        while self._in_flight > 0:
            self._retire_one(block=True)
        self._raise_failed()

    @property
    def in_flight(self) -> int:
        return self._in_flight

    def close(self) -> None:
        """Cancel outstanding work and join the worker. Idempotent; never
        raises. Pending un-finalized chunks are dropped, not finalized."""
        if self._closed:
            return
        self._closed = True
        self._cancel.set()
        self._todo.put(_END)
        self._worker.join(timeout=30)
        # drop whatever completed after cancellation without finalizing
        while True:
            try:
                self._done.get_nowait()
                self._in_flight -= 1
            except queue.Empty:
                break

    def __enter__(self) -> "RolloutPipeline":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            try:
                self.drain()
            finally:
                self.close()
        else:
            # the submitting thread failed: don't run more finalizes under an
            # exception — cancel, join, and let the original error propagate
            self.close()


class _Cancelled(Exception):
    """Internal marker: chunk retired un-run after an earlier failure."""
