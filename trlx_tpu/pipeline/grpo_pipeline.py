"""GRPO rollout storage: the PPO replay-buffer/collator shape
(``trlx/pipeline/ppo_pipeline.py:13-80`` analogue) carrying per-sequence
advantages and reference logprobs instead of values/per-token rewards."""

from typing import List, Optional

import numpy as np

from trlx_tpu.data.grpo_types import GRPORLBatch, GRPORLElement
from trlx_tpu.pipeline.offline_pipeline import pad_rows
from trlx_tpu.pipeline.ppo_pipeline import PPORolloutStorage


class GRPORolloutStorage(PPORolloutStorage):
    """Replay buffer of :class:`GRPORLElement` used during GRPO learning.

    Shares the PPO store's push/clear/loader machinery; only the element
    fields differ (per-sequence advantage + reference logprobs instead of
    values/per-token rewards), so only collation and export change."""

    def export_history(self, location: str):
        """Append rollouts as JSON (reference ``ppo_pipeline.py:30-40``);
        ordinal file naming shared with the PPO store — deterministic and
        collision-free where the old timestamp name was neither."""
        import json
        import os

        assert os.path.exists(location)
        fpath = os.path.join(location, f"epoch-{self._next_export_index(location):06d}.json")
        with open(fpath, "w") as f:
            json.dump(
                [
                    {
                        "query_tensor": np.asarray(e.query_tensor).tolist(),
                        "response_tensor": np.asarray(e.response_tensor).tolist(),
                        "logprobs": np.asarray(e.logprobs).tolist(),
                        "ref_logprobs": np.asarray(e.ref_logprobs).tolist(),
                        "advantage": float(e.advantage),
                    }
                    for e in self.history
                ],
                f,
            )

    def collate(
        self,
        elems: List[GRPORLElement],
        pad_multiple: int = 8,
        query_length: Optional[int] = None,
        response_length: Optional[int] = None,
    ) -> GRPORLBatch:
        queries, query_mask = pad_rows(
            [e.query_tensor for e in elems], self.pad_token_id, "left", pad_multiple, query_length
        )
        responses, response_mask = pad_rows(
            [e.response_tensor for e in elems], self.pad_token_id, "right", pad_multiple, response_length
        )
        r_len = responses.shape[1]
        logprobs, _ = pad_rows([e.logprobs for e in elems], 0.0, "right", 1, r_len, np.float32)
        ref_logprobs, _ = pad_rows([e.ref_logprobs for e in elems], 0.0, "right", 1, r_len, np.float32)
        behavior = None
        if all(e.behavior_logprobs is not None for e in elems):
            behavior, _ = pad_rows(
                [e.behavior_logprobs for e in elems], 0.0, "right", 1, r_len, np.float32
            )
        return GRPORLBatch(
            query_tensors=queries,
            response_tensors=responses,
            logprobs=logprobs,
            ref_logprobs=ref_logprobs,
            advantages=np.asarray([e.advantage for e in elems], np.float32),
            query_mask=query_mask,
            response_mask=response_mask,
            behavior_logprobs=behavior,
        )
