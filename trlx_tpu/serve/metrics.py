"""Per-tenant / per-class serving SLO metrics (docs/SERVING.md).

Derived from the per-request spans the engine stamps on every
:class:`~trlx_tpu.engine.core.CompletedSequence`
(``t_enqueue → t_prefill0 → t_prefill1 → t_harvest``) plus the frontend's
wall timestamps:

- **queue wait** — enqueue → first prefill work (``t_prefill0 −
  t_enqueue``): the admission SLO's measured counterpart;
- **TTFT** — submit → first streamed token on the wire;
- **TPOT** — (done − first token) / (tokens − 1): steady-state decode
  cadence as the client sees it.

Two output shapes:

- :meth:`metrics` — the FLAT gauge dict merged into the trainer's step
  stats, every key registered in ``SERVE_KEYS`` (GL501 registry,
  ``trlx_tpu/analysis/conventions.py``) — aggregate percentiles over all
  traffic, the shape dashboards join on;
- :meth:`detail` — the nested per-(tenant, class) breakdown the HTTP
  ``/metrics`` endpoint serves (cardinality stays out of the flat
  registry).

Lock discipline (graftlint GL401/403): handler threads, the pump thread,
and the trainer thread all report here — every mutable field is
``# guarded-by: _lock``.
"""

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["ServeMetrics"]

# sample lists are clipped to this many most-recent entries per
# (tenant, class) — serving is long-lived, percentile memory must not be
_MAX_SAMPLES = 2048


def _pct(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    return float(np.percentile(np.asarray(samples, np.float64), q))


class ServeMetrics:
    def __init__(self):
        self._lock = threading.Lock()
        # (tenant, class) → samples, most recent _MAX_SAMPLES
        self._ttft: Dict[Tuple[str, str], List[float]] = {}  # guarded-by: _lock
        self._tpot: Dict[Tuple[str, str], List[float]] = {}  # guarded-by: _lock
        self._qwait: Dict[Tuple[str, str], List[float]] = {}  # guarded-by: _lock
        self._counts: Dict[Tuple[str, str], int] = {}  # guarded-by: _lock
        self.completed = 0  # guarded-by: _lock
        self.failed = 0  # guarded-by: _lock
        self.dropped = 0  # guarded-by: _lock
        self.streamed_tokens = 0  # guarded-by: _lock
        self.flood_rejected = 0  # guarded-by: _lock
        self.active = 0  # guarded-by: _lock
        self.params_version = 0  # guarded-by: _lock
        # admission / host-tier snapshots pushed by their owners
        self._admission: Dict[str, float] = {}  # guarded-by: _lock
        self._tier: Dict[str, float] = {}  # guarded-by: _lock

    # -- reporting (pump / handler / trainer threads) --------------------

    def observe_request(
        self,
        tenant: str,
        klass: str,
        ttft_s: float,
        tpot_s: float,
        queue_wait_s: float,
        tokens: int,
    ) -> None:
        key = (tenant, klass)
        with self._lock:
            for store, v in (
                (self._ttft, ttft_s),
                (self._tpot, tpot_s),
                (self._qwait, queue_wait_s),
            ):
                samples = store.setdefault(key, [])
                samples.append(float(v))
                if len(samples) > _MAX_SAMPLES:
                    del samples[: len(samples) - _MAX_SAMPLES]
            self._counts[key] = self._counts.get(key, 0) + 1
            self.completed += 1
            self.streamed_tokens += int(tokens)

    def note_failed(self) -> None:
        with self._lock:
            self.failed += 1

    def note_dropped(self) -> None:
        with self._lock:
            self.dropped += 1

    def note_flood_rejected(self, n: int) -> None:
        with self._lock:
            self.flood_rejected += int(n)

    def adjust_active(self, delta: int) -> None:
        with self._lock:
            self.active += delta

    def set_params_version(self, version: Optional[int]) -> None:
        with self._lock:
            self.params_version = int(version or 0)

    def set_admission(self, snapshot: Dict[str, float]) -> None:
        with self._lock:
            self._admission = dict(snapshot)

    def set_tier(self, snapshot: Dict[str, float]) -> None:
        with self._lock:
            self._tier = dict(snapshot)

    # -- output ----------------------------------------------------------

    def metrics(self) -> Dict[str, float]:
        """Flat ``SERVE_KEYS`` gauges (aggregate over every tenant/class)."""
        with self._lock:
            ttft = [v for s in self._ttft.values() for v in s]
            tpot = [v for s in self._tpot.values() for v in s]
            qwait = [v for s in self._qwait.values() for v in s]
            stats: Dict[str, float] = {}
            stats["serve/ttft_p50"] = _pct(ttft, 50)
            stats["serve/ttft_p95"] = _pct(ttft, 95)
            stats["serve/tpot_p50"] = _pct(tpot, 50)
            stats["serve/tpot_p95"] = _pct(tpot, 95)
            stats["serve/queue_wait_p50"] = _pct(qwait, 50)
            stats["serve/queue_wait_p95"] = _pct(qwait, 95)
            stats["serve/admitted"] = self._admission.get("admitted", 0.0)
            stats["serve/rejected"] = self._admission.get("rejected", 0.0)
            stats["serve/drain_rejected"] = self._admission.get(
                "drain_rejected", 0.0
            )
            stats["serve/flood_rejected"] = float(self.flood_rejected)
            stats["serve/completed"] = float(self.completed)
            stats["serve/failed"] = float(self.failed)
            stats["serve/dropped"] = float(self.dropped)
            stats["serve/active"] = float(self.active)
            stats["serve/streamed_tokens"] = float(self.streamed_tokens)
            stats["serve/host_tier_blocks"] = self._tier.get("blocks", 0.0)
            stats["serve/host_tier_spilled"] = self._tier.get("spilled", 0.0)
            stats["serve/host_tier_relanded"] = self._tier.get("relanded", 0.0)
            stats["serve/params_version"] = float(self.params_version)
            return stats

    def detail(self) -> Dict[str, Dict[str, float]]:
        """Per-(tenant, class) SLO breakdown for the ``/metrics`` endpoint —
        the cardinality that stays out of the flat gauge registry."""
        with self._lock:
            out: Dict[str, Dict[str, float]] = {}
            for key, n in sorted(self._counts.items()):
                tenant, klass = key
                out[f"{tenant}/{klass}"] = {
                    "completed": float(n),
                    "ttft_p50_s": _pct(self._ttft.get(key, []), 50),
                    "ttft_p95_s": _pct(self._ttft.get(key, []), 95),
                    "tpot_p50_s": _pct(self._tpot.get(key, []), 50),
                    "tpot_p95_s": _pct(self._tpot.get(key, []), 95),
                    "queue_wait_p50_s": _pct(self._qwait.get(key, []), 50),
                    "queue_wait_p95_s": _pct(self._qwait.get(key, []), 95),
                }
            return out
