"""Host-RAM tiering of evicted prefix blocks (docs/SERVING.md).

When the block pool (or a tenant quota) forces the prefix cache to evict a
committed entry, the KV bytes it took a prefill to produce are normally
gone — a re-arriving prompt pays the full re-prefill. :class:`HostTier` is
the second tier: the cache's ``spill`` hook copies the evicted block's
pool rows to a bounded host pool (keyed by the entry's content-chained
digest, which survives evict/re-insert cycles), and the engine's admission
path (``ContinuousEngine._prepare_row``) probes it for the chunks beyond
the device hit — a host hit allocates a fresh device block and writes the
saved bytes back instead of re-prefilling them.

Bit-equality by construction: a spill is ``device_get`` of committed
(immutable) block rows, a re-land is a verbatim ``.at[blocks].set`` of the
same bytes — no compute touches the values, so a re-landed prefix is
byte-identical to the device-resident prefix it was spilled from, which
the prefix-cache tests pin byte-identical to a cold prefill. Pinned across
block sizes in ``tests/test_serve.py``.

Sharp edges (docs/SERVING.md):

- The tier is flushed whenever the engine adopts changed params
  (``swap_params`` / ``begin_collection``) — spilled KV is only valid
  under the params that computed it, exactly like device-side entries.
- Spill/re-land move ``block_bytes`` per block over PCIe/host memory; the
  win is elastic: it pays off when re-prefill compute > transfer, which is
  the regime long shared prompts live in (measured by
  ``scripts/bench_serve_ab.py``).
- The write-back runs un-donated (CPU backends do not implement buffer
  donation and would warn); on a real accelerator a donated variant would
  avoid the transient pool copy.

Thread affinity: owned and touched ONLY by the thread driving the engine
(the serve pump, or the trainer's main thread) — same single-threaded
contract as the allocator and prefix cache. Serve-side metric snapshots go
through ``ServeMetrics``, never through direct cross-thread reads here.
"""

from collections import OrderedDict
from typing import Any, Dict

import numpy as np

__all__ = ["HostTier"]


def _read_block(pool: Any, block: int) -> Any:
    """Host (numpy) copy of one block's rows across every pool leaf."""
    import jax

    def rd(leaf):
        if leaf is None:
            return None
        if leaf.ndim - 4 == 1:  # scanned: [L, NB, bs, kvH, D]
            return np.asarray(leaf[:, block])
        return np.asarray(leaf[block])

    return jax.tree_util.tree_map(rd, pool, is_leaf=lambda x: x is None)


def _write_blocks(pool: Any, blocks: Any, vals: Any) -> Any:
    """New pool with each ``vals[i]`` written verbatim into ``blocks[i]``'s
    rows — ONE copy-on-write of each pool leaf for the whole run (the
    per-block variant cost a full pool copy per block, which dominated the
    re-land path for multi-block prefixes)."""
    import jax
    import jax.numpy as jnp

    idx = np.asarray(blocks, np.int32)

    def wr(leaf, *vs):
        if leaf is None:
            return None
        # stack host-side: one device put for the whole run, not one per
        # block (the per-val jnp.asarray puts dominated the re-land cost)
        if leaf.ndim - 4 == 1:  # scanned: [L, NB, bs, kvH, D]
            stacked = np.stack([np.asarray(v) for v in vs], 1)
            return leaf.at[:, idx].set(jnp.asarray(stacked, leaf.dtype))
        stacked = np.stack([np.asarray(v) for v in vs], 0)
        return leaf.at[idx].set(jnp.asarray(stacked, leaf.dtype))

    return jax.tree_util.tree_map(wr, pool, *vals, is_leaf=lambda x: x is None)


class HostTier:
    """Bounded LRU host pool of spilled prefix-block KV, digest-keyed."""

    def __init__(self, max_blocks: int, block_bytes: int = 0):
        if max_blocks < 1:
            raise ValueError(f"host tier needs max_blocks >= 1, got {max_blocks}")
        self.max_blocks = int(max_blocks)
        self.block_bytes = int(block_bytes)  # informational (metrics)
        self._pool: "OrderedDict[bytes, Any]" = OrderedDict()
        # lifetime counters, read via snapshot() from the owning thread
        self.spilled = 0
        self.evicted = 0
        self.hits = 0
        self.misses = 0
        self.relanded_blocks = 0

    def __len__(self) -> int:
        return len(self._pool)

    def __contains__(self, digest: bytes) -> bool:
        return digest in self._pool

    # -- owning-thread operations ----------------------------------------

    def spill(self, digest: bytes, pool: Any, block: int) -> None:
        """Copy ``block``'s rows host-side under ``digest`` (LRU insert);
        beyond capacity the least-recently-touched spill is dropped."""
        if digest in self._pool:
            self._pool.move_to_end(digest)
            return
        self._pool[digest] = _read_block(pool, block)
        self.spilled += 1
        while len(self._pool) > self.max_blocks:
            self._pool.popitem(last=False)
            self.evicted += 1

    def probe(self, digest: bytes) -> bool:
        hit = digest in self._pool
        if hit:
            self.hits += 1
        else:
            self.misses += 1
        return hit

    def reland(self, digest: bytes, pool: Any, block: int) -> Any:
        """Write the spilled bytes back into a freshly allocated device
        ``block``; returns the new pool. The host copy is retained (the
        device entry may be evicted again before the host LRU turns)."""
        return self.reland_many([digest], pool, [block])

    def reland_many(self, digests: Any, pool: Any, blocks: Any) -> Any:
        """Re-land a consecutive run of spilled chunks in one pool update:
        each pool leaf is copy-on-written ONCE for the whole run instead of
        once per block (``scripts/bench_serve_ab.py`` measures the
        difference on multi-block prefixes)."""
        vals = [self._pool[d] for d in digests]
        for d in digests:
            self._pool.move_to_end(d)
        self.relanded_blocks += len(vals)
        return _write_blocks(pool, blocks, vals)

    def clear(self) -> None:
        """Drop every spilled block — params changed, the bytes are void."""
        self._pool.clear()

    def snapshot(self) -> Dict[str, float]:
        """Counter snapshot for the serve metrics pump (owning thread)."""
        return {
            "blocks": float(len(self._pool)),
            "bytes": float(len(self._pool) * self.block_bytes),
            "spilled": float(self.spilled),
            "evicted": float(self.evicted),
            "hits": float(self.hits),
            "misses": float(self.misses),
            "relanded": float(self.relanded_blocks),
        }
