"""Serving frontend on the continuous-batching engine (docs/SERVING.md).

``request``   — per-request handler↔pump shared state (bounded streaming)
``scheduler`` — SLO-aware admission control (429/503 at the door)
``metrics``   — per-tenant/per-class TTFT/TPOT/queue-wait SLO metrics
``frontend``  — stdlib threaded HTTP server (SSE token streaming)
``server``    — the pump thread owning the serving engine
``tiering``   — host-RAM second tier for evicted prefix-cache KV

Imports are deliberately lazy at the package level: the serving stack
pulls in jax only when an engine is actually driven.
"""

__all__ = [
    "AdmissionController",
    "HostTier",
    "ServeMetrics",
    "ServeRequest",
    "ServeServer",
]


def __getattr__(name: str):
    if name == "ServeServer":
        from trlx_tpu.serve.server import ServeServer

        return ServeServer
    if name == "ServeRequest":
        from trlx_tpu.serve.request import ServeRequest

        return ServeRequest
    if name == "ServeMetrics":
        from trlx_tpu.serve.metrics import ServeMetrics

        return ServeMetrics
    if name == "AdmissionController":
        from trlx_tpu.serve.scheduler import AdmissionController

        return AdmissionController
    if name == "HostTier":
        from trlx_tpu.serve.tiering import HostTier

        return HostTier
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
