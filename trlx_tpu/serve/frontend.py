"""Stdlib-only HTTP frontend over :class:`~trlx_tpu.serve.server.ServeServer`.

Endpoints (docs/SERVING.md):

- ``POST /v1/generate`` — JSON body::

      {"prompt_ids": [1, 2, 3],      # required, token ids
       "tenant": "team-a",           # optional (serve.default_tenant)
       "class": "interactive",       # optional priority class
       "seed": 7,                    # optional per-request RNG seed
       "stream": true}               # optional: SSE token streaming

  Non-streaming: one JSON response with the full token list. Streaming:
  ``text/event-stream`` — one ``data: {"tokens": [...]}`` event per decode
  delta, then ``data: {"done": true, ...}`` (chunked transfer; the SSE
  frames ride on ``ThreadingHTTPServer``'s per-connection handler thread).
  Rejections: **429** with a ``Retry-After`` header when the queue-wait
  SLO is provably blown, **503** while draining, **400** on malformed
  bodies.

- ``GET /healthz`` — liveness + drain state.
- ``GET /metrics`` — the flat ``SERVE_KEYS`` gauges plus the per-tenant /
  per-class SLO breakdown.

Handler threads only ever touch the ``ServeServer`` handoff surface
(``submit`` → per-request condition variables) — never the engine. Slow or
vanished consumers are the *request's* problem (bounded stream buffer →
DROPPED; ``BrokenPipeError`` → ``drop()``), never the pump's.

The ``slow_client@request:N`` fault (docs/RESILIENCE.md) is consulted
HERE, on the consumer side: the afflicted handler simply stops reading its
deltas, which must end with the producer dropping the connection while the
engine finishes the sequence — the wedge-free-slot guarantee the
resilience test pins.
"""

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

import numpy as np

from trlx_tpu.resilience.faults import poll_fault

__all__ = ["make_http_server"]

# how long a non-streaming handler waits for its result before giving up
# (the admission gate bounds queue wait well below this; a hit means the
# server is draining or wedged, and 504 beats a handler thread leak)
_RESULT_TIMEOUT_S = 120.0


class _ServeHTTPServer(ThreadingHTTPServer):
    daemon_threads = True  # handler threads must never outlive shutdown
    allow_reuse_address = True
    serve_server: Any = None  # the ServeServer, set by make_http_server


class _Handler(BaseHTTPRequestHandler):
    server_version = "trlx-tpu-serve/1.0"
    protocol_version = "HTTP/1.1"

    # silence the default stderr access log (serving rides inside training
    # runs whose stdout/stderr are the trainer's)
    def log_message(self, format: str, *args: Any) -> None:
        pass

    def _send_json(self, status: int, payload: dict, headers: dict = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    # -- GET -------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        srv = self.server.serve_server
        if self.path == "/healthz":
            self._send_json(
                200,
                {
                    "status": "draining" if srv.admission.draining else "ok",
                    "active": srv.metrics.metrics()["serve/active"],
                },
            )
        elif self.path == "/metrics":
            self._send_json(200, srv.detail_metrics())
        else:
            self._send_json(404, {"error": f"no such path {self.path!r}"})

    # -- POST ------------------------------------------------------------

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        srv = self.server.serve_server
        if self.path != "/v1/generate":
            self._send_json(404, {"error": f"no such path {self.path!r}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"{}")
            prompt_ids = np.asarray(body["prompt_ids"], np.int32)
        except (KeyError, ValueError, TypeError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": f"malformed request body: {e}"})
            return
        req, rejection = srv.submit(
            prompt_ids=prompt_ids,
            tenant=body.get("tenant"),
            klass=body.get("class"),
            seed=int(body.get("seed", 0)),
            stream=bool(body.get("stream", False)),
            max_new_tokens=int(body.get("max_new_tokens", 0)),
        )
        if req is None:
            status, reason, retry_after = rejection
            headers = {}
            if status == 429 and retry_after > 0:
                headers["Retry-After"] = str(int(retry_after))
            self._send_json(status, {"error": reason}, headers)
            return
        if req.stream:
            self._stream_response(req)
        else:
            self._unary_response(req)

    def _unary_response(self, req: Any) -> None:
        state = req.wait_done(timeout=_RESULT_TIMEOUT_S)
        snap = req.snapshot()
        if state == "DONE":
            self._send_json(
                200,
                {
                    "tokens": [int(t) for t in req.result_tokens],
                    "n_tokens": snap["n_tokens"],
                    "params_version": snap["params_version"],
                    "tenant": snap["tenant"],
                    "class": snap["class"],
                },
            )
        elif state == "pending":
            req.drop("handler result timeout")
            self._send_json(504, {"error": "generation timed out"})
        else:
            self._send_json(503, {"error": snap["error"] or state.lower()})

    def _stream_response(self, req: Any) -> None:
        # the slow-client fault drill: THIS consumer stalls forever — the
        # producer must fill the bounded buffer, drop the request, and keep
        # the engine slot decoding to harvest (docs/RESILIENCE.md)
        stalled = poll_fault("slow_client", request=req.rid)
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            while True:
                if stalled:
                    # injected stalled consumer: stop reading events until
                    # the producer gives up on us
                    if req.wait_done(timeout=0.1) in ("DROPPED", "FAILED"):
                        return
                    continue
                kind, payload = req.next_event(timeout=0.1)
                if kind == "tokens":
                    self._write_sse(
                        {"tokens": [int(t) for t in payload]}
                    )
                elif kind == "done":
                    snap = req.snapshot()
                    self._write_sse(
                        {
                            "done": True,
                            "n_tokens": snap["n_tokens"],
                            "params_version": snap["params_version"],
                        }
                    )
                    self._write_chunk(b"")  # chunked-transfer terminator
                    return
                elif kind in ("failed", "dropped"):
                    self._write_sse({"error": payload, "state": kind})
                    self._write_chunk(b"")
                    return
                # "pending": poll again
        except (BrokenPipeError, ConnectionResetError):
            req.drop("client connection lost")

    def _write_sse(self, payload: dict) -> None:
        self._write_chunk(f"data: {json.dumps(payload)}\n\n".encode())

    def _write_chunk(self, data: bytes) -> None:
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()


def make_http_server(serve_server: Any, host: str, port: int) -> _ServeHTTPServer:
    """Bind the threaded HTTP frontend (``port=0`` = ephemeral — read the
    bound port back from ``ServeServer.port``)."""
    httpd = _ServeHTTPServer((host, port), _Handler)
    httpd.serve_server = serve_server
    return httpd
