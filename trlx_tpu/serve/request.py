"""ServeRequest: one in-flight serving request's cross-thread state.

A request is born on an HTTP handler thread (``frontend.py``), generated
by the pump thread (``server.py``), and consumed — token deltas, then the
final result — back on the handler thread. This class is the ONLY object
those two threads share per request, so everything mutable on it is
guarded by one condition variable:

- the pump *produces*: stream deltas (``push_tokens``), terminal
  transitions (``finish`` / ``fail``), lifecycle timestamps;
- the handler *consumes*: ``next_event`` blocks on the condition until a
  delta or the terminal state arrives;
- the bounded stream buffer is the slow-client firewall
  (docs/RESILIENCE.md ``slow_client@request:N``): when a stalled consumer
  lets ``max_buffered`` deltas pile up, the producer marks the request
  DROPPED and stops buffering — the engine slot keeps decoding and
  harvests normally (its work may feed the prefix cache), only the
  *connection* is abandoned. The pump never blocks on a client.

States::

    QUEUED ──► GENERATING ──► DONE
       │            ├───────► FAILED   (tenant quota / internal error)
       │            └───────► DROPPED  (slow or vanished client)
       └──► (REJECTED requests never construct a ServeRequest)

Lock discipline (graftlint GL401/403, docs/STATIC_ANALYSIS.md): all
cross-thread fields are annotated ``# guarded-by: _cond`` and only touched
inside ``with self._cond:``.
"""

import threading
import time
from typing import Any, List, Optional, Tuple

import numpy as np

__all__ = ["ServeRequest"]

# terminal states — next_event() unblocks for good once one is reached
_TERMINAL = ("DONE", "FAILED", "DROPPED")


class ServeRequest:
    """One serving request's handler↔pump shared state (see module doc)."""

    def __init__(
        self,
        rid: int,
        prompt_ids: np.ndarray,  # [p] token ids (left-padded or raw)
        prompt_mask: np.ndarray,  # [p]
        tenant: str,
        klass: str,
        seed: int,
        stream: bool,
        max_new_tokens: int = 0,
        max_buffered: int = 64,
    ):
        # immutable after construction (set before the request escapes the
        # submitting thread — safe to read anywhere unlocked)
        self.rid = rid
        self.prompt_ids = np.asarray(prompt_ids, np.int32)
        self.prompt_mask = np.asarray(prompt_mask, np.int32)
        self.tenant = tenant
        self.klass = klass
        self.seed = int(seed)
        self.stream = bool(stream)
        self.max_new_tokens = int(max_new_tokens)
        self.max_buffered = max(1, int(max_buffered))
        self.t_submit = time.perf_counter()

        # pump-thread-only terminal-accounting latch (server.py _terminal)
        self._accounted = False

        self._cond = threading.Condition()
        self.state = "QUEUED"  # guarded-by: _cond
        self.error: Optional[str] = None  # guarded-by: _cond
        # undelivered stream deltas, each a [k] int32 chunk of new tokens
        self._chunks: List[np.ndarray] = []  # guarded-by: _cond
        # full masked response + engine span timestamps, set at finish()
        self.result_tokens: Optional[np.ndarray] = None  # guarded-by: _cond
        self.t_first_token = 0.0  # guarded-by: _cond
        self.t_done = 0.0  # guarded-by: _cond
        self.queue_wait_s = 0.0  # guarded-by: _cond
        self.n_tokens = 0  # guarded-by: _cond
        self.params_version: Optional[int] = None  # guarded-by: _cond

    # -- pump (producer) side --------------------------------------------

    def mark_generating(self, params_version: Optional[int]) -> None:
        """Request handed to the engine under ``params_version``."""
        with self._cond:
            if self.state == "QUEUED":
                self.state = "GENERATING"
                self.params_version = params_version
            self._cond.notify_all()

    def push_tokens(self, delta: np.ndarray) -> bool:
        """Buffer freshly decoded tokens for the streaming consumer.
        Returns False (and transitions to DROPPED) when the consumer has
        stalled past the buffer bound — the caller stops streaming this
        request but MUST keep driving the engine."""
        with self._cond:
            if self.state in _TERMINAL:
                return self.state == "DONE"
            if len(self._chunks) >= self.max_buffered:
                self.state = "DROPPED"
                self.error = (
                    f"client stalled: {len(self._chunks)} undelivered stream "
                    "chunks (serve.stream_buffer) — connection dropped"
                )
                self._chunks.clear()
                self._cond.notify_all()
                return False
            if self.t_first_token == 0.0:
                self.t_first_token = time.perf_counter()
            self._chunks.append(np.asarray(delta, np.int32))
            self._cond.notify_all()
            return True

    def finish(
        self,
        tokens: np.ndarray,
        queue_wait_s: float,
        t_first_token: float = 0.0,
    ) -> None:
        """Terminal success: ``tokens`` is the full masked response (what a
        solo ``generate`` at the served params version returns)."""
        with self._cond:
            if self.state in _TERMINAL:
                return
            self.result_tokens = np.asarray(tokens, np.int32)
            self.n_tokens = int(self.result_tokens.shape[0])
            self.queue_wait_s = float(queue_wait_s)
            if self.t_first_token == 0.0:
                self.t_first_token = t_first_token or time.perf_counter()
            self.t_done = time.perf_counter()
            self.state = "DONE"
            self._cond.notify_all()

    def fail(self, error: str) -> None:
        with self._cond:
            if self.state in _TERMINAL:
                return
            self.error = error
            self.t_done = time.perf_counter()
            self.state = "FAILED"
            self._chunks.clear()
            self._cond.notify_all()

    def drop(self, reason: str) -> None:
        """Consumer vanished (broken pipe / stall): stop buffering, keep
        the engine-side work running to completion."""
        with self._cond:
            if self.state in _TERMINAL:
                return
            self.error = reason
            self.t_done = time.perf_counter()
            self.state = "DROPPED"
            self._chunks.clear()
            self._cond.notify_all()

    # -- handler (consumer) side -----------------------------------------

    def next_event(self, timeout: float = 0.1) -> Tuple[str, Any]:
        """Block up to ``timeout`` for the next consumer event:

        - ``("tokens", np.ndarray)`` — one stream delta;
        - ``("done", np.ndarray)``   — terminal, remaining deltas already
          drained (the payload is the FULL masked response);
        - ``("failed"|"dropped", str)`` — terminal, error message;
        - ``("pending", None)``      — timeout, poll again.
        """
        with self._cond:
            if not self._chunks and self.state not in _TERMINAL:
                self._cond.wait(timeout)
            if self._chunks:
                return "tokens", self._chunks.pop(0)
            if self.state == "DONE":
                return "done", self.result_tokens
            if self.state == "FAILED":
                return "failed", self.error or "internal error"
            if self.state == "DROPPED":
                return "dropped", self.error or "connection dropped"
            return "pending", None

    def wait_done(self, timeout: float = 60.0) -> str:
        """Block until terminal (non-streaming responses); returns the
        terminal state, or ``"pending"`` on timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self.state not in _TERMINAL:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return "pending"
                self._cond.wait(min(remain, 0.25))
            return self.state

    def snapshot(self) -> dict:
        """Locked copy of the SLO-relevant fields (metrics/HTTP payloads)."""
        with self._cond:
            return {
                "rid": self.rid,
                "state": self.state,
                "tenant": self.tenant,
                "class": self.klass,
                "error": self.error,
                "n_tokens": self.n_tokens,
                "params_version": self.params_version,
                "ttft_s": (
                    self.t_first_token - self.t_submit
                    if self.t_first_token
                    else 0.0
                ),
                "queue_wait_s": self.queue_wait_s,
            }
