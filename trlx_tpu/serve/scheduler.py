"""SLO-aware admission control for the serving frontend.

Priority *scheduling* lives in the engine (``engine/core.py``: per-class
queue pop order + segment-boundary preemption); this module is the gate in
front of it — the decision, made on the HTTP handler thread at submit
time, whether a request should enter the queue at all:

- **503 + draining** once :meth:`set_draining` ran (graceful shutdown,
  docs/SERVING.md): in-flight requests finish, new ones are turned away
  immediately instead of being accepted into a server that will not serve
  them.
- **429 + Retry-After** when the queue-wait SLO for the request's class is
  *provably* blown: the controller keeps an EWMA of observed per-request
  service time; ``predicted wait = queued-at-or-above-rank / slots ×
  EWMA``. Admission is rejected only on evidence — with no completed
  request yet (no EWMA), everything is admitted and the SLO is enforced
  ex post by the metrics. A hard queue-depth cap (``max_queue``) bounds
  memory regardless.

Accounting: a request occupies its class's queue count from admission
until the pump reports it terminal (``release``) — the simple conservative
model: everything admitted-but-unfinished is load ahead of you.

Lock discipline (graftlint GL401/403): handler threads admit, the pump
thread releases and feeds service times — every mutable field is
``# guarded-by: _lock``.
"""

import math
import threading
from typing import Dict, Optional, Tuple

from trlx_tpu.engine.core import SERVE_CLASSES, _CLASS_RANK

__all__ = ["AdmissionController", "AdmissionDecision"]


class AdmissionDecision:
    """Outcome of one :meth:`AdmissionController.try_admit`."""

    __slots__ = ("admitted", "status", "retry_after_s", "reason")

    def __init__(
        self,
        admitted: bool,
        status: int = 200,
        retry_after_s: float = 0.0,
        reason: str = "",
    ):
        self.admitted = admitted
        self.status = status  # HTTP status when rejected (429 / 503)
        self.retry_after_s = retry_after_s
        self.reason = reason


class AdmissionController:
    def __init__(
        self,
        slots: int,
        slo_s: Optional[Dict[str, float]] = None,
        max_queue: int = 64,
        ewma_alpha: float = 0.3,
    ):
        if slots < 1:
            raise ValueError(f"admission needs >= 1 engine slot, got {slots}")
        self.slots = int(slots)
        # per-class queue-wait SLO in seconds; absent class = no SLO gate
        self.slo_s = dict(slo_s or {})
        for k in self.slo_s:
            if k not in _CLASS_RANK:
                raise ValueError(
                    f"unknown priority class {k!r} in serve SLOs: expected "
                    f"one of {SERVE_CLASSES}"
                )
        self.max_queue = int(max_queue)
        self.ewma_alpha = float(ewma_alpha)
        self._lock = threading.Lock()
        self._queued: Dict[str, int] = {k: 0 for k in SERVE_CLASSES}  # guarded-by: _lock
        self._ewma_service_s: Optional[float] = None  # guarded-by: _lock
        self._draining = False  # guarded-by: _lock
        self.admitted = 0  # guarded-by: _lock
        self.rejected = 0  # guarded-by: _lock
        self.drain_rejected = 0  # guarded-by: _lock

    # -- handler-thread side ---------------------------------------------

    def try_admit(self, klass: str) -> AdmissionDecision:
        if klass not in _CLASS_RANK:
            return AdmissionDecision(
                False, 400, 0.0, f"unknown class {klass!r}"
            )
        rank = _CLASS_RANK[klass]
        with self._lock:
            if self._draining:
                self.drain_rejected += 1
                return AdmissionDecision(False, 503, 0.0, "draining")
            # load that will be served at-or-before this request: classes
            # of equal or better rank (worse-ranked queued work yields)
            ahead = sum(
                n
                for k, n in self._queued.items()
                if _CLASS_RANK[k] <= rank
            )
            total = sum(self._queued.values())
            if total >= self.max_queue:
                retry = self._predict_locked(ahead) or 1.0
                self.rejected += 1
                return AdmissionDecision(
                    False,
                    429,
                    math.ceil(retry),
                    f"queue full ({total}/{self.max_queue})",
                )
            slo = self.slo_s.get(klass)
            predicted = self._predict_locked(ahead)
            if slo is not None and predicted is not None and predicted > slo:
                self.rejected += 1
                return AdmissionDecision(
                    False,
                    429,
                    math.ceil(predicted - slo) or 1,
                    f"predicted queue wait {predicted:.2f}s exceeds the "
                    f"{klass} SLO of {slo:.2f}s",
                )
            self._queued[klass] += 1
            self.admitted += 1
            return AdmissionDecision(True)

    def _predict_locked(self, ahead: int) -> Optional[float]:
        """Predicted queue wait given ``ahead`` requests at-or-above rank;
        None without service-time evidence (reject needs proof)."""
        if self._ewma_service_s is None:
            return None
        return ahead / self.slots * self._ewma_service_s

    # -- pump-thread side ------------------------------------------------

    def release(self, klass: str) -> None:
        """A previously admitted request reached a terminal state."""
        with self._lock:
            if self._queued.get(klass, 0) > 0:
                self._queued[klass] -= 1

    def note_service(self, seconds: float) -> None:
        """Fold one completed request's submit→done wall time into the EWMA
        the admission predictions run on."""
        if seconds <= 0:
            return
        with self._lock:
            if self._ewma_service_s is None:
                self._ewma_service_s = seconds
            else:
                a = self.ewma_alpha
                self._ewma_service_s = a * seconds + (1 - a) * self._ewma_service_s

    # -- lifecycle -------------------------------------------------------

    def set_draining(self) -> None:
        with self._lock:
            self._draining = True

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = {
                "admitted": float(self.admitted),
                "rejected": float(self.rejected),
                "drain_rejected": float(self.drain_rejected),
                "queued": float(sum(self._queued.values())),
                "ewma_service_s": float(self._ewma_service_s or 0.0),
            }
            for k, n in self._queued.items():
                out[f"queued_{k}"] = float(n)
            return out
