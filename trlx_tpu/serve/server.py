"""ServeServer: the pump thread that owns the serving engine, plus the
request/param plumbing between it, the HTTP frontend, and the trainer.

Threading model (docs/SERVING.md; the engine's single-threaded contract):

- the **pump thread** (``trlx-serve-pump``) EXCLUSIVELY drives the serving
  :class:`~trlx_tpu.engine.core.ContinuousEngine` — every ``step()``,
  ``enqueue_prompts``, ``swap_params``, allocator/prefix/host-tier touch
  happens here and only here;
- **HTTP handler threads** (``frontend.py``) talk to the pump through
  ``queue.Queue`` handoffs and per-request :class:`ServeRequest` condition
  variables — they never touch the engine;
- the **trainer thread** publishes fresh params through a latest-wins
  queue (``publish``), runs admission drills (``flood_drill``), and owns
  start/drain/close.

Single-version responses: published params are adopted only when the
engine has NO live serve work, so every response is generated end-to-end
under one params version (stamped on the request as ``params_version``).
The serve-while-training e2e pins a mid-training streamed response
bit-identical to a solo ``generate`` under that version's retained params.

Graceful drain (``serve.drain_timeout_s``): new admissions 503
immediately, in-flight requests get a bounded window to finish, then the
pump exits — failing whatever remains so no handler thread is left blocked
— and the HTTP listener (``trlx-serve-http``) shuts down. Both threads are
joined; the leaked-thread sentinel (tests/conftest.py) holds us to that.

Lock discipline (graftlint GL401/403, docs/STATIC_ANALYSIS.md): the
RolloutPipeline idiom — ``queue.Queue``/``Event`` for handoffs, one lock
for the few genuinely shared fields, all ``# guarded-by:``-annotated;
pump-local state (slot bookkeeping, streamed counts) lives in loop locals.
"""

import queue
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from trlx_tpu.serve.metrics import ServeMetrics
from trlx_tpu.serve.request import ServeRequest
from trlx_tpu.serve.scheduler import AdmissionController

__all__ = ["ServeServer"]


class ServeServer:
    """Serving frontend over one exclusively-owned ContinuousEngine."""

    def __init__(
        self,
        engine: Any,  # ContinuousEngine (paged backend), pump-owned
        default_tenant: str = "default",
        default_class: str = "interactive",
        slo_s: Optional[Dict[str, float]] = None,
        max_queue: int = 64,
        stream_buffer: int = 64,
        drain_timeout_s: float = 5.0,
        retain_param_versions: int = 0,
        default_max_new_tokens: int = 0,
    ):
        if getattr(engine, "spec", None) is None:
            raise ValueError(
                "serving requires the paged engine backend "
                "(engine.backend: paged) — streaming snapshots and "
                "preemption are block-table operations"
            )
        self.engine = engine
        self.default_tenant = default_tenant
        self.default_class = default_class
        self.stream_buffer = int(stream_buffer)
        self.drain_timeout_s = float(drain_timeout_s)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.admission = AdmissionController(
            slots=engine.B, slo_s=slo_s, max_queue=max_queue
        )
        self.metrics = ServeMetrics()
        self._ingress: "queue.Queue[ServeRequest]" = queue.Queue()
        self._params_q: "queue.Queue[Tuple[Any, Optional[int]]]" = queue.Queue()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._lock = threading.Lock()
        # published-params history for the bit-equality e2e: version →
        # params, newest retain_param_versions kept (0 = keep none)
        self._retain = int(retain_param_versions)
        self._history: "OrderedDict[int, Any]" = OrderedDict()  # guarded-by: _lock
        self._pump: Optional[threading.Thread] = None
        self._httpd: Any = None
        self._http_thread: Optional[threading.Thread] = None
        self._rid_iter = iter(range(1, 1 << 62))
        self._started = False
        self._closed = False

    # -- lifecycle (trainer thread) --------------------------------------

    def start(self, host: Optional[str] = None, port: int = 0) -> None:
        """Start the pump (and, when ``host`` is given, the HTTP listener)."""
        if self._started:
            return
        self._started = True
        self._pump = threading.Thread(
            target=self._pump_loop, name="trlx-serve-pump", daemon=True
        )
        self._pump.start()
        if host is not None:
            from trlx_tpu.serve.frontend import make_http_server

            self._httpd = make_http_server(self, host, port)
            self._http_thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="trlx-serve-http",
                daemon=True,
            )
            self._http_thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd is not None else 0

    def publish(self, params: Any, version: Optional[int] = None) -> None:
        """Latest-wins params handoff; the pump adopts at the next point
        with no live serve work (single-version responses). Also retains
        the newest ``retain_param_versions`` published trees for
        :meth:`params_for_version` (the e2e parity probe)."""
        self._params_q.put((params, version))
        if self._retain > 0 and version is not None:
            with self._lock:
                self._history[int(version)] = params
                self._history.move_to_end(int(version))
                while len(self._history) > self._retain:
                    self._history.popitem(last=False)
        self._wake.set()

    def params_for_version(self, version: int) -> Any:
        with self._lock:
            return self._history.get(int(version))

    def flood_drill(self, n: int = 0) -> int:
        """Admission-control drill (``request_flood@step:N``,
        docs/RESILIENCE.md): push a synthetic admission burst through the
        real gate — accepted probes are released immediately (no engine
        work), rejections prove the 429 path sheds load. Returns the
        rejection count."""
        n = int(n) or 2 * self.admission.max_queue
        accepted: List[str] = []
        rejected = 0
        for _ in range(n):
            d = self.admission.try_admit(self.default_class)
            if d.admitted:
                accepted.append(self.default_class)
            else:
                rejected += 1
        for k in accepted:
            self.admission.release(k)
        self.metrics.note_flood_rejected(rejected)
        return rejected

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Graceful shutdown: stop admitting (503), give in-flight requests
        ``timeout_s`` (default ``drain_timeout_s``) to finish, then stop
        the pump (which fails any survivors) and the HTTP listener.
        Returns True when everything in flight finished in time."""
        timeout_s = self.drain_timeout_s if timeout_s is None else timeout_s
        self.admission.set_draining()
        deadline = time.monotonic() + max(0.0, timeout_s)
        clean = True
        while time.monotonic() < deadline:
            if self.metrics.metrics()["serve/active"] <= 0:
                break
            time.sleep(0.02)
        else:
            clean = self.metrics.metrics()["serve/active"] <= 0
        self.close()
        return clean

    def close(self) -> None:
        """Stop and join both serve threads. Idempotent; never raises."""
        if self._closed:
            return
        self._closed = True
        self.admission.set_draining()
        self._stop.set()
        self._wake.set()
        if self._pump is not None:
            self._pump.join(timeout=30)
        if self._httpd is not None:
            try:
                self._httpd.shutdown()
                self._httpd.server_close()
            except Exception:  # pragma: no cover - defensive teardown
                pass
        if self._http_thread is not None:
            self._http_thread.join(timeout=30)

    # -- submission (handler threads / tests) ----------------------------

    def submit(
        self,
        prompt_ids: np.ndarray,
        prompt_mask: Optional[np.ndarray] = None,
        tenant: Optional[str] = None,
        klass: Optional[str] = None,
        seed: int = 0,
        stream: bool = False,
        max_new_tokens: int = 0,
    ) -> Tuple[Optional[ServeRequest], Optional[Tuple[int, str, float]]]:
        """Admission-checked request entry. Returns ``(request, None)`` on
        acceptance or ``(None, (status, reason, retry_after_s))`` on
        rejection — the frontend maps the triple straight onto
        429/503/400."""
        tenant = tenant or self.default_tenant
        klass = klass or self.default_class
        prompt_ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt_mask is None:
            prompt_mask = np.ones_like(prompt_ids)
        prompt_mask = np.asarray(prompt_mask, np.int32).reshape(-1)
        if prompt_ids.size == 0 or prompt_ids.shape != prompt_mask.shape:
            return None, (400, "empty prompt or mask/ids shape mismatch", 0.0)
        if prompt_ids.shape[0] > self.engine.P:
            return None, (
                400,
                f"prompt length {prompt_ids.shape[0]} exceeds the engine's "
                f"padded width {self.engine.P}",
                0.0,
            )
        decision = self.admission.try_admit(klass)
        if not decision.admitted:
            return None, (
                decision.status,
                decision.reason,
                decision.retry_after_s,
            )
        req = ServeRequest(
            rid=next(self._rid_iter),
            prompt_ids=prompt_ids,
            prompt_mask=prompt_mask,
            tenant=tenant,
            klass=klass,
            seed=seed,
            stream=stream,
            max_new_tokens=int(max_new_tokens) or self.default_max_new_tokens,
            max_buffered=self.stream_buffer,
        )
        self.metrics.adjust_active(+1)
        self._ingress.put(req)
        self._wake.set()
        return req, None

    # -- pump thread -----------------------------------------------------

    def _request_keys(self, req: ServeRequest) -> np.ndarray:
        """Per-row RNG chain start for a B=1 solo reference: the exact
        chain ``per_row_keys(PRNGKey(seed), 1)`` a plain ``generate`` call
        with the same seed derives — the streaming-parity anchor."""
        import jax

        from trlx_tpu.ops.sampling import per_row_keys

        return np.asarray(per_row_keys(jax.random.PRNGKey(req.seed), 1))

    def _pump_loop(self) -> None:
        engine = self.engine
        # pump-local bookkeeping (single-threaded by construction):
        # engine submission index → (request, tokens streamed so far)
        tracked: Dict[int, List[Any]] = {}
        pending_pub: Optional[Tuple[Any, Optional[int]]] = None
        version: Optional[int] = None
        try:
            while not self._stop.is_set():
                # latest-wins params adoption, only with no serve work in
                # flight — every response is single-version
                while True:
                    try:
                        pending_pub = self._params_q.get_nowait()
                    except queue.Empty:
                        break
                if pending_pub is not None and not engine.busy and not tracked:
                    params, version = pending_pub
                    engine.swap_params(params, version)
                    self.metrics.set_params_version(version)
                    pending_pub = None
                # ingress → engine
                moved = False
                while True:
                    try:
                        req = self._ingress.get_nowait()
                    except queue.Empty:
                        break
                    engine.enqueue_prompts(
                        req.prompt_ids[None],
                        req.prompt_mask[None],
                        self._request_keys(req),
                        metas=[req],
                        tenant=req.tenant,
                        klass=req.klass,
                    )
                    idx = engine._submitted - 1
                    tracked[idx] = [req, 0]
                    req.mark_generating(version)
                    moved = True
                if not engine.busy:
                    if not moved:
                        self._wake.wait(0.02)
                        self._wake.clear()
                    continue
                completed = engine.step()
                # stream deltas for still-live rows (streamed == -1 marks
                # a dropped consumer: decode continues, streaming stops)
                for idx, meta, toks in engine.progress_snapshot():
                    entry = tracked.get(idx)
                    if entry is None or entry[1] < 0 or not entry[0].stream:
                        continue
                    req, streamed = entry
                    if toks.shape[0] > streamed:
                        if req.push_tokens(toks[streamed:]):
                            entry[1] = int(toks.shape[0])
                        else:
                            # slow client: stop streaming, keep decoding
                            entry[1] = -1
                            self._terminal(req, "dropped")
                for c in completed:
                    entry = tracked.pop(c.index, None)
                    if entry is None:
                        continue
                    self._finish(entry[0], entry[1], c)
                while engine.failed:
                    req_obj, err = engine.failed.popleft()
                    sr = req_obj.meta
                    tracked.pop(req_obj.index, None)
                    if isinstance(sr, ServeRequest):
                        sr.fail(err)
                        self._terminal(sr, "failed")
                self._publish_gauges()
        finally:
            # pump exit (drain timeout / close): nothing will ever finish
            # the remaining requests — fail them so no handler blocks
            for req, _streamed in tracked.values():
                req.fail("server draining: request abandoned")
                self._terminal(req, "failed")
            while True:
                try:
                    req = self._ingress.get_nowait()
                except queue.Empty:
                    break
                req.fail("server draining: request abandoned")
                self._terminal(req, "failed")
            self._publish_gauges()

    def _finish(self, req: ServeRequest, streamed: int, c: Any) -> None:
        masked = np.asarray(c.tokens)[np.asarray(c.mask) == 1]
        if req.stream and streamed >= 0 and masked.shape[0] > streamed:
            req.push_tokens(masked[streamed:])
        queue_wait = max(0.0, c.t_prefill0 - c.t_enqueue)
        req.finish(masked, queue_wait, t_first_token=c.t_harvest)
        snap = req.snapshot()
        if snap["state"] == "DONE":
            req._accounted = True
            ttft = snap["ttft_s"]
            n = snap["n_tokens"]
            tpot = (
                max(0.0, req.t_done - req.t_first_token) / max(1, n - 1)
                if n > 1
                else 0.0
            )
            self.metrics.observe_request(
                req.tenant, req.klass, ttft, tpot, queue_wait, n
            )
            self.admission.release(req.klass)
            self.admission.note_service(
                max(0.0, req.t_done - req.t_submit)
            )
            self.metrics.adjust_active(-1)
        else:
            # the consumer dropped mid-flight; terminal accounting already
            # ran (or runs) through _terminal
            self._terminal(req, "dropped")

    def _terminal(self, req: ServeRequest, how: str) -> None:
        """Terminal accounting, exactly once per request (``_accounted``
        is pump-thread-only, like every call site here)."""
        if req._accounted:
            return
        req._accounted = True
        if how == "failed":
            self.metrics.note_failed()
        else:
            self.metrics.note_dropped()
        self.admission.release(req.klass)
        self.metrics.adjust_active(-1)

    def _publish_gauges(self) -> None:
        self.metrics.set_admission(self.admission.snapshot())
        if self.engine.host_tier is not None:
            self.metrics.set_tier(self.engine.host_tier.snapshot())

    # -- observation (any thread) ----------------------------------------

    def flat_metrics(self) -> Dict[str, float]:
        """The ``SERVE_KEYS`` gauges (merged into trainer step stats)."""
        return self.metrics.metrics()

    def detail_metrics(self) -> Dict[str, Any]:
        return {
            "serve": self.metrics.metrics(),
            "tenants": self.metrics.detail(),
            "admission": self.admission.snapshot(),
        }
