"""Host-call hardening: retry / timeout / exponential backoff with jitter
around the host-side calls that can flake without the device being at fault —
``reward_fn`` (often a remote scoring endpoint, cf. ``examples/hh/
serve_reward.py``) and tracker/hub publishes.

Device code is deterministic and compiled; the host boundary is where real
runs die. A transient reward-endpoint 500 previously killed the entire run
(and with it every collected rollout since the last checkpoint). Now:

- each failing attempt is retried up to ``retries`` times with
  ``base * 2**attempt`` backoff, capped at ``max_backoff``, multiplied by a
  deterministic jitter in [0.5, 1.0) (seeded per guard — reproducible under
  the fault harness, still decorrelated across guards);
- an optional per-attempt ``timeout`` runs the call on a worker thread; a
  hung endpoint counts as a failed attempt (the stuck worker is abandoned —
  daemon thread — and a fresh one takes over);
- when every attempt fails, the ``fallback`` policy decides: ``"raise"``
  re-raises the last error (the old behavior), ``"neutral"`` returns a
  caller-supplied neutral value (for rewards: zeros, keeping the batch but
  contributing no signal) and the run continues;
- every retry/failure/fallback increments ``resilience/*`` counters in the
  trainer's metrics registry, so flaky endpoints are *visible* in the stats
  stream, not silently absorbed.

Fault-plan integration: when an active plan has ``reward_raise`` /
``publish_raise`` entries, the guard polls it before each attempt — every
attempt advances the plan's call counter, so ``reward_raise@call:3*2``
deterministically fails attempts 3 and 4 and succeeds on 5.
"""

import random
import threading
import time
from typing import Any, Callable, Dict, Optional

from trlx_tpu.resilience.faults import FaultPlan, InjectedFault
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


class HostCallGuard:
    """Wrap a host-side callable in retry/timeout/backoff + metric accounting.

    ``name`` keys the metric counters (``resilience/<name>_retries``,
    ``_failures``, ``_fallbacks``) and the fault-plan kind
    (``<name>_raise``). ``neutral_fn(*args, **kwargs)`` supplies the
    fallback value under the ``"neutral"`` policy.
    """

    def __init__(
        self,
        fn: Callable,
        name: str,
        retries: int = 3,
        backoff_s: float = 0.5,
        backoff_max_s: float = 30.0,
        timeout_s: Optional[float] = None,
        fallback: str = "raise",
        neutral_fn: Optional[Callable] = None,
        max_consecutive_fallbacks: int = 0,
        metrics: Any = None,
        plan: Optional[FaultPlan] = None,
        seed: int = 0,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if fallback not in ("raise", "neutral"):
            raise ValueError(
                f"unknown fallback policy {fallback!r} (use 'raise' or 'neutral')"
            )
        if fallback == "neutral" and neutral_fn is None:
            raise ValueError("fallback='neutral' needs a neutral_fn")
        self.fn = fn
        self.name = name
        self.retries = max(0, int(retries))
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.timeout_s = timeout_s
        self.fallback = fallback
        self.neutral_fn = neutral_fn
        # escalation valve for the "neutral" policy: a DETERMINISTIC bug
        # (vs a transient outage) fails every call — without a cap the run
        # silently degrades into neutral-value training to total_steps.
        # After this many consecutive fallbacks the guard re-raises.
        # 0 disables the cap.
        self.max_consecutive_fallbacks = int(max_consecutive_fallbacks)
        self.consecutive_fallbacks = 0
        self.metrics = metrics
        self.plan = plan
        self._rng = random.Random(seed)
        self._sleep = sleep
        # propagate the wrapped fn's face: reward_fn identity matters to
        # callers that introspect (e.g. examples logging the fn name)
        self.__wrapped__ = fn

    # -- internals ------------------------------------------------------

    def _inc(self, key: str, value: float = 1.0) -> None:
        if self.metrics is not None:
            self.metrics.inc(key, value)

    def backoff_delay(self, attempt: int) -> float:
        """Deterministic-jitter exponential backoff for the given attempt
        (0-based): ``min(max, base * 2**attempt) * U[0.5, 1.0)``."""
        base = min(self.backoff_max_s, self.backoff_s * (2.0 ** attempt))
        return base * (0.5 + 0.5 * self._rng.random())

    def _call_with_timeout(self, *args, **kwargs):
        if self.timeout_s is None:
            return self.fn(*args, **kwargs)
        # One fresh DAEMON thread per timed attempt, not a ThreadPoolExecutor:
        # modern CPython's executor threads are non-daemon and joined at
        # interpreter exit, so an abandoned worker stuck inside a dead
        # endpoint would hang process shutdown — the exact failure mode this
        # guard exists to survive. The guarded calls are host RPCs (ms+), so
        # per-call thread spawn cost is noise. A timed-out worker is
        # deliberately abandoned (Python can't kill a thread); being daemon,
        # it dies with the process, and the leaked-thread sentinel in
        # tests/conftest.py allowlists the `-guard` suffix for exactly this.
        result: Dict[str, Any] = {}
        done = threading.Event()

        def _run():
            try:
                result["value"] = self.fn(*args, **kwargs)
            except BaseException as e:
                result["error"] = e
            finally:
                done.set()

        worker = threading.Thread(
            target=_run, name=f"trlx-tpu-{self.name}-guard", daemon=True
        )
        worker.start()
        if not done.wait(self.timeout_s):
            raise TimeoutError(
                f"{self.name} call exceeded timeout {self.timeout_s}s"
            ) from None
        if "error" in result:
            raise result["error"]
        return result["value"]

    # -- the call -------------------------------------------------------

    def __call__(self, *args, **kwargs):
        last_err: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            try:
                if self.plan is not None and self.plan.poll(f"{self.name}_raise"):
                    raise InjectedFault(
                        f"fault plan: injected {self.name} failure "
                        f"(attempt {attempt + 1})"
                    )
                result = self._call_with_timeout(*args, **kwargs)
                self.consecutive_fallbacks = 0
                return result
            except Exception as e:
                last_err = e
                if attempt < self.retries:
                    delay = self.backoff_delay(attempt)
                    self._inc(f"resilience/{self.name}_retries")
                    logger.warning(
                        f"{self.name} failed (attempt {attempt + 1}/"
                        f"{self.retries + 1}): {e}; retrying in {delay:.2f}s"
                    )
                    self._sleep(delay)
        self._inc(f"resilience/{self.name}_failures")
        if self.fallback == "neutral":
            self.consecutive_fallbacks += 1
            if (
                self.max_consecutive_fallbacks
                and self.consecutive_fallbacks >= self.max_consecutive_fallbacks
            ):
                logger.error(
                    f"{self.name} fell back {self.consecutive_fallbacks} "
                    "calls in a row — this is a deterministic failure, not "
                    "a transient outage; re-raising"
                )
                raise last_err
            self._inc(f"resilience/{self.name}_fallbacks")
            logger.error(
                f"{self.name} failed after {self.retries + 1} attempts "
                f"({last_err}); substituting the neutral fallback"
            )
            return self.neutral_fn(*args, **kwargs)
        raise last_err


def neutral_rewards(*args, **kwargs):
    """Zero reward per sample — the neutral fallback for ``reward_fn``:
    the batch stays (shapes hold) but contributes no learning signal."""
    samples = kwargs.get("samples")
    if samples is None and args:
        samples = args[0]
    return [0.0] * len(samples or [])


class ResilientTracker:
    """Tracker decorator: publishes retry with backoff and NEVER kill the
    run — metrics logging is not worth a training job.

    Wraps any ``Tracker`` (JSONL/TensorBoard/W&B). ``log`` and ``finish``
    retry like :class:`HostCallGuard`; after exhaustion the record is
    dropped with an error log and ``resilience/publish_failures``
    increments — dropped stats are visible in the *surviving* stream.
    Attribute access proxies to the inner tracker so integrations keep
    working (e.g. ``tracker.path`` for JSONL).
    """

    def __init__(
        self,
        tracker: Any,
        retries: int = 2,
        backoff_s: float = 0.2,
        backoff_max_s: float = 5.0,
        metrics: Any = None,
        plan: Optional[FaultPlan] = None,
        seed: int = 0,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self._inner = tracker
        self._guard = HostCallGuard(
            self._publish,
            name="publish",
            retries=retries,
            backoff_s=backoff_s,
            backoff_max_s=backoff_max_s,
            fallback="neutral",
            neutral_fn=lambda *a, **k: None,  # drop the record
            metrics=metrics,
            plan=plan,
            seed=seed,
            sleep=sleep,
        )
        self._lock = threading.Lock()

    def _publish(self, method: str, *args, **kwargs):
        return getattr(self._inner, method)(*args, **kwargs)

    def log(self, stats: Dict[str, Any], step: int) -> None:
        with self._lock:  # pipeline workers and the main loop both log
            self._guard("log", stats, step=step)

    def finish(self) -> None:
        with self._lock:
            self._guard("finish")

    def __enter__(self) -> "ResilientTracker":
        return self

    def __exit__(self, *exc_info) -> None:
        self.finish()

    def __getattr__(self, name: str):
        return getattr(self._inner, name)
