"""Elastic topology-change resilience: reshard-on-restore + coordinated
multihost preemption.

The preemption machinery (``resilience/preemption.py``) proves "survive
preemption bit-identically" — but only onto the *same* mesh. Real fleets
hand back whatever slice the scheduler has: Podracer-style learner/actor
pairs (arXiv 2104.06272) and RLAX's preemption-tolerant disaggregated TPU
design (arXiv 2512.06392) both assume an n=16 checkpoint resumes onto an
n=8 (or n=32) replacement. Two pieces close that gap:

**Topology manifest** — every committed checkpoint now carries
``topology.json`` (written by :func:`build_manifest` at save, staged and
committed atomically with the state tree): mesh axis names + shape,
process/device counts, and a per-leaf record of ``PartitionSpec``, dtype,
and global shape. Restore compares it against the live mesh
(:func:`manifest_mismatch`) *before* touching Orbax, so a topology change
is a detected condition, not a sharding crash.

**Reshard-on-restore** — :func:`restore_state_elastic` is the one restore
entry the trainers use. Matching topology takes the existing fast path
(sharded Orbax restore straight onto the mesh). A mismatch (or an injected
``topology_shrink@resume:N`` fault) takes the elastic path: every leaf is
restored *host-side* (numpy — Orbax reads the global array regardless of
who wrote which shard), then re-materialized under the **live** mesh's
sharding via ``jax.make_array_from_callback`` (each process feeds exactly
its addressable shards, so the same code reshards 2-process→1-process and
1→2). Values are byte-preserved and dtypes follow the restoring template,
so the post-resume trajectory is bit-identical to an uninterrupted run on
the destination topology (``tests/test_resilience.py::TestElasticRestore``,
``tests/test_multihost.py``). Cost: the elastic path stages the full tree
in host RAM (one process-local copy) instead of streaming shards to
devices — the price of crossing topologies; ``resilience/reshard_s``
gauges it.

**Coordinated preemption** — a SIGTERM lands on *one* host; the others
keep stepping. :func:`coordinate_preemption` allgathers the local
preemption flag at every step boundary (``multihost_utils``), so all
processes agree on the same emergency-checkpoint step; the commit marker
is then written by process 0 only (``utils/checkpoint.py``). Injectable
end-to-end via the ``sigterm_one_proc@step:N`` fault.

Knobs: ``resilience.elastic`` / ``resilience.coordinate_preemption``
(docs/RESILIENCE.md "Elastic restore").
"""

import json
import os
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

MANIFEST_NAME = "topology.json"
MANIFEST_FORMAT = 1


class ElasticRestoreError(RuntimeError):
    """A checkpoint cannot be restored onto the live mesh — with the reason
    spelled out (topology mismatch with elastic off, shape drift, or a
    manifest-less checkpoint meeting a changed topology)."""


def _spec_of(leaf: Any):
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    return spec


def _leaf_paths_and_values(tree: Any):
    from trlx_tpu.parallel.sharding import path_keys

    for key_path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        yield "/".join(path_keys(key_path)), leaf


def live_mesh_of(template: Any):
    """The mesh the template state lives on (first NamedSharding leaf), or
    None for host/abstract templates."""
    for _path, leaf in _leaf_paths_and_values(template):
        sharding = getattr(leaf, "sharding", None)
        mesh = getattr(sharding, "mesh", None)
        if mesh is not None:
            return mesh
    return None


def build_manifest(state: Any) -> Optional[Dict[str, Any]]:
    """The topology manifest for a live train state: mesh descriptor plus a
    per-leaf ``{spec, dtype, shape}`` record. None when the state carries no
    mesh (abstract/host trees) — such saves stay manifest-less (legacy
    layout) rather than recording a topology they don't have."""
    from trlx_tpu.parallel.mesh import mesh_descriptor
    from trlx_tpu.parallel.sharding import spec_to_jsonable

    mesh = live_mesh_of(state)
    if mesh is None:
        return None
    leaves: Dict[str, Dict[str, Any]] = {}
    for path, leaf in _leaf_paths_and_values(state):
        if not isinstance(leaf, jax.Array):
            continue
        spec = _spec_of(leaf)
        leaves[path] = {
            "spec": spec_to_jsonable(spec) if spec is not None else None,
            "dtype": str(np.dtype(leaf.dtype)) if hasattr(leaf, "dtype") else None,
            "shape": [int(d) for d in leaf.shape],
        }
    return {
        "format": MANIFEST_FORMAT,
        "mesh": mesh_descriptor(mesh),
        "leaves": leaves,
    }


def read_manifest(directory: str) -> Optional[Dict[str, Any]]:
    """The committed topology manifest of ``directory``, or None for
    checkpoints written before the manifest protocol."""
    path = os.path.join(os.path.abspath(directory), MANIFEST_NAME)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def manifest_mismatch(manifest: Dict[str, Any], mesh) -> Optional[str]:
    """None when the manifest's topology matches the live ``mesh``; else a
    human-readable description of what changed (the elastic path's trigger
    and the strict path's diagnostic)."""
    from trlx_tpu.parallel.mesh import mesh_descriptor

    saved = manifest.get("mesh") or {}
    live = mesh_descriptor(mesh)
    diffs = []
    for field in ("axes", "shape", "device_count", "process_count"):
        if saved.get(field) != live.get(field):
            diffs.append(f"{field}: saved {saved.get(field)} != live {live.get(field)}")
    return "; ".join(diffs) if diffs else None


def _validate_leaves(manifest: Dict[str, Any], template: Any, directory: str) -> None:
    """Global shapes must agree between the manifest and the restoring
    template — resharding changes placement, never values or geometry."""
    saved = manifest.get("leaves") or {}
    for path, leaf in _leaf_paths_and_values(template):
        rec = saved.get(path)
        if rec is None or not isinstance(leaf, jax.Array):
            continue
        shape = tuple(rec.get("shape") or ())
        if shape and shape != tuple(leaf.shape):
            raise ElasticRestoreError(
                f"checkpoint {directory} leaf {path!r} has global shape "
                f"{shape}, but the live state expects {tuple(leaf.shape)} — "
                "a topology change reshards placement only; a model/config "
                "change needs a fresh run (docs/RESILIENCE.md)"
            )


def _is_sharding_error(e: BaseException) -> bool:
    """Whether a restore failure is placement-shaped (mesh/sharding drift)
    rather than IO/corruption/resources. Gates the manifest-less topology
    diagnostic: wrapping a disk-full or truncated-shard error in "the
    topology changed" sends the operator down the wrong debugging path."""
    if isinstance(e, (OSError, MemoryError)):
        return False
    text = f"{type(e).__name__}: {e}".lower()
    # placement-specific phrases only: bare "shard" would match Orbax's
    # corrupt-data "failed to read shard N of array", bare "device" would
    # match XLA's "out of memory ... on device" — both are NOT topology
    # problems and must keep their real traceback
    return any(
        tok in text
        for tok in ("sharding", "mesh", "addressable", "partition",
                    "device assignment", "device count", "process count")
    )


def _reshard_restore(directory: str, template: Any) -> Any:
    """The elastic path: restore every leaf host-side (numpy), then
    re-materialize under the template's (live-mesh) sharding. Leaf dtypes
    follow the template — bf16 states come back bf16."""
    import orbax.checkpoint as ocp

    from trlx_tpu.utils.checkpoint import _recover_interrupted_swap

    # a commit that crashed between its two renames leaves the intact tree
    # at state.old (the COMMITTED marker still vouches for it); the fast
    # path heals this inside restore_state — the elastic path must too, or
    # a topology-changing resume after a crash-mid-save dies on a missing
    # state/ dir despite a fully restorable checkpoint
    _recover_interrupted_swap(directory)
    tree_dir = os.path.join(os.path.abspath(directory), "state")

    def as_host_restore(x):
        if isinstance(x, jax.Array):
            return ocp.type_handlers.RestoreArgs(restore_type=np.ndarray)
        return ocp.type_handlers.RestoreArgs()

    restore_args = jax.tree_util.tree_map(as_host_restore, template)
    with ocp.PyTreeCheckpointer() as ckptr:
        host = ckptr.restore(tree_dir, item=template, restore_args=restore_args)

    from trlx_tpu.parallel.sharding import put_global

    def reland(x, t):
        if not isinstance(t, jax.Array):
            return x
        arr = np.asarray(x)
        if arr.dtype != t.dtype:
            arr = arr.astype(t.dtype)
        # put_global places the host array under the live sharding on
        # single- AND multi-process meshes (each process supplies exactly
        # the shards its devices own, so shrink 2-proc→1-proc and grow
        # 1→2 are the same code path). reland=True forces the copy
        # protocol on the single-process branch too: these leaves are
        # donated into the cached train step, and a zero-copy device_put
        # of the host buffer there corrupts the heap. Landing leaf by
        # leaf (not tree-at-once) is deliberate: peak memory stays one
        # staged leaf above the state size.
        return put_global(arr, t.sharding, reland=True)

    return jax.tree_util.tree_map(reland, host, template)


def restore_state_elastic(
    directory: str,
    template: Any,
    elastic: bool = True,
    metrics: Any = None,
) -> Any:
    """Restore a checkpoint onto whatever mesh ``template`` lives on.

    Decision table (docs/RESILIENCE.md "Elastic restore"):

    - manifest matches the live mesh → the existing sharded Orbax fast path
      (``utils/checkpoint.py::restore_state``), byte-for-byte as before;
    - manifest differs and ``elastic`` → host-side reshard
      (:func:`_reshard_restore`), timed into ``resilience/reshard_s``;
    - manifest differs and not ``elastic`` → :class:`ElasticRestoreError`
      naming exactly what changed;
    - no manifest (pre-manifest checkpoint) → the fast path, with any
      sharding failure re-raised as a clear "topology may have changed"
      diagnostic instead of a raw Orbax crash.

    A ``topology_shrink@resume:N`` fault forces the reshard path even on a
    matching mesh, so the whole elastic machinery is deterministically
    testable without ever re-launching at a different device count.
    """
    from trlx_tpu.resilience.faults import poll_fault
    from trlx_tpu.utils.checkpoint import restore_state, wait_for_saves

    wait_for_saves()  # the manifest may still be pending its commit
    manifest = read_manifest(directory)
    mesh = live_mesh_of(template)
    forced = poll_fault("topology_shrink")
    if forced:
        logger.warning(
            f"fault plan: topology_shrink — forcing the elastic reshard "
            f"path for restore from {directory}"
        )

    if manifest is None:
        if mesh is not None and forced:
            return _timed_reshard(directory, template, "forced (manifest-less)", metrics)
        try:
            return restore_state(directory, template)
        except ElasticRestoreError:
            raise
        except Exception as e:
            # only placement-shaped failures earn the topology diagnostic;
            # a corrupt shard, missing dir, or OOM keeps its real identity
            # (sending the operator topology-debugging for a data-corruption
            # problem is worse than a raw traceback)
            if not _is_sharding_error(e):
                raise
            raise ElasticRestoreError(
                f"restore from {directory} failed and the checkpoint carries "
                f"no topology manifest (written before elastic resilience): "
                f"if the device/process topology changed since the save, "
                f"this checkpoint cannot be auto-resharded — re-save it on "
                f"its original topology to stamp a manifest, or restore on "
                f"a matching mesh (docs/RESILIENCE.md). Underlying error: {e}"
            ) from e

    if mesh is None:  # host/abstract template: placement is not ours to pick
        return restore_state(directory, template)

    _validate_leaves(manifest, template, directory)
    mismatch = manifest_mismatch(manifest, mesh)
    if mismatch is None and not forced:
        return restore_state(directory, template)
    if not elastic:
        if mismatch is None:
            # fault-forced reshard on a matching mesh: name the injected
            # fault, not a topology change that never happened
            raise ElasticRestoreError(
                f"fault plan injected topology_shrink for restore from "
                f"{directory}, but resilience.elastic is off and the live "
                f"mesh matches the manifest — drop the fault or enable "
                f"resilience.elastic (docs/RESILIENCE.md)"
            )
        raise ElasticRestoreError(
            f"checkpoint {directory} was saved on a different topology "
            f"({mismatch}) and resilience.elastic is off — enable it to "
            f"reshard on restore, or relaunch on the original topology "
            "(docs/RESILIENCE.md)"
        )
    return _timed_reshard(directory, template, mismatch or "forced", metrics)


def _timed_reshard(directory: str, template: Any, reason: str, metrics: Any) -> Any:
    t0 = time.monotonic()
    state = _reshard_restore(directory, template)
    dt = time.monotonic() - t0
    logger.warning(
        f"elastic restore: resharded {directory} onto the live mesh in "
        f"{dt:.2f}s ({reason})"
    )
    if metrics is not None:
        metrics.set_gauge("resilience/reshard_s", float(dt))
        metrics.inc("resilience/elastic_restores")
    return state


# ---------------------------------------------------------------------------
# coordinated multihost preemption
# ---------------------------------------------------------------------------


def coordinate_preemption(requested: bool) -> bool:
    """Allgather the local preemption flag across processes; True when ANY
    process was signaled. Called at every step boundary (SPMD lockstep —
    every process reaches the same boundary before any starts the next
    update), so all processes choose the same emergency-checkpoint step.
    Single-process: returns the flag untouched, no collective.

    Cost: one scalar allgather per update in multihost jobs — gate with
    ``resilience.coordinate_preemption`` if that ever shows up in profiles
    (an uncoordinated multihost SIGTERM leaves no consistent restorable
    state, so the default is on). That gate field is registered
    rank-uniform (``RANK_UNIFORM_FIELDS``, graftlint GL704): every rank
    must be launched with the same value, or the ranks that post this
    allgather hang on the ones that don't (docs/STATIC_ANALYSIS.md "The
    rank-uniformity contract").
    """
    if jax.process_count() == 1:
        return bool(requested)
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(np.asarray(int(bool(requested)), np.int32))
    return bool(np.asarray(flags).any())
