"""Deterministic fault injection: a parsed :class:`FaultPlan` that trainer,
checkpoint, and host-call sites consult at well-defined points.

Production RL runs die in ways unit tests never exercise: a reward endpoint
times out on call 3, the scheduler SIGTERMs the pod at step 5, one batch
produces a NaN loss at step 7, the process is OOM-killed halfway through a
checkpoint write. The fault plan makes each of those a *reproducible* event:
the same plan string always fires the same faults at the same points, so the
recovery machinery (``trlx_tpu/resilience/``) is testable end-to-end on CPU.

Plan syntax (``;``-separated entries, whitespace ignored)::

    kind@trigger:N[*count]

    kind     one of: reward_raise | publish_raise | sigterm | sigint |
             sigterm_one_proc | nan_loss | crash_save | topology_shrink |
             sleep_one_proc | flightrec_dump | actor_crash |
             weight_sync_drop | health_trip | slow_client | request_flood
    trigger  call  — the Nth invocation of the consulting site (1-based;
                     for reward_raise/publish_raise every *attempt* counts,
                     so retries advance the counter)
             step  — fires when the trainer's completed-update count == N
             save  — the Nth ``save_state`` call (1-based)
             resume — the Nth checkpoint restore (1-based)
             collection — fires when the async actor's collection index
                     == N (1-based; docs/ASYNC_RL.md)
             version — fires when the weight channel publishes params
                     version N
             request — fires when the serve frontend's request id == N
                     (1-based; docs/SERVING.md)
    count    consecutive firings (default 1)

Examples::

    reward_raise@call:3*2        # reward_fn attempts 3 and 4 raise
    sigterm@step:5               # SIGTERM delivered before update 6 starts
    sigterm_one_proc@step:5      # same, but ONLY process 0 is signaled —
                                 # the coordinated-preemption allgather must
                                 # propagate it to the peers
    nan_loss@step:7              # the loss of update 8 is poisoned to NaN
    crash_save@save:2            # the 2nd save_state dies before committing
    topology_shrink@resume:1     # the 1st restore takes the elastic reshard
                                 # path even on a matching mesh
    sleep_one_proc@step:2*3      # the LAST process (highest rank) sleeps
                                 # inside updates 3-5 — a deterministic
                                 # straggler for the cluster-telemetry
                                 # watchdog (cluster/straggler_rank)
    flightrec_dump@step:4        # dump the crash flight recorder at the
                                 # boundary before update 5 (deterministic
                                 # flightrec.json exercise, no crash needed)
    actor_crash@collection:2     # an async generation actor dies at the
                                 # start of its collection-2 chunk — the
                                 # supervisor must requeue the chunk and
                                 # respawn the actor (docs/ASYNC_RL.md)
    weight_sync_drop@version:3   # the weight channel drops the payload of
                                 # params-version-3's publish; actors keep
                                 # the previous params until the next
                                 # publish (deterministic staleness/IW
                                 # exercise)
    health_trip@step:1           # force the RL health monitor to trip at
                                 # the boundary before update 2 — exercises
                                 # the detector → flightrec-dump → bad-batch
                                 # triage path (observability/health.py)
                                 # without needing an organically sick run
    slow_client@request:2        # serve request 2's streaming consumer
                                 # stalls forever — the engine-side producer
                                 # must keep harvesting (bounded stream
                                 # buffer, connection dropped), never wedge
                                 # the slot (docs/RESILIENCE.md, SERVING.md)
    request_flood@step:3         # admission-control drill at the boundary
                                 # before update 4: a synthetic burst is
                                 # pushed through the serve admission path,
                                 # which must shed it with 429s instead of
                                 # letting the queue-wait SLO blow

Plans come from ``config.resilience.fault_plan`` or the
``TRLX_TPU_FAULT_PLAN`` env var (env wins — a relaunched run can drop the
fault by clearing the variable without editing configs). Sites reach the
plan through the module-level *active plan* (:func:`set_active_plan` /
:func:`poll_fault`) so low-level code (``utils/checkpoint.py``) needs no
trainer handle.
"""

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

_KINDS = frozenset({
    "reward_raise", "publish_raise", "sigterm", "sigint", "sigterm_one_proc",
    "nan_loss", "crash_save", "topology_shrink", "sleep_one_proc",
    "flightrec_dump", "actor_crash", "weight_sync_drop", "health_trip",
    "slow_client", "request_flood",
})

# how long a ``sleep_one_proc`` fault stalls the afflicted rank's train step
# (env-overridable so tests can size the stall above the real step time)
SLEEP_FAULT_S = float(os.environ.get("TRLX_TPU_FAULT_SLEEP_S", "0.5"))
_TRIGGERS = frozenset(
    {"call", "step", "save", "resume", "collection", "version", "request"}
)


class InjectedFault(RuntimeError):
    """Raised by a fault-plan site standing in for a real failure."""


@dataclass(frozen=True)
class FaultSpec:
    """One parsed plan entry: fire ``kind`` for ``count`` consecutive
    trigger values starting at ``n``."""

    kind: str
    trigger: str  # "call" | "step" | "save"
    n: int
    count: int = 1

    def matches(self, value: int) -> bool:
        return self.n <= value < self.n + self.count


@dataclass
class FaultPlan:
    """A set of :class:`FaultSpec` plus per-site call counters.

    ``poll(kind)`` advances the counter for call/save-triggered entries and
    reports whether this invocation should fault; ``poll(kind, step=s)``
    checks step-triggered entries against the caller's step counter without
    advancing anything. Thread-safe: host-call sites poll from pipeline
    worker threads.
    """

    specs: List[FaultSpec] = field(default_factory=list)
    _counters: Dict[str, int] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)
    fired: Dict[str, int] = field(default_factory=dict)

    @classmethod
    def parse(cls, plan: Optional[str]) -> "FaultPlan":
        specs: List[FaultSpec] = []
        for raw in (plan or "").split(";"):
            entry = raw.strip()
            if not entry:
                continue
            try:
                kind, rest = entry.split("@", 1)
                count = 1
                if "*" in rest:
                    rest, count_s = rest.rsplit("*", 1)
                    count = int(count_s)
                trigger, n_s = rest.split(":", 1)
                spec = FaultSpec(kind.strip(), trigger.strip(), int(n_s), count)
            except (ValueError, TypeError) as e:
                raise ValueError(
                    f"unparseable fault-plan entry {entry!r} (syntax: "
                    f"kind@trigger:N[*count], docs/RESILIENCE.md): {e}"
                ) from e
            if spec.kind not in _KINDS:
                raise ValueError(
                    f"unknown fault kind {spec.kind!r} (known: {sorted(_KINDS)})"
                )
            if spec.trigger not in _TRIGGERS:
                raise ValueError(
                    f"unknown fault trigger {spec.trigger!r} "
                    f"(known: {sorted(_TRIGGERS)})"
                )
            if spec.count < 1 or spec.n < 0:
                raise ValueError(f"fault-plan entry {entry!r}: n/count out of range")
            specs.append(spec)
        return cls(specs=specs)

    @classmethod
    def from_config(cls, plan: Optional[str]) -> "FaultPlan":
        """Parse ``plan``, letting ``TRLX_TPU_FAULT_PLAN`` override it."""
        return cls.parse(os.environ.get("TRLX_TPU_FAULT_PLAN") or plan)

    def __bool__(self) -> bool:
        return bool(self.specs)

    def poll(
        self,
        kind: str,
        step: Optional[int] = None,
        collection: Optional[int] = None,
        version: Optional[int] = None,
        request: Optional[int] = None,
    ) -> bool:
        """Should the consulting site fault now?

        With no caller counter this is an *invocation* poll: the per-kind
        call counter advances by one and call/save/resume-triggered entries
        match against it. With ``step=s`` / ``collection=c`` / ``version=v``
        / ``request=r`` only the matching trigger's entries are checked
        against the caller's own counter (idempotent — the caller polls
        once per update / collection / publish / serve request)."""
        if not self.specs:
            return False
        with self._lock:
            if step is not None:
                value, triggers = step, ("step",)
            elif collection is not None:
                value, triggers = collection, ("collection",)
            elif version is not None:
                value, triggers = version, ("version",)
            elif request is not None:
                value, triggers = request, ("request",)
            else:
                value = self._counters.get(kind, 0) + 1
                self._counters[kind] = value
                triggers = ("call", "save", "resume")
            hit = any(
                s.kind == kind and s.trigger in triggers and s.matches(value)
                for s in self.specs
            )
            if hit:
                self.fired[kind] = self.fired.get(kind, 0) + 1
            return hit


# ---------------------------------------------------------------------------
# process-wide active plan: low-level sites (checkpoint commit) consult this
# without a trainer handle. One training run per process is the norm; the
# last-constructed Resilience bundle owns the slot.
# ---------------------------------------------------------------------------

_ACTIVE_PLAN: Optional[FaultPlan] = None


def set_active_plan(plan: Optional[FaultPlan]) -> None:
    global _ACTIVE_PLAN
    _ACTIVE_PLAN = plan if plan else None


def get_active_plan() -> Optional[FaultPlan]:
    return _ACTIVE_PLAN


def poll_fault(
    kind: str, step: Optional[int] = None, request: Optional[int] = None
) -> bool:
    """Convenience for sites without a plan handle; False when no plan."""
    plan = _ACTIVE_PLAN
    return bool(plan) and plan.poll(kind, step=step, request=request)
