"""Update guard: on-device all-finite check fused into the train step, with
a host-side ``skip`` / ``rollback`` / ``halt`` policy.

A single NaN loss previously corrupted the parameters (NaN gradients flow
through ``optax.apply_updates`` into every weight) and the run kept training
on garbage until someone read the curves. The guard closes that hole with
**zero extra host syncs**:

- device side (``trainer/base.py::_build_train_step``): the step computes
  ``all_finite = isfinite(global_norm(grads))`` — the global norm is already
  computed for ``gradients/global_norm``, and any non-finite loss, grad, or
  activation NaN propagates into it. Under the ``skip`` policy it also
  selects the *old* params/opt-state via ``jnp.where`` when the check fails
  (NOTE: the select keeps both state versions live, defeating donation's
  in-place update — ≈2× train-step temp memory; ``rollback``/``halt`` are
  flag-only and keep the donated memory profile). The flag rides back in
  the stats dict the learn loop already fetches every step;
- host side (:class:`UpdateGuard`): reads ``resilience/update_ok`` from the
  landed stats and applies the configured policy:

  ``skip``      drop the poison update (device already kept the old state),
                count it, continue with the next batch;
  ``rollback``  restore the newest *committed* checkpoint from the
                retention ring (the poisoned update has landed on device —
                without a committed checkpoint this halts). Also right for
                when a bad update landed earlier, e.g. bf16 overflow
                poisoning the optimizer moments a few steps before the
                norm finally blew up;
  ``halt``      raise :class:`NonFiniteUpdateError` after flushing
                observability — for debugging runs where silent recovery
                would hide the bug.

``max_consecutive`` bounds pathological loops: a run whose every update is
non-finite (true divergence, not a poison batch) escalates to ``halt``
instead of spinning to ``total_steps`` without learning anything.

Metric accounting: ``resilience/skipped_updates``, ``resilience/rollbacks``,
``resilience/nonfinite_updates``, and the ``resilience/goodput_frac`` gauge
(committed updates ÷ attempted updates) all flow through the tracker stream.
"""

from typing import Any, Dict, Optional

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

POLICIES = ("off", "skip", "rollback", "halt")

# the stats key the device-side check publishes (1.0 = update committed)
UPDATE_OK_KEY = "resilience/update_ok"


class NonFiniteUpdateError(RuntimeError):
    """A non-finite update under the ``halt`` policy (or escalation)."""


class UpdateGuard:
    """Host-side policy half of the update guard (see module docstring)."""

    def __init__(
        self,
        policy: str = "off",
        max_consecutive: int = 25,
        metrics: Any = None,
    ):
        if policy not in POLICIES:
            raise ValueError(
                f"unknown update_guard policy {policy!r} (use one of {POLICIES})"
            )
        self.policy = policy
        self.max_consecutive = int(max_consecutive)
        self.metrics = metrics
        self.consecutive = 0
        self.attempted = 0
        self.committed = 0

    @property
    def enabled(self) -> bool:
        return self.policy != "off"

    def _inc(self, key: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(key)

    def after_step(self, stats: Dict[str, float]) -> Optional[str]:
        """Inspect one step's landed host stats; return the action the learn
        loop must take: ``None`` (continue), ``"rollback"``, or raise
        :class:`NonFiniteUpdateError` for ``halt``/escalation."""
        if not self.enabled:
            return None
        ok = stats.get(UPDATE_OK_KEY, 1.0) >= 0.5
        self.attempted += 1
        if ok:
            self.committed += 1
            self.consecutive = 0
        else:
            self.consecutive += 1
            self._inc("resilience/nonfinite_updates")
        if self.metrics is not None:
            goodput = self.committed / max(self.attempted, 1)
            self.metrics.set_gauge("resilience/goodput_frac", goodput)
        if ok:
            return None
        if self.policy == "halt":
            raise NonFiniteUpdateError(
                "non-finite loss/gradients and update_guard='halt'"
            )
        if self.consecutive >= self.max_consecutive:
            raise NonFiniteUpdateError(
                f"{self.consecutive} consecutive non-finite updates "
                f"(update_guard='{self.policy}', max_consecutive="
                f"{self.max_consecutive}): the run has diverged — halting "
                "instead of spinning"
            )
        if self.policy == "rollback":
            self._inc("resilience/rollbacks")
            logger.warning(
                "non-finite update: rolling back to the newest committed "
                "checkpoint and skipping the poison batch"
            )
            return "rollback"
        self._inc("resilience/skipped_updates")
        logger.warning("non-finite update: skipped (old state kept on device)")
        return None
