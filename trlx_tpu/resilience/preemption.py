"""Preemption handling: SIGTERM/SIGINT → emergency checkpoint at the next
step boundary → clean exit.

TPU fleets preempt routinely (RLAX, arxiv 2512.06392, treats this as table
stakes; Podracer, arxiv 2104.06272, shows pod-scale RL only pays off when
restarts are cheap). The handler converts an asynchronous kill signal into a
*synchronous, step-aligned* event: the signal callback only sets a flag; the
learn loop checks :attr:`PreemptionHandler.requested` before starting each
update, saves an emergency checkpoint (full train state + host-side
controller state + rollout RNG + the PPO store), commits it, and raises
:class:`TrainingPreempted`. ``maybe_resume`` then restores the run to the
exact step boundary — bit-identical to never having been preempted
(``tests/test_resilience.py``).

A second signal while the first is being honored restores the previous
handler and re-raises, so an impatient double Ctrl-C still kills the
process immediately.
"""

import signal
import threading
from typing import Any, Dict, List, Optional

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


class TrainingPreempted(SystemExit):
    """Raised by the learn loop after the emergency checkpoint commits.

    Subclasses ``SystemExit`` (code 0) so an unhandled preemption exits the
    process cleanly — the scheduler sees a graceful shutdown, and a relaunch
    with ``train.resume_from_checkpoint`` continues the run.
    """

    def __init__(self, message: str, checkpoint_dir: Optional[str] = None):
        super().__init__(0)
        self.message = message
        self.checkpoint_dir = checkpoint_dir

    def __str__(self) -> str:
        return self.message


class PreemptionHandler:
    """Flag-setting signal handler, installed only while training runs.

    Use as a context manager around the learn loop::

        with self.resilience.preemption:
            for step in ...:
                if self.resilience.preemption.requested:
                    <emergency checkpoint, raise TrainingPreempted>

    Handlers install on entry and the *previous* handlers are restored on
    exit, so a trainer never hijacks signals for the rest of the process.
    Installation is skipped (with a warning) off the main thread — Python
    only allows signal handlers there — and when ``enabled`` is False.
    ``request()`` triggers the same path programmatically (tests, fault
    plans, cluster-specific preemption notices).
    """

    def __init__(
        self,
        enabled: bool = True,
        signals: Optional[List[str]] = None,
        metrics: Any = None,
    ):
        self.enabled = enabled
        self.signal_names = list(signals or ("SIGTERM", "SIGINT"))
        self.metrics = metrics
        self.requested = False
        self.signal_received: Optional[str] = None
        self._previous: Dict[int, Any] = {}
        self._installed = False

    # -- signal plumbing ------------------------------------------------

    def _signums(self) -> List[int]:
        nums = []
        for name in self.signal_names:
            num = getattr(signal, name, None)
            if num is None:
                logger.warning(f"unknown preemption signal {name!r}; skipping")
            else:
                nums.append(int(num))
        return nums

    def _on_signal(self, signum, frame) -> None:
        name = signal.Signals(signum).name
        if self.requested:
            # second signal: the operator really means it — restore the old
            # handler and re-deliver so default disposition (kill) applies
            logger.warning(f"second {name} during shutdown; exiting immediately")
            self._uninstall()
            signal.raise_signal(signum)
            return
        self.requested = True
        self.signal_received = name
        if self.metrics is not None:
            self.metrics.inc("resilience/preemptions")
        logger.warning(
            f"{name} received: emergency checkpoint at the next step boundary"
        )

    def request(self, reason: str = "programmatic") -> None:
        """Trigger preemption without a signal (tests, external notices)."""
        if not self.requested:
            self.requested = True
            self.signal_received = reason
            if self.metrics is not None:
                self.metrics.inc("resilience/preemptions")

    # -- context manager ------------------------------------------------

    def __enter__(self) -> "PreemptionHandler":
        self.requested = False
        self.signal_received = None
        if not self.enabled:
            return self
        if threading.current_thread() is not threading.main_thread():
            logger.warning(
                "preemption handlers need the main thread; running without "
                "signal handling (request() still works)"
            )
            return self
        for num in self._signums():
            try:
                self._previous[num] = signal.signal(num, self._on_signal)
            except (ValueError, OSError) as e:  # pragma: no cover - platform
                logger.warning(f"could not install handler for signal {num}: {e}")
        self._installed = True
        return self

    def _uninstall(self) -> None:
        if not self._installed:
            return
        for num, prev in self._previous.items():
            try:
                signal.signal(num, prev)
            except (ValueError, OSError):  # pragma: no cover - defensive
                pass
        self._previous = {}
        self._installed = False

    def __exit__(self, *exc_info) -> None:
        self._uninstall()
