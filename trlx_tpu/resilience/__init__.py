"""Resilience: preemption-safe training, non-finite-update recovery, host-call
hardening, and deterministic fault injection.

The observability subsystem (PR 1) made runs *self-reporting*; this one makes
them *self-healing*. Four pieces, bundled per trainer as
``trainer.resilience`` (a :class:`Resilience` instance — the shape mirrors
``trainer.obs``):

- :mod:`preemption` — SIGTERM/SIGINT → emergency checkpoint at the next step
  boundary → clean exit; resume is bit-identical to an uninterrupted run;
- :mod:`guard` — on-device all-finite check fused into the train step (no
  extra host sync) with ``skip`` / ``rollback`` / ``halt`` policies;
- :mod:`retry` — retry/timeout/exponential-backoff-with-jitter around
  ``reward_fn`` and tracker publishes, with configurable fallbacks;
- :mod:`faults` — a deterministic :class:`FaultPlan`
  (``"sigterm@step:5; nan_loss@step:7"``) that tests and ``bench.py`` use to
  prove recovery end-to-end on CPU.

Atomic checkpoint commits (stage → rename → marker) live in
``trlx_tpu/utils/checkpoint.py``; the guard's rollback and ``maybe_resume``
both trust only *committed* checkpoints. Knobs: ``config.resilience``
(:class:`~trlx_tpu.data.configs.ResilienceConfig`); semantics:
``docs/RESILIENCE.md``.
"""

from typing import Any, Callable, Optional

from trlx_tpu.resilience.elastic import (
    ElasticRestoreError,
    build_manifest,
    coordinate_preemption,
    manifest_mismatch,
    read_manifest,
    restore_state_elastic,
)
from trlx_tpu.resilience.faults import (
    FaultPlan,
    InjectedFault,
    get_active_plan,
    poll_fault,
    set_active_plan,
)
from trlx_tpu.resilience.guard import (
    UPDATE_OK_KEY,
    NonFiniteUpdateError,
    UpdateGuard,
)
from trlx_tpu.resilience.preemption import PreemptionHandler, TrainingPreempted
from trlx_tpu.resilience.retry import (
    HostCallGuard,
    ResilientTracker,
    neutral_rewards,
)

__all__ = [
    "ElasticRestoreError",
    "FaultPlan",
    "HostCallGuard",
    "InjectedFault",
    "NonFiniteUpdateError",
    "PreemptionHandler",
    "Resilience",
    "ResilientTracker",
    "TrainingPreempted",
    "UPDATE_OK_KEY",
    "UpdateGuard",
    "build_manifest",
    "coordinate_preemption",
    "get_active_plan",
    "manifest_mismatch",
    "neutral_rewards",
    "poll_fault",
    "read_manifest",
    "restore_state_elastic",
    "set_active_plan",
]


class Resilience:
    """Per-trainer bundle: fault plan + preemption handler + update guard +
    host-call hardening, built from ``config.resilience`` and sharing the
    trainer's metrics registry so every ``resilience/*`` counter rides the
    existing tracker stream.
    """

    def __init__(self, config: Any, metrics: Any = None):
        from trlx_tpu.data.configs import ResilienceConfig

        rcfg = getattr(config, "resilience", None)
        if rcfg is None:
            rcfg = ResilienceConfig()
        self.config = rcfg
        self.metrics = metrics
        self.plan = FaultPlan.from_config(rcfg.fault_plan)
        # low-level sites (checkpoint commit) consult the process-active
        # plan; a plan-less trainer clears it so a previous trainer's faults
        # don't leak across runs in one process
        set_active_plan(self.plan)
        self.preemption = PreemptionHandler(
            enabled=rcfg.handle_preemption,
            signals=list(rcfg.preemption_signals),
            metrics=metrics,
        )
        self.guard = UpdateGuard(
            policy=rcfg.update_guard,
            max_consecutive=rcfg.max_consecutive_nonfinite,
            metrics=metrics,
        )

    def harden_reward_fn(
        self, reward_fn: Optional[Callable], seed: int = 0
    ) -> Optional[Callable]:
        """Wrap ``reward_fn`` in retry/timeout/backoff per the config; the
        trainer installs this once so every call site (rollout scoring,
        eval) is hardened transparently."""
        if reward_fn is None:
            return None
        rcfg = self.config
        return HostCallGuard(
            reward_fn,
            name="reward",
            retries=rcfg.reward_retries,
            backoff_s=rcfg.reward_backoff_s,
            backoff_max_s=rcfg.reward_backoff_max_s,
            timeout_s=rcfg.reward_timeout_s,
            fallback=rcfg.reward_fallback,
            neutral_fn=neutral_rewards,
            max_consecutive_fallbacks=rcfg.reward_max_consecutive_fallbacks,
            metrics=self.metrics,
            plan=self.plan,
            seed=seed,
        )

    def harden_tracker(self, tracker: Any, seed: int = 0) -> Any:
        """Wrap a tracker so publish failures retry, then drop — never
        killing the run."""
        return ResilientTracker(
            tracker,
            retries=self.config.publish_retries,
            backoff_s=self.config.publish_backoff_s,
            metrics=self.metrics,
            plan=self.plan,
            seed=seed,
        )
