"""The weight-sync channel: learner → actors param dissemination with
versioned publishes and a staleness gate.

The learner publishes its params after each optimizer update (``version`` =
completed update count; thinned by ``async_rl.sync_every``) and *announces*
``(collection, target)``: the version at which collection ``collection``
will be consumed. Actors gate each chunk on its own collection::

    chunk.collection >  announced collection → wait (its consumption
                                               version is unknown — running
                                               further ahead could exceed
                                               any bound)
    chunk.collection == announced collection → wait until
                                               target − newest_payload_version
                                               ≤ max_staleness
    chunk.collection <  announced collection → free (its consumption point
                                               has already arrived)

which bounds staleness at consumption structurally — no chunk can start
under params older than the bound allows, production never runs more than
one collection ahead of consumption, and the learner re-publishes +
re-announces at drain start so an over-estimated target (or a dropped
publish) can never deadlock the gate.

Publishes deep-copy the param tree: the train step donates its input
state, so a published reference into ``state.params`` would be invalidated
by the next update while an actor is mid-generation under it.

The deterministic ``weight_sync_drop@version:N`` fault drops the payload of
publish N (actors keep version N−1's params until the next publish) — the
reproducible exercise of the staleness/IW-correction path.

Two transports: :class:`WeightChannel` (in-process, thread mode) and
:class:`FileWeightChannel` (atomic weights file + manifest, process mode —
the filesystem stand-in for RLAX's param-dissemination tree).
"""

import json
import os
import threading
import time
import zipfile
from typing import Any, Optional, Tuple

import numpy as np

__all__ = ["WeightChannel", "FileWeightChannel"]


def _copy_params(params: Any) -> Any:
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(jnp.copy, params)


class WeightChannel:
    """In-process learner→actor param channel (thread mode)."""

    def __init__(self, plan: Any = None, metrics: Any = None, sync_every: int = 1):
        self._plan = plan
        self.metrics = metrics
        self.sync_every = max(1, int(sync_every))
        self._cond = threading.Condition()
        self._params: Any = None  # guarded-by: _cond
        self._payload_version = -1  # guarded-by: _cond
        self._target = 0  # guarded-by: _cond
        self._announced_col = 0  # guarded-by: _cond
        self._closed = False  # guarded-by: _cond

    def publish(self, params: Any, version: int, force: bool = False) -> None:
        """Publish ``params`` as ``version``. Thinned by ``sync_every``
        unless ``force`` (the learner forces at phase boundaries so actors
        always see the consumption-time params). The ``weight_sync_drop``
        fault drops this publish's payload deterministically."""
        if not force and version % self.sync_every != 0:
            return
        with self._cond:
            if version <= self._payload_version:
                return  # already-published version (boundary force republish)
                # — checked BEFORE the full-tree copy below, which is a real
                # allocation at model scale
        if self._plan is not None and self._plan.poll("weight_sync_drop", version=version):
            if self.metrics is not None:
                self.metrics.inc("async/weight_sync_drops")
            return
        copied = _copy_params(params)
        with self._cond:
            if version <= self._payload_version:
                return  # lost a publish race while copying
            self._params = copied
            self._payload_version = version
            self._cond.notify_all()
        if self.metrics is not None:
            self.metrics.inc("async/weight_syncs")

    def announce(self, target: int, collection: int) -> None:
        """Record that collection ``collection`` will be consumed at version
        ``target``. The collection index is monotonic; a LATER announcement
        for the SAME collection may lower the target — the drain-start
        announce carries the true consumption version, which heals an
        over-estimated phase-end target (a learn phase that ran fewer
        updates than predicted must not gate actors forever)."""
        with self._cond:
            if int(collection) > self._announced_col:
                self._announced_col = int(collection)
                self._target = int(target)
            elif int(collection) == self._announced_col:
                self._target = min(self._target, int(target))
            self._cond.notify_all()

    def fetch(self, template: Any = None) -> Tuple[Any, int]:
        """Newest published (params, version); blocks until the first
        publish lands. ``template`` is accepted for transport symmetry with
        :class:`FileWeightChannel` (in-process payloads need no restore)."""
        with self._cond:
            while self._params is None:
                if self._closed:
                    raise RuntimeError("weight channel closed before first publish")
                self._cond.wait(timeout=0.1)
            return self._params, self._payload_version

    def _gate(self, max_staleness: int, collection: int) -> bool:
        # caller holds _cond
        if self._params is None or collection > self._announced_col:
            return False
        if collection < self._announced_col:
            return True  # its consumption point has already arrived
        return self._target - self._payload_version <= max_staleness

    def ready(self, max_staleness: int, collection: int = 1) -> bool:
        """Non-blocking gate check: may a chunk of ``collection`` start
        under the newest payload without violating the staleness bound?"""
        with self._cond:
            return self._gate(max_staleness, collection)

    def wait_ready(
        self,
        max_staleness: int,
        collection: int = 1,
        stop: Optional[threading.Event] = None,
    ) -> bool:
        """Block until starting a chunk of ``collection`` under the newest
        payload satisfies the staleness bound. Returns False when
        closed/stopped."""
        with self._cond:
            while True:
                if self._closed or (stop is not None and stop.is_set()):
                    return False
                if self._gate(max_staleness, collection):
                    return True
                self._cond.wait(timeout=0.05)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


class FileWeightChannel:
    """Atomic file-backed param channel (process mode).

    Layout under ``root``: ``weights.npz`` (flattened leaf list, tmp+rename
    committed) and ``MANIFEST.json`` (``{"version": payload version,
    "target": phase-end target}``). The manifest is written after the
    weights file; the version stamped inside the npz lets a reader detect a
    racing overwrite and retry. Readers cache the last adopted version, so
    polling is one small JSON read until something actually changes.
    """

    MANIFEST = "MANIFEST.json"
    WEIGHTS = "weights.npz"

    def __init__(
        self,
        root: str,
        plan: Any = None,
        metrics: Any = None,
        sync_every: int = 1,
        poll_interval_s: float = 0.02,
        fetch_timeout_s: float = 60.0,
    ):
        self.root = root
        self._plan = plan
        self.metrics = metrics
        self.sync_every = max(1, int(sync_every))
        self.poll = float(poll_interval_s)
        # fetch retry is DEADLINE-based, never attempt-count-based: the
        # learner's npz write scales with the model, and a healthy slow
        # writer must not read as "writer dead" (floor 30s)
        self.fetch_timeout_s = max(30.0, float(fetch_timeout_s))
        os.makedirs(root, exist_ok=True)
        self._cache: Tuple[Any, int] = (None, -1)
        self._closed = False

    # -- learner side ----------------------------------------------------

    def _read_manifest(self) -> dict:
        try:
            with open(os.path.join(self.root, self.MANIFEST)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {"version": -1, "target": 0}

    def _write_manifest(self, manifest: dict) -> None:
        path = os.path.join(self.root, self.MANIFEST)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, path)

    def publish(self, params: Any, version: int, force: bool = False) -> None:
        if not force and version % self.sync_every != 0:
            return
        if self._plan is not None and self._plan.poll("weight_sync_drop", version=version):
            if self.metrics is not None:
                self.metrics.inc("async/weight_sync_drops")
            return
        manifest = self._read_manifest()
        if version <= int(manifest.get("version", -1)):
            return
        import jax

        leaves = jax.tree_util.tree_leaves(jax.device_get(params))
        arrays = {"__version__": np.asarray(version, np.int64)}
        for i, leaf in enumerate(leaves):
            arr = np.asarray(leaf)
            if arr.dtype.kind == "V":  # bf16 → f32 is exact; cast back on load
                arr = arr.astype(np.float32)
            arrays[f"leaf_{i:05d}"] = arr
        path = os.path.join(self.root, self.WEIGHTS)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
        manifest["version"] = version
        self._write_manifest(manifest)
        if self.metrics is not None:
            self.metrics.inc("async/weight_syncs")

    def announce(self, target: int, collection: int) -> None:
        """Same semantics as :meth:`WeightChannel.announce`: monotonic
        collection, same-collection announcements may LOWER the target
        (the drain-start heal)."""
        manifest = self._read_manifest()
        old_target = int(manifest.get("target", 0))
        old_col = int(manifest.get("collection", 0))
        if int(collection) > old_col:
            new_col, new_target = int(collection), int(target)
        elif int(collection) == old_col:
            new_col, new_target = old_col, min(old_target, int(target))
        else:
            return
        if new_target == old_target and new_col == old_col:
            return  # no-op announce (the drain-time heal path) — skip the write
        manifest["target"] = new_target
        manifest["collection"] = new_col
        self._write_manifest(manifest)

    # -- actor side ------------------------------------------------------

    def fetch(self, template: Any = None) -> Tuple[Any, int]:
        """Newest published (params, version), restored into ``template``'s
        tree structure/dtypes (the actor's own built params). Blocks until
        the first publish lands."""
        manifest = self._read_manifest()
        while int(manifest.get("version", -1)) < 0:
            if self._closed:
                raise RuntimeError("weight channel closed before first publish")
            time.sleep(self.poll)
            manifest = self._read_manifest()
        version = int(manifest["version"])
        if version == self._cache[1]:
            return self._cache
        import jax

        path = os.path.join(self.root, self.WEIGHTS)
        leaves = None
        deadline = time.monotonic() + self.fetch_timeout_s
        # the retry sleep is floored: with poll_interval_s ≈ 0 a deadline
        # this long would otherwise busy-spin re-deserializing the full
        # npz at 100% CPU until the writer lands
        retry_pause = max(self.poll, 0.005)
        while time.monotonic() < deadline:
            try:
                with np.load(path) as data:
                    stamped = int(data["__version__"])
                    read = [data[k] for k in sorted(data.files) if k.startswith("leaf_")]
            except (OSError, ValueError, KeyError, zipfile.BadZipFile):
                time.sleep(retry_pause)  # mid-replace read; retry
                continue
            if stamped < version:
                time.sleep(retry_pause)  # manifest ahead of a racing writer
                continue
            # a payload at least as new as the manifest promised: adopt it
            # under ITS stamped version (never mislabel old leaves new)
            version = stamped
            leaves = read
            break
        if leaves is None:
            raise RuntimeError(
                f"weight channel: no readable payload >= version {version} "
                f"at {path} after {self.fetch_timeout_s:.0f}s — writer dead "
                "or directory corrupted? (a slow large-model write needs a "
                "larger async_rl.fetch_timeout_s)"
            )
        if template is not None:
            treedef = jax.tree_util.tree_structure(template)
            tleaves = jax.tree_util.tree_leaves(template)
            leaves = [
                np.asarray(leaf).astype(t.dtype) if hasattr(t, "dtype") else leaf
                for leaf, t in zip(leaves, tleaves)
            ]
            params = jax.tree_util.tree_unflatten(treedef, leaves)
        else:
            params = leaves
        self._cache = (params, version)
        return self._cache

    def ready(self, max_staleness: int, collection: int = 1) -> bool:
        """Non-blocking gate check: may a chunk of ``collection`` start
        under the newest payload without violating the staleness bound?"""
        manifest = self._read_manifest()
        version = int(manifest.get("version", -1))
        target = int(manifest.get("target", 0))
        announced_col = int(manifest.get("collection", 0))
        if version < 0 or collection > announced_col:
            return False
        if collection < announced_col:
            return True
        return target - version <= max_staleness

    def wait_ready(
        self,
        max_staleness: int,
        collection: int = 1,
        stop: Optional[threading.Event] = None,
    ) -> bool:
        while True:
            if self._closed or (stop is not None and stop.is_set()):
                return False
            if self.ready(max_staleness, collection):
                return True
            time.sleep(self.poll)

    def close(self) -> None:
        self._closed = True
