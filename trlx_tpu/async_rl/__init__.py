"""Disaggregated async RL: actor/learner split with a staleness-bounded
experience queue and in-flight weight sync (ROADMAP item 1).

The single-program loop alternates ``make_experience`` and ``learn`` — the
device is either generating or training, never both. This subsystem splits
training into one **learner** and N **generation actors**:

- **thread mode** (``async_rl.mode: thread``): actors are in-process
  threads over the existing Engine paths; the learner is the main thread's
  train loop. The split overlaps host-side rollout work (string decode,
  ``reward_fn``, device→host fetches) *and* whole collections with
  optimization — collection k+1 is generated while the learner optimizes
  on collection k.
- **process mode** (``async_rl.mode: process``): actors are separate
  processes (their own JAX runtime, their own devices — on a pod, their
  own slice). The transport between them is selectable
  (``async_rl.transport``): the filesystem fallback (atomic weights file +
  chunk spool), or the **collective fleet fabric**
  (``async_rl/transport.py``) — a param-dissemination tree shipping
  versioned deltas with unchanged-leaf skipping over a
  configurable-fanout relay, in-fabric chunk commits, and elastic
  join/leave membership (RLAX's tree, Podracer's in-fabric pairs; see
  docs/ASYNC_RL.md "Transports"). Provable on the 2-process CPU harness.

The two halves meet at two seams:

- :class:`~trlx_tpu.async_rl.queue.ExperienceQueue` — a bounded buffer of
  version-tagged experience chunks. Capacity back-pressures actors
  (``block``) or evicts the oldest chunk (``drop_oldest``).
- :class:`~trlx_tpu.async_rl.channel.WeightChannel` — the learner
  publishes params after each optimizer update (version = completed
  update count) and announces the version at which the next collection
  will be consumed; actors adopt the newest payload at chunk *and* segment
  boundaries (PipelineRL-style in-flight updates, riding
  ``ContinuousEngine.swap_params``'s version-counter check so unchanged
  params never re-walk the tree and changed params flush the prefix
  cache — stale shared KV is never reused).

**Staleness bound.** A chunk's staleness is the number of learner updates
between the params that *started* it and the learner's version when it is
consumed. The learner announces ``target`` = the version at which the next
collection drains; an actor may only start a chunk once the newest
published payload satisfies ``target − version ≤ max_staleness``. With
``max_staleness: 0`` the gate degenerates to the alternating loop — the
store is bit-identical to the serial reference path under a fixed seed
(``tests/test_async_rl.py``). Off-policy lag is corrected in the loss by
the clipped per-token behavior-logprob ratio (``method.iw_correction``),
off by default.

Crash containment leans on the resilience subsystem: a dead actor's
in-flight chunk spec (prompts + RNG) is requeued and a replacement actor
respawned (thread mode), or the respawned actor process fast-forwards to
the first uncommitted chunk (process mode) — deterministic either way,
exercised by the ``actor_crash@collection:N`` fault.

Semantics, knobs, and deployment notes: docs/ASYNC_RL.md.
"""

from trlx_tpu.async_rl.channel import FileWeightChannel, WeightChannel
from trlx_tpu.async_rl.queue import (
    ExperienceChunk,
    ExperienceQueue,
    FileExperienceQueue,
    QueueClosed,
)
from trlx_tpu.async_rl.runtime import AsyncCollector, ChunkSpec
from trlx_tpu.async_rl.transport import (
    CollectiveExperienceQueue,
    CollectiveWeightChannel,
    FleetActorClient,
    FleetCoordinator,
)

__all__ = [
    "AsyncCollector",
    "ChunkSpec",
    "CollectiveExperienceQueue",
    "CollectiveWeightChannel",
    "ExperienceChunk",
    "ExperienceQueue",
    "FileExperienceQueue",
    "FileWeightChannel",
    "FleetActorClient",
    "FleetCoordinator",
    "QueueClosed",
    "WeightChannel",
]
