"""The async collection runtime: chunk dispatch, actor supervision, and the
learner-side ordered drain.

One :class:`AsyncCollector` per trainer (built lazily at the first async
``make_experience``). Thread mode spawns ``num_actors`` actor threads that
pull :class:`ChunkSpec`\\ s from a deterministic dispatcher (prompt batch +
per-chunk RNG drawn in index order — exactly the serial path's draw
sequence), gate on the weight channel's staleness bound, produce chunk
payloads through the trainer's ``_async_produce_chunk``, and commit them to
the experience queue. Process mode spawns nothing: remote actors (see
``async_rl/actor.py``) feed a :class:`FileExperienceQueue` and the
collector only consumes.

Determinism and crash containment:

- the learner finalizes chunks strictly in index order (a reorder buffer
  absorbs multi-actor completion races), so order-sensitive learner state
  (PPO's reward running moments) folds chunks exactly as the serial path
  would;
- a dying actor's in-flight spec is REQUEUED at the front of the dispatch
  queue and a replacement actor thread is spawned — the respawned actor
  regenerates the identical chunk (same prompts, same RNG), so with
  ``max_staleness: 0`` a crash is invisible in the store
  (``tests/test_async_rl.py``). The deterministic
  ``actor_crash@collection:N`` fault drives this path on demand; it fires
  at most once per matching collection (the requeue covers the retry).
"""

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from trlx_tpu.async_rl.queue import ExperienceChunk, ExperienceQueue, QueueClosed
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

__all__ = ["AsyncCollector", "ChunkSpec"]


@dataclass
class ChunkSpec:
    """One unit of actor work, fully determined at dispatch: regenerating a
    spec is bit-deterministic given the same params version."""

    index: int  # global chunk position (finalize order)
    collection: int  # 1-based collection this chunk is expected to feed
    prompt_ids: np.ndarray  # [b, p] raw loader batch (pre group fan-out)
    prompt_mask: np.ndarray
    rng: Any  # this chunk's PRNG key (the serial path's per-chunk split)


class _ActorDied(RuntimeError):
    """Internal: an actor loop failed; its spec has been requeued."""


class AsyncCollector:
    """Actor supervision + ordered learner drain over one queue/channel pair.

    ``trainer`` supplies ``_async_produce_chunk(spec, params, version,
    channel)`` (the device+host half of one chunk) and the prompt iterator;
    everything order- or state-sensitive stays on the learner thread.
    """

    def __init__(
        self,
        trainer: Any,
        queue: Any,
        channel: Any,
        num_actors: int = 1,
        max_staleness: int = 0,
        updates_per_phase: int = 1,
        chunks_per_collection: int = 1,
        spawn_actors: bool = True,
        chunk_timeout_s: float = 300.0,
        max_actor_restarts: int = 3,
        metrics: Any = None,
        tracer: Any = None,
        span: Any = None,
        member_factory: Any = None,
        transport: Any = None,
    ):
        self._trainer = trainer
        self.queue = queue
        self.channel = channel
        self.num_actors = max(1, int(num_actors))
        self.max_staleness = max(0, int(max_staleness))
        self.updates_per_phase = max(1, int(updates_per_phase))
        self.chunks_per_collection = max(1, int(chunks_per_collection))
        self._spawn_actors = spawn_actors
        self._chunk_timeout_s = float(chunk_timeout_s)
        self._max_actor_restarts = int(max_actor_restarts)
        self.metrics = metrics
        self._tracer = tracer
        self._span = span
        # collective transport (async_rl.transport: collective): each actor
        # thread joins the fleet as its own member through member_factory;
        # `transport` is the learner-side FleetCoordinator (stats + elastic
        # membership). With the file/in-memory transports both stay None.
        self._member_factory = member_factory
        self._transport = transport
        self._elastic = transport is not None

        # dispatcher state: prompt/RNG draws happen in spec-index order under
        # this lock, so the draw stream is identical to the serial path's
        self._dispatch_lock = threading.Lock()
        self._retry: List[ChunkSpec] = []  # guarded-by: _dispatch_lock
        # every dispatched-but-unfinalized chunk's spec, by index: the
        # regeneration source for drop_oldest evictions and crash requeues
        self._inflight_specs: Dict[int, ChunkSpec] = {}  # guarded-by: _dispatch_lock
        self._next_index = 0  # guarded-by: _dispatch_lock
        self._rng = trainer._rollout_rng  # guarded-by: _dispatch_lock
        self._crash_fired: set = set()  # guarded-by: _dispatch_lock
        self._restarts = 0  # guarded-by: _dispatch_lock
        self._active_actors = 0  # guarded-by: _dispatch_lock
        self._fatal: Optional[BaseException] = None  # guarded-by: _dispatch_lock
        # actor busy/idle accounting (actor_idle_frac)
        self._idle_s = 0.0  # guarded-by: _dispatch_lock
        self._busy_s = 0.0  # guarded-by: _dispatch_lock

        self._stop = threading.Event()
        # respawns append from dying actor threads while close() snapshots
        self._threads: List[threading.Thread] = []  # guarded-by: _dispatch_lock
        self._started = False

        # learner-side (single-threaded) state
        self.version = 0  # completed learner updates (the version clock)
        self._next_finalize = 0
        self._reorder: Dict[int, ExperienceChunk] = {}
        self._col_stats = {"chunks": 0, "staleness_sum": 0.0, "staleness_max": 0.0,
                           "wait_s": 0.0}
        # actor busy/idle window start: rolls at each collection_stats()
        # call, so a collection's idle frac covers the whole production
        # window — including chunks produced DURING the previous learn phase
        self._win0 = (0.0, 0.0)

    # ------------------------------------------------------------------
    # dispatch (actor threads; index-ordered draws)
    # ------------------------------------------------------------------

    def _next_spec(self) -> ChunkSpec:
        import jax

        with self._dispatch_lock:
            if self._retry:
                return self._retry.pop(0)
            batch = next(self._trainer.prompt_iterator)
            ids = np.asarray(batch["input_ids"], np.int32)
            mask = np.asarray(batch["attention_mask"], np.int32)
            self._rng, chunk_rng = jax.random.split(self._rng)
            spec = ChunkSpec(
                index=self._next_index,
                collection=self._next_index // self.chunks_per_collection + 1,
                prompt_ids=ids,
                prompt_mask=mask,
                rng=chunk_rng,
            )
            self._inflight_specs[spec.index] = spec
            self._next_index += 1
            return spec

    def _requeue(self, spec: ChunkSpec) -> None:
        with self._dispatch_lock:
            self._retry.insert(0, spec)
        if self.metrics is not None:
            self.metrics.inc("async/requeued_chunks")

    def requeue_dropped(self, chunk: ExperienceChunk) -> None:
        """A drop_oldest eviction lost this chunk's DATA; its spec is still
        in flight, so the next free actor regenerates the index under
        fresher params — the learner's in-order drain depends on every
        index eventually arriving."""
        with self._dispatch_lock:
            spec = self._inflight_specs.get(chunk.index)
            if spec is not None:
                self._retry.insert(0, spec)
        if spec is not None and self.metrics is not None:
            self.metrics.inc("async/requeued_chunks")

    def _maybe_inject_crash(self, spec: ChunkSpec) -> None:
        plan = getattr(self._trainer.resilience, "plan", None)
        if not plan:
            return
        with self._dispatch_lock:
            if spec.collection in self._crash_fired:
                return
            if not plan.poll("actor_crash", collection=spec.collection):
                return
            self._crash_fired.add(spec.collection)
        from trlx_tpu.resilience.faults import InjectedFault

        raise InjectedFault(
            f"fault plan: actor crash in collection {spec.collection} "
            f"(chunk {spec.index})"
        )

    # ------------------------------------------------------------------
    # actor threads
    # ------------------------------------------------------------------

    def _actor_loop(self, actor_id: int) -> None:
        if self._tracer is not None and hasattr(self._tracer, "alias_current_thread"):
            self._tracer.alias_current_thread(f"async actor {actor_id}")
        # collective transport: this thread joins the fleet as its own
        # member — the in-process fleet exercises the same wire protocol
        # (tree deltas, in-fabric commits) as a pod's actor processes. A
        # failed join is an actor death (supervised: restart/shrink/fatal),
        # not a silently-vanished thread.
        client = None
        try:
            if self._member_factory is not None:
                client = self._member_factory(actor_id)
        except BaseException as e:
            raise _ActorDied(
                f"actor {actor_id} failed to join the fleet"
            ) from e
        channel = client if client is not None else self.channel
        queue = client if client is not None else self.queue
        try:
            while not self._stop.is_set():
                spec = self._next_spec()
                t_gate = time.perf_counter()
                if not channel.wait_ready(
                    self.max_staleness, spec.collection, stop=self._stop
                ):
                    self._requeue(spec)  # shutdown: leave the spec for nobody
                    return
                params, version = channel.fetch()
                gate_s = time.perf_counter() - t_gate
                try:
                    self._maybe_inject_crash(spec)
                    t0 = time.perf_counter()
                    if self._span is not None:
                        with self._span(
                            "async/actor_chunk", index=spec.index, version=version
                        ):
                            payload = self._trainer._async_produce_chunk(
                                spec, params, version, channel
                            )
                    else:
                        payload = self._trainer._async_produce_chunk(
                            spec, params, version, channel
                        )
                    busy_s = time.perf_counter() - t0
                except BaseException as e:
                    self._requeue(spec)
                    raise _ActorDied(
                        f"actor {actor_id} died on chunk {spec.index}"
                    ) from e
                t_put = time.perf_counter()
                try:
                    queue.put(ExperienceChunk(spec.index, version, payload))
                except QueueClosed:
                    return
                with self._dispatch_lock:
                    self._idle_s += gate_s + (time.perf_counter() - t_put)
                    self._busy_s += busy_s
                if self.metrics is not None and client is None:
                    # collective transport counts arrivals coordinator-side
                    self.metrics.inc("async/chunks")
        finally:
            if client is not None:
                client.close()

    def _actor_main(self, actor_id: int) -> None:
        died: Optional[_ActorDied] = None
        try:
            self._actor_loop(actor_id)
        except _ActorDied as e:
            died = e
        except QueueClosed:
            pass
        respawn = shrink = False
        with self._dispatch_lock:
            # the live-actor count is maintained under THIS lock (never
            # inferred from thread liveness): two actors dying at once
            # serialize here, so the second one to arrive sees an empty
            # fleet and goes fatal instead of both "shrinking" to zero
            self._active_actors -= 1
            if died is not None and not self._stop.is_set():
                self._restarts += 1
                if self._restarts <= self._max_actor_restarts:
                    respawn = True
                elif self._elastic and self._active_actors > 0:
                    # elastic membership: restarts are exhausted but the
                    # fleet still has live members — SHRINK instead of
                    # killing the run. The dead actor's spec is already
                    # requeued; a survivor regenerates it identically.
                    shrink = True
                else:
                    self._fatal = died.__cause__ or died
        if respawn:
            if self.metrics is not None:
                self.metrics.inc("async/actor_restarts")
            self._spawn(actor_id)
        elif shrink:
            if self.metrics is not None:
                self.metrics.inc("async/fleet_shrinks")
            logger.warning(
                f"async_rl: actor {actor_id} died with restarts "
                "exhausted; fleet shrinks and survivors take over its "
                "chunks"
            )

    def _spawn(self, actor_id: int) -> None:
        thread = threading.Thread(
            target=self._actor_main,
            args=(actor_id,),
            name=f"trlx-async-actor-{actor_id}",
            daemon=True,
        )
        with self._dispatch_lock:
            self._threads.append(thread)
            self._active_actors += 1
        thread.start()

    def _ensure_started(self) -> None:
        if self._started or not self._spawn_actors:
            return
        self._started = True
        for actor_id in range(self.num_actors):
            self._spawn(actor_id)

    # ------------------------------------------------------------------
    # learner side (single thread)
    # ------------------------------------------------------------------

    def on_update(self, params: Any, version: int) -> None:
        """Called by the trainer after every optimizer update: advance the
        version clock and publish (thinned by the channel's sync_every)."""
        self.version = int(version)
        self.channel.publish(params, version)

    def _consuming_collection(self) -> int:
        """The collection index the NEXT consumed chunk belongs to — drives
        the gate's collection-scoped announcements."""
        return self._next_finalize // self.chunks_per_collection + 1

    def begin_collection(self) -> None:
        """Drain is about to start: force-publish the CURRENT params at the
        current version and announce that this collection is being consumed
        NOW. This heals dropped publishes and over-estimated phase targets
        (the gate can never deadlock), and in the ``max_staleness: 0`` case
        hands actors exactly the params this collection will be consumed
        under."""
        self.channel.publish(self._trainer.state.params, self.version, force=True)
        self.channel.announce(self.version, self._consuming_collection())
        self._col_stats = {"chunks": 0, "staleness_sum": 0.0, "staleness_max": 0.0,
                           "wait_s": 0.0}
        self._ensure_started()

    def end_collection(self) -> None:
        """Drain finished: announce the NEXT collection's consumption point
        — the end of the upcoming learn phase. Actors may not start that
        collection's chunks any earlier (production never runs more than
        one collection ahead), and its chunks gate on this target."""
        self.channel.announce(
            self.version + self.updates_per_phase, self._consuming_collection()
        )

    def _check_fatal(self) -> None:
        with self._dispatch_lock:
            fatal = self._fatal
        if fatal is not None:
            self.close()
            raise fatal

    def next_chunk(self) -> ExperienceChunk:
        """The next chunk in strict index order (blocks; reorder buffer
        absorbs multi-actor completion races). Records staleness at
        consumption."""
        indexed_get = hasattr(self.queue, "committed_indices")  # file spool
        t0 = time.perf_counter()
        while self._next_finalize not in self._reorder:
            self._check_fatal()
            # top-up heal: empty-response rows can push a drain past its
            # estimated chunk count into the next collection's index range —
            # announce that consumption has reached that collection at the
            # CURRENT version so the gate frees the needed chunk (a no-op
            # whenever the normal begin/end announcements already cover it)
            self.channel.announce(self.version, self._consuming_collection())
            try:
                if indexed_get:
                    chunk = self.queue.get(
                        self._next_finalize, timeout=self._chunk_timeout_s
                    )
                else:
                    chunk = self.queue.get(timeout=1.0)
            except TimeoutError:
                if indexed_get:
                    raise
                continue  # thread mode: loop to re-check actor failures
            self._reorder[chunk.index] = chunk
        self._col_stats["wait_s"] += time.perf_counter() - t0
        chunk = self._reorder.pop(self._next_finalize)
        with self._dispatch_lock:
            self._inflight_specs.pop(self._next_finalize, None)
        self._next_finalize += 1
        if hasattr(self.queue, "note_finalized"):
            # collective transport: the finalize floor widens the fleet's
            # production window and prunes remote spec caches
            self.queue.note_finalized(self._next_finalize)
        staleness = float(max(self.version - chunk.version, 0))
        self._col_stats["chunks"] += 1
        self._col_stats["staleness_sum"] += staleness
        self._col_stats["staleness_max"] = max(
            self._col_stats["staleness_max"], staleness
        )
        if self.metrics is not None:
            self.metrics.observe("async/staleness", staleness)
        return chunk

    def collection_stats(self) -> Dict[str, float]:
        """The async/* gauges of the collection just drained."""
        col = self._col_stats
        n = max(col["chunks"], 1)
        with self._dispatch_lock:
            idle = self._idle_s - self._win0[0]
            busy = self._busy_s - self._win0[1]
            self._win0 = (self._idle_s, self._busy_s)
        stats: Dict[str, float] = {}
        stats["async/chunks"] = float(col["chunks"])
        stats["async/staleness_mean"] = col["staleness_sum"] / n
        stats["async/staleness_max"] = col["staleness_max"]
        stats["async/learner_wait_s"] = col["wait_s"]
        stats["async/queue_depth"] = float(self.queue.depth)
        if idle + busy > 0:
            stats["async/actor_idle_frac"] = idle / (idle + busy)
        if self._transport is not None:
            # fleet transport gauges (docs/ASYNC_RL.md "Transports"):
            # fleet size, learner publish egress, ack-measured tree latency
            stats.update(self._transport.window_stats())
        return stats

    def fleet_size(self) -> Optional[int]:
        """Live collective-fleet member count (``None`` off-fleet) — rides
        the cluster telemetry beat as ``cluster/fleet_size``."""
        if self._transport is None:
            return None
        return self._transport.fleet_size()

    def close(self) -> None:
        """Stop actors, wake anything blocked, join threads. Idempotent."""
        self._stop.set()
        try:
            self.channel.close()
        except Exception:  # pragma: no cover - defensive
            pass
        try:
            self.queue.close()
        except Exception:  # pragma: no cover - defensive
            pass
        with self._dispatch_lock:
            threads = list(self._threads)
        for thread in threads:
            thread.join(timeout=10)
        leaked = [t.name for t in threads if t.is_alive()]
        if leaked:  # pragma: no cover - requires a wedged actor
            # a worker stuck past the join deadline is exactly what the
            # tests' leaked-thread sentinel fails on (docs/TESTING.md) —
            # name it loudly in production too instead of leaking silently
            logger.warning(
                f"async_rl: actor thread(s) {leaked} did not join within "
                "10s — wedged in generation or a host call; they are daemon "
                "threads and die with the process, but this run leaked them"
            )
