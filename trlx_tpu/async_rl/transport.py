"""Collective actor-fleet transport: param-dissemination tree, sharded
experience queue, elastic membership (``async_rl.transport: collective``).

The PR-9 process transport is a filesystem spool and an atomic weights
file: per publish the learner rewrites the FULL param tree as an npz and
every actor polls ``MANIFEST.json`` on a 20 ms loop — fine for 2
processes, absurd for a pod (RLAX, arXiv 2512.06392, disseminates params
as a tree over collectives; Podracer, arXiv 2104.06272, pairs learner and
actor meshes that exchange weights and trajectories entirely in-fabric).
This module moves the fleet onto a message fabric with three pieces:

**Param-dissemination tree.** The learner (the fleet *root*) publishes
versioned param **deltas**: each leaf is digested (blake2b over
bytes+dtype+shape) and only leaves the update actually changed ship —
frozen layers (``model.num_layers_unfrozen``) never move after the first
snapshot. Deltas fan out over a configurable-``fanout`` tree: the root
sends to its direct children only; every actor relays to the children the
tree layout assigns it, so the learner's egress is O(fanout), not
O(fleet). Joiners bootstrap from a full snapshot in their WELCOME; a
member whose delta base mismatches (it missed a publish — e.g. it joined
mid-publish or its parent died) requests a resync and receives a full
snapshot — the tree self-heals, never deadlocks. The
``publish/announce/fetch/ready`` staleness-gate contract of
:class:`~trlx_tpu.async_rl.channel.WeightChannel` is kept verbatim, so
``max_staleness: 0`` remains bit-identical to the alternating loop.

**Sharded experience queue.** Chunk *headers* (index, version, producer)
travel down the same tree as the params — every member sees global commit
state — while chunk *payloads* move exactly once, point-to-point over the
producing actor's link to the learner. The learner's ordered drain and
requeue-on-actor-death semantics are unchanged: the
:class:`CollectiveExperienceQueue` facade hands the
:class:`~trlx_tpu.async_rl.runtime.AsyncCollector` arrival-ordered chunks
and its reorder buffer enforces strict index order.

**Elastic membership.** Actors join (HELLO → WELCOME with snapshot + tree
position) and leave (LEAVE, or link EOF on death) mid-run; liveness rides
the messages the fleet already exchanges — work requests, chunk commits,
delta acks — so membership adds **zero new sync points** (the learner-side
fleet gauges additionally ride the PR-8 telemetry allgather's packed
vector, see ``observability/distributed.py``). A departed member's leased
chunk indices requeue onto survivors, which regenerate the identical
specs (the chunk stream is seed-derived, PR-7-style deterministic
regeneration), so a fleet that shrinks mid-run still produces a store
bit-identical to serial at ``max_staleness: 0``.

Fabric choice, stated honestly: host links are stdlib
``multiprocessing.connection`` TCP (message-framed, authenticated) — NOT
the gloo allgather the learner's SPMD ranks use. gloo/jax collectives fix
the world size at initialization and barrier every participant, which is
exactly wrong for a fleet whose membership changes mid-run and whose
members run heterogeneous programs. The tree/relay layer here is
fabric-agnostic; on a TPU pod the intra-slice hop becomes a device
collective and this host tree carries only the inter-slice edges.

Bootstrap discovery (process mode) is the single remaining file:
``ENDPOINT.json`` under ``async_rl.root_dir`` names the root's address and
auth key. All params, chunks, and membership move in-fabric.
"""

import hashlib
import json
import os
import pickle
import threading
import time
from multiprocessing.connection import Client, Listener
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from trlx_tpu.async_rl.queue import (
    ExperienceChunk,
    QueueClosed,
    _atomic_write_json,
)
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

__all__ = [
    "CollectiveExperienceQueue",
    "CollectiveWeightChannel",
    "FleetActorClient",
    "FleetCoordinator",
    "read_endpoint",
    "tree_parent_slot",
    "write_endpoint",
]

ENDPOINT_FILE = "ENDPOINT.json"


# ---------------------------------------------------------------------------
# tree layout + wire helpers
# ---------------------------------------------------------------------------


def tree_parent_slot(slot: int, fanout: int) -> Optional[int]:
    """Parent of actor ``slot`` in the dissemination tree (``None`` = the
    learner root). Slots are assigned in join order and form a ``fanout``-ary
    heap rooted at the learner: actor slot ``s`` is heap node ``s + 1``, so
    its parent node is ``s // fanout`` — node 0 is the root, node ``p >= 1``
    is actor slot ``p - 1``. Vacant slots are never reused; when a member
    dies, the root takes over its orphaned children's tree edges directly
    (their control links — see ``FleetCoordinator._direct_links``)."""
    parent_node = slot // max(1, int(fanout))
    return None if parent_node == 0 else parent_node - 1


def _encode_delta(pairs: List[Tuple[int, np.ndarray]]) -> bytes:
    """Serialize ``(leaf_index, array)`` pairs. Pickle keeps exact dtypes
    (bf16 included — ml_dtypes registers with numpy), so a delta round-trip
    is bit-exact; the blob length is the measured ``async/publish_bytes``."""
    return pickle.dumps(pairs, protocol=4)


def _decode_delta(blob: bytes) -> List[Tuple[int, np.ndarray]]:
    return pickle.loads(blob)


def _leaf_digest(arr: np.ndarray) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(np.ascontiguousarray(arr).tobytes())
    return h.digest()


def _host_leaves(params: Any) -> List[np.ndarray]:
    import jax

    return [np.asarray(leaf) for leaf in jax.tree_util.tree_leaves(jax.device_get(params))]


def _assemble(leaves: List[np.ndarray], template: Any) -> Any:
    """Leaves → ``template``'s tree structure/dtypes (the
    :meth:`FileWeightChannel.fetch` restore contract)."""
    if template is None:
        return list(leaves)
    import jax

    treedef = jax.tree_util.tree_structure(template)
    tleaves = jax.tree_util.tree_leaves(template)
    cast = [
        np.asarray(leaf).astype(t.dtype) if hasattr(t, "dtype") else leaf
        for leaf, t in zip(leaves, tleaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, cast)


def write_endpoint(root_dir: str, address: Tuple[str, int], authkey: bytes) -> str:
    """Atomically publish the root's fabric endpoint for process-mode
    actors — the ONLY file the collective transport touches (discovery;
    everything else moves in-fabric)."""
    os.makedirs(root_dir, exist_ok=True)
    path = os.path.join(root_dir, ENDPOINT_FILE)
    _atomic_write_json(
        path, {"host": address[0], "port": address[1], "authkey": authkey.hex()}
    )
    return path


def read_endpoint(
    root_dir: str, timeout_s: float = 60.0, poll_interval_s: float = 0.05
) -> Tuple[Tuple[str, int], bytes]:
    """Wait for the root's endpoint file (the learner may start second)."""
    path = os.path.join(root_dir, ENDPOINT_FILE)
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            with open(path) as f:
                data = json.load(f)
            return (data["host"], int(data["port"])), bytes.fromhex(data["authkey"])
        except (OSError, ValueError, KeyError):
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no fleet endpoint at {path} after {timeout_s:.0f}s — "
                    "is the learner running with async_rl.transport: collective?"
                )
            time.sleep(poll_interval_s)


class _Link:
    """One fabric connection with serialized sends (broadcast and reply
    paths write concurrently from different threads)."""

    def __init__(self, conn):
        self.conn = conn
        self._send_lock = threading.Lock()

    def send(self, msg) -> None:
        with self._send_lock:
            self.conn.send(msg)

    def recv(self, should_stop: Optional[Callable[[], bool]] = None):
        """Blocking receive. With ``should_stop``, polls in short slices so
        a locally-initiated shutdown terminates the loop promptly — closing
        a socket fd does NOT wake a peer thread blocked in ``read`` on
        Linux, only remote EOF does, so every receive loop must be able to
        notice its own side shutting down. Returns ``None`` on stop."""
        if should_stop is None:
            return self.conn.recv()
        while True:
            if should_stop():
                return None
            if self.conn.poll(0.1):
                return self.conn.recv()

    def close(self) -> None:
        try:
            self.conn.close()
        except OSError:  # pragma: no cover - already torn down
            pass


def _listener_timeout(listener: Listener, seconds: float) -> None:
    """Give a Listener's accept a timeout so its accept loop can observe a
    shutdown flag: close() does not wake a thread blocked in ``accept``.
    Reaches one level into multiprocessing internals (stable since 2.x);
    degrades to the dummy-wake-free blocking accept if they move."""
    try:
        listener._listener._socket.settimeout(seconds)
    except AttributeError:  # pragma: no cover - stdlib internals moved
        pass


class _Member:
    """Coordinator-side record of one fleet member."""

    def __init__(self, member_id: int, slot: int, link: _Link, info: Dict[str, Any]):
        self.id = member_id
        self.slot = slot
        self.link = link  # control link (work, chunks, acks, beats)
        self.info = info
        self.last_seen = time.perf_counter()


class FleetCoordinator:
    """The learner-side fleet root: membership, the dissemination tree,
    chunk arrival, and work leasing. Facades
    (:class:`CollectiveWeightChannel` / :class:`CollectiveExperienceQueue`)
    adapt it to the channel/queue contracts the
    :class:`~trlx_tpu.async_rl.runtime.AsyncCollector` consumes."""

    def __init__(
        self,
        fanout: int = 2,
        bind_host: str = "127.0.0.1",
        capacity: int = 8,
        plan: Any = None,
        metrics: Any = None,
        sync_every: int = 1,
        actor_timeout_s: float = 300.0,
        authkey: Optional[bytes] = None,
    ):
        self.fanout = max(1, int(fanout))
        self.capacity = max(1, int(capacity))
        self._plan = plan
        self.metrics = metrics
        self.sync_every = max(1, int(sync_every))
        self.actor_timeout_s = float(actor_timeout_s)
        self.authkey = authkey if authkey is not None else os.urandom(16)
        self._listener = Listener((bind_host, 0), authkey=self.authkey)
        self.address: Tuple[str, int] = self._listener.address

        # reentrant: helper methods (tree-edge enumeration, work
        # assignment, the staleness gate) take the lock themselves and are
        # also called from sections that already hold it
        self._cond = threading.Condition(threading.RLock())
        self._members: Dict[int, _Member] = {}  # guarded-by: _cond
        self._slots: Dict[int, Optional[int]] = {}  # guarded-by: _cond
        self._next_member_id = 0  # guarded-by: _cond
        self._closed = False  # guarded-by: _cond
        # param state (host leaves; one copy, same footprint as the old npz)
        self._leaves: Optional[List[np.ndarray]] = None  # guarded-by: _cond
        self._digests: List[bytes] = []  # guarded-by: _cond
        self._version = -1  # guarded-by: _cond
        self._target = 0  # guarded-by: _cond
        self._announced_col = 0  # guarded-by: _cond
        # experience state
        self._arrived: Dict[int, ExperienceChunk] = {}  # guarded-by: _cond
        self._popped: set = set()  # guarded-by: _cond (handed to the drain)
        self._cursor = 0  # guarded-by: _cond (learner finalize floor)
        # work leasing (process-mode actors; thread actors dispatch in-proc)
        self._next_index = 0  # guarded-by: _cond
        self._pending: List[int] = []  # guarded-by: _cond (requeued, sorted)
        self._leases: Dict[int, int] = {}  # guarded-by: _cond (index -> member)
        self._work_waiters: List[int] = []  # guarded-by: _cond (member ids, FIFO)
        # dissemination accounting (ack-based latency on the learner clock)
        self._await_acks: Dict[int, set] = {}  # guarded-by: _cond
        self._publish_t0: Dict[int, float] = {}  # guarded-by: _cond
        self._win_bytes = 0  # guarded-by: _cond
        self._win_latencies: List[float] = []  # guarded-by: _cond
        # stall guard: "no member ever joined" counts as empty from t0
        self._empty_since: Optional[float] = time.perf_counter()  # guarded-by: _cond

        self._threads: List[threading.Thread] = []  # guarded-by: _cond
        _listener_timeout(self._listener, 0.2)
        accept = threading.Thread(
            target=self._accept_loop, name="trlx-fleet-accept", daemon=True
        )
        self._threads.append(accept)
        accept.start()

    def _is_closed(self) -> bool:
        with self._cond:
            return self._closed

    # -- membership ------------------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            if self._is_closed():
                return
            try:
                conn = self._listener.accept()
            except Exception:
                # accept timeout (the shutdown-observation beat), listener
                # closed, or a failed auth handshake; only shutdown ends
                # the loop
                continue
            try:
                if not conn.poll(5):
                    conn.close()
                    continue
                first = conn.recv()
            except (EOFError, OSError, TypeError):
                conn.close()
                continue
            if not isinstance(first, tuple) or not first:
                conn.close()
                continue
            if first[0] == "hello":
                self._register(conn, first[1])
            else:
                conn.close()

    def _register(self, conn, info: Dict[str, Any]) -> None:
        link = _Link(conn)
        with self._cond:
            if self._closed:
                link.send(("done",))
                link.close()
                return
            member_id = self._next_member_id
            self._next_member_id += 1
            slot = len(self._slots)
            self._slots[slot] = member_id
            parent = tree_parent_slot(slot, self.fanout)
            parent_addr = None
            if parent is not None:
                pid = self._slots.get(parent)
                pm = self._members.get(pid) if pid is not None else None
                if pm is not None and pm.info.get("listen"):
                    parent_addr = tuple(pm.info["listen"])
            leaves = self._leaves  # immutable list; swapped whole by publish
            state = {
                "version": self._version,
                "target": self._target,
                "collection": self._announced_col,
                "cursor": self._cursor,
            }
        # snapshot pickling happens OUTSIDE the lock (see publish). A
        # publish landing in between leaves the joiner one version behind
        # its first delta's base — the documented gap-detect → resync heal.
        snapshot = None
        if leaves is not None:
            snapshot = _encode_delta(list(enumerate(leaves)))
        welcome = (
            "welcome",
            {
                "member_id": member_id,
                "slot": slot,
                "parent": parent_addr,
                "params": snapshot,
                "capacity": self.capacity,
                **state,
            },
        )
        member = _Member(member_id, slot, link, info)
        if snapshot is not None:
            with self._cond:
                self._win_bytes += len(snapshot)  # join bootstrap egress
        # the welcome must be this link's FIRST message: the member is
        # inserted (and so becomes a broadcast target) only after it ships
        try:
            link.send(welcome)
        except (OSError, ValueError):
            link.close()
            return
        with self._cond:
            self._members[member_id] = member
            self._empty_since = None
            thread = threading.Thread(
                target=self._member_loop,
                args=(member,),
                name=f"trlx-fleet-peer-{member_id}",
                daemon=True,
            )
            self._threads.append(thread)
            self._cond.notify_all()
        thread.start()
        if self.metrics is not None:
            self.metrics.inc("async/fleet_joins")
        logger.info(
            f"fleet: member {member_id} joined (slot {slot}, "
            f"parent {'root' if parent_addr is None else parent_addr})"
        )

    def _member_loop(self, member: _Member) -> None:
        graceful = False
        try:
            while True:
                try:
                    msg = member.link.recv(should_stop=self._is_closed)
                except (EOFError, OSError, TypeError, pickle.UnpicklingError):
                    break
                if msg is None:
                    return  # local shutdown; close() handles the fleet
                member.last_seen = time.perf_counter()
                kind = msg[0]
                if kind == "work":
                    with self._cond:
                        self._work_waiters.append(member.id)
                        sends = self._maybe_assign()
                    self._dispatch(sends)
                elif kind == "chunk":
                    self._on_chunk(member, msg[1], msg[2])
                elif kind == "ack":
                    self._on_ack(member.id, int(msg[1]))
                elif kind == "resync":
                    self._send_snapshot(member)
                elif kind == "beat":
                    pass  # liveness already stamped above
                elif kind == "leave":
                    graceful = True
                    break
        finally:
            self._on_member_dead(member, graceful=graceful)

    def _on_member_dead(self, member: _Member, graceful: bool) -> None:
        with self._cond:
            if self._members.pop(member.id, None) is None:
                return  # already reaped
            self._slots[member.slot] = None
            self._work_waiters = [w for w in self._work_waiters if w != member.id]
            requeued = sorted(
                idx
                for idx, owner in self._leases.items()
                if owner == member.id and idx not in self._arrived
                and idx not in self._popped and idx >= self._cursor
            )
            for idx in requeued:
                del self._leases[idx]
            self._pending = sorted(set(self._pending).union(requeued))
            for acks in self._await_acks.values():
                acks.discard(member.id)
            self._check_acks_locked()
            if not self._members:
                self._empty_since = time.perf_counter()
            closed = self._closed
            sends = self._maybe_assign()
            self._cond.notify_all()
        member.link.close()
        self._dispatch(sends)
        if closed:
            return
        if not graceful and self.metrics is not None:
            self.metrics.inc("async/fleet_shrinks")
        if requeued and self.metrics is not None:
            self.metrics.inc("async/requeued_chunks", len(requeued))
        detail = (
            f"fleet: member {member.id} {'left' if graceful else 'died'}"
            + (f"; requeued chunks {requeued} onto survivors" if requeued else "")
        )
        if graceful:
            logger.info(detail)
        else:
            logger.warning(detail)

    def fleet_size(self) -> int:
        with self._cond:
            return len(self._members)

    def pending_acks(self) -> int:
        """Publishes not yet acked by every live member (bench/test hook:
        drain this to 0 before reading the latency window)."""
        with self._cond:
            return len(self._await_acks)

    def members_snapshot(self) -> List[Dict[str, Any]]:
        """Diagnostic view: (id, slot, mesh descriptor) per live member."""
        with self._cond:
            members = sorted(self._members.values(), key=lambda m: m.id)
            return [
                {"id": m.id, "slot": m.slot, "mesh": m.info.get("mesh")}
                for m in members
            ]

    # -- param dissemination --------------------------------------------

    def _direct_links(self) -> List[_Link]:
        # the tree's root edges: members whose parent slot is the root or
        # is vacant (the parent died — the orphan's future tree traffic
        # arrives on its control link; its one-time state catch-up is the
        # resync snapshot). _cond is reentrant: most callers already hold
        # it to keep edge choice atomic with the state they are about to
        # send.
        with self._cond:
            out = []
            for member in sorted(self._members.values(), key=lambda m: m.slot):
                parent = tree_parent_slot(member.slot, self.fanout)
                if parent is None:
                    out.append(member.link)
                    continue
                pid = self._slots.get(parent)
                if pid is None or pid not in self._members:
                    out.append(member.link)  # orphaned: root takes over
            return out

    def _dispatch(self, sends: List[Tuple[_Link, tuple]]) -> None:
        for link, msg in sends:
            try:
                link.send(msg)
            except (OSError, ValueError):
                pass  # the member's recv loop will reap it

    def _broadcast(self, msg: tuple) -> None:
        with self._cond:
            links = self._direct_links()
        self._dispatch([(link, msg) for link in links])

    def publish(self, params: Any, version: int, force: bool = False) -> None:
        """Publish ``params`` as ``version`` down the tree as a delta of
        changed leaves (unchanged-leaf skipping). Same thinning/force/drop
        semantics as :meth:`WeightChannel.publish`."""
        if not force and version % self.sync_every != 0:
            return
        with self._cond:
            if version <= self._version:
                return  # checked before the device_get below (real work)
        if self._plan is not None and self._plan.poll("weight_sync_drop", version=version):
            if self.metrics is not None:
                self.metrics.inc("async/weight_sync_drops")
            return
        leaves = _host_leaves(params)
        digests = [_leaf_digest(leaf) for leaf in leaves]
        with self._cond:
            if version <= self._version:
                return  # lost a publish race while hashing
            if self._digests and len(self._digests) == len(digests):
                changed = [
                    i for i, d in enumerate(digests) if d != self._digests[i]
                ]
                full = False
            else:
                changed = list(range(len(leaves)))
                full = True
            base = self._version
            self._leaves = leaves
            self._digests = digests
            self._version = version
        # serialize OUTSIDE the lock: a model-scale pickle takes real time
        # and _cond also guards chunk arrival / work assignment / the
        # learner's drain — holding it here would stall the whole control
        # plane. The version/leaf state above was already swapped
        # atomically; `leaves` is immutable from here on.
        blob = _encode_delta([(i, leaves[i]) for i in changed])
        header = {
            "version": version,
            "base": base,
            "full": full,
            "n_changed": len(changed),
            "n_leaves": len(leaves),
        }
        with self._cond:
            links = self._direct_links()
            live = set(self._members)
            if live:
                self._await_acks[version] = live
                self._publish_t0[version] = time.perf_counter()
            self._win_bytes += len(blob) * len(links)
            self._cond.notify_all()
        self._dispatch([(link, ("params", header, blob)) for link in links])
        if self.metrics is not None:
            self.metrics.inc("async/weight_syncs")
            self.metrics.observe("async/publish_bytes", float(len(blob)))

    def _send_snapshot(self, member: _Member) -> None:
        with self._cond:
            leaves = self._leaves  # immutable; swapped whole by publish
            version = self._version
        if leaves is None:
            return
        blob = _encode_delta(list(enumerate(leaves)))  # outside the lock
        header = {
            "version": version,
            "base": -1,
            "full": True,
            "n_changed": len(leaves),
            "n_leaves": len(leaves),
        }
        with self._cond:
            self._win_bytes += len(blob)
        self._dispatch([(member.link, ("params", header, blob))])

    def _on_ack(self, member_id: int, version: int) -> None:
        with self._cond:
            # an ack at version v covers every outstanding publish <= v
            # (a resync snapshot jumps a member past intermediate deltas)
            for v, acks in self._await_acks.items():
                if v <= version:
                    acks.discard(member_id)
            self._check_acks_locked()

    def _check_acks_locked(self) -> None:
        with self._cond:  # reentrant: ack/death handlers already hold it
            done = [v for v, acks in self._await_acks.items() if not acks]
            for version in done:
                del self._await_acks[version]
                t0 = self._publish_t0.pop(version, None)
                if t0 is not None:
                    self._win_latencies.append(time.perf_counter() - t0)

    def announce(self, target: int, collection: int) -> None:
        """Same monotonic-collection / min-target semantics as
        :meth:`WeightChannel.announce`; no-op announcements (the drain-time
        heal path) skip the broadcast."""
        with self._cond:
            if int(collection) > self._announced_col:
                self._announced_col = int(collection)
                self._target = int(target)
            elif int(collection) == self._announced_col:
                new = min(self._target, int(target))
                if new == self._target:
                    return
                self._target = new
            else:
                return
            target, collection = self._target, self._announced_col
            cursor = self._cursor
        self._broadcast(("announce", target, collection, cursor))

    # -- experience arrival + leasing -----------------------------------

    def _on_chunk(self, member: _Member, header: Dict[str, Any], blob: bytes) -> None:
        index = int(header["index"])
        payload = pickle.loads(blob)
        with self._cond:
            if (
                index < self._cursor
                or index in self._arrived
                or index in self._popped
            ):
                return  # stale duplicate (requeue race already resolved)
            self._arrived[index] = ExperienceChunk(
                index=index, version=int(header["version"]), payload=payload
            )
            self._leases.pop(index, None)
            cursor = self._cursor
            self._cond.notify_all()
        if self.metrics is not None:
            self.metrics.inc("async/chunks")
        # the header rides the tree: every member sees global commit state
        # (spec-cache pruning + join-time dedup); the payload moved once,
        # point-to-point, on the producer's own link
        self._broadcast(
            ("header", {"index": index, "version": int(header["version"]),
                        "producer": member.id, "cursor": cursor})
        )

    def _maybe_assign(self) -> List[Tuple[_Link, tuple]]:
        # returns the (link, message) sends to dispatch AFTER the caller
        # releases the lock (_cond is reentrant; callers hold it to keep
        # assignment atomic with the membership change that triggered it)
        with self._cond:
            sends: List[Tuple[_Link, tuple]] = []
            while self._work_waiters:
                if self._closed:
                    member = self._members.get(self._work_waiters.pop(0))
                    if member is not None:
                        sends.append((member.link, ("done",)))
                    continue
                if self._pending:
                    index = self._pending[0]
                    fresh = False
                elif self._next_index - self._cursor < self.capacity:
                    index = self._next_index
                    fresh = True
                else:
                    break  # production window full: leave waiters queued
                member = self._members.get(self._work_waiters[0])
                if member is None:
                    self._work_waiters.pop(0)
                    continue
                self._work_waiters.pop(0)
                if fresh:
                    self._next_index += 1
                else:
                    self._pending.pop(0)
                self._leases[index] = member.id
                sends.append((member.link, ("assign", index)))
            return sends

    def note_finalized(self, cursor: int) -> None:
        """The learner's finalize floor advanced: widen the production
        window, drop consumed state, and tell the fleet (cursor rides the
        header/announce traffic — actors prune their spec caches on it)."""
        with self._cond:
            if cursor <= self._cursor:
                return
            self._cursor = cursor
            self._popped = {i for i in self._popped if i >= cursor}
            sends = self._maybe_assign()
            links = self._direct_links()
        self._dispatch(sends)
        self._dispatch([(link, ("cursor", cursor)) for link in links])

    def get(self, timeout: Optional[float] = None) -> ExperienceChunk:
        """Arrival-ordered pop (lowest arrived index first); the
        collector's reorder buffer enforces strict finalize order."""
        deadline = None if timeout is None else time.monotonic() + timeout
        last_heal = time.monotonic()
        while True:
            with self._cond:
                while not self._arrived:
                    if self._closed:
                        raise QueueClosed("fleet transport closed")
                    if (
                        self._empty_since is not None
                        and time.perf_counter() - self._empty_since
                        > self.actor_timeout_s
                    ):
                        raise RuntimeError(
                            f"fleet empty for {self.actor_timeout_s:.0f}s "
                            "with chunks outstanding — every actor died or "
                            "left and no replacement joined"
                        )
                    remaining = (
                        None if deadline is None else deadline - time.monotonic()
                    )
                    if remaining is not None and remaining <= 0:
                        raise TimeoutError("fleet queue get timed out")
                    if time.monotonic() - last_heal > 0.5:
                        break  # heal beat: re-sync outside the lock
                    self._cond.wait(
                        timeout=0.1 if remaining is None else min(remaining, 0.1)
                    )
                else:
                    index = min(self._arrived)
                    self._popped.add(index)
                    self._leases.pop(index, None)
                    return self._arrived.pop(index)
                target, col, cursor, version = (
                    self._target, self._announced_col, self._cursor,
                    self._version,
                )
            # the learner is starved: broadcast a sync beat so a member
            # that missed a tree message (joined mid-publish, relay parent
            # died mid-send) detects the gap and resyncs — the collective
            # analogue of the file channel's manifest poll, but only
            # active while the drain is actually waiting
            self._broadcast(("sync", version, target, col, cursor))
            last_heal = time.monotonic()

    @property
    def depth(self) -> int:
        with self._cond:
            return len(self._arrived)

    # -- stats + shutdown ------------------------------------------------

    def window_stats(self) -> Dict[str, float]:
        """Per-collection transport gauges; resets the window."""
        stats: Dict[str, float] = {}
        with self._cond:
            stats["async/fleet_size"] = float(len(self._members))
            stats["async/publish_bytes"] = float(self._win_bytes)
            if self._win_latencies:
                stats["async/dissemination_latency_s"] = float(
                    np.mean(self._win_latencies)
                )
            self._win_bytes = 0
            self._win_latencies = []
        return stats

    def close(self) -> None:
        with self._cond:
            if self._closed:
                already = True
            else:
                already = False
                self._closed = True
            members = list(self._members.values())
            self._cond.notify_all()
        if already:
            return
        for member in members:
            try:
                member.link.send(("done",))
            except (OSError, ValueError):
                pass
        try:
            self._listener.close()
        except OSError:  # pragma: no cover - already torn down
            pass
        for member in members:
            member.link.close()
        with self._cond:
            threads = list(self._threads)
        me = threading.current_thread()
        for thread in threads:
            if thread is not me:
                thread.join(timeout=10)
        leaked = [t.name for t in threads if t is not me and t.is_alive()]
        if leaked:  # pragma: no cover - requires a wedged link
            logger.warning(
                f"fleet: transport thread(s) {leaked} did not join within 10s"
            )


class CollectiveWeightChannel:
    """Learner-side :class:`WeightChannel` facade over the coordinator
    (``publish``/``announce``/``close`` — the learner never fetches)."""

    def __init__(self, coordinator: FleetCoordinator):
        self._coord = coordinator

    def publish(self, params: Any, version: int, force: bool = False) -> None:
        self._coord.publish(params, version, force=force)

    def announce(self, target: int, collection: int) -> None:
        self._coord.announce(target, collection)

    def close(self) -> None:
        self._coord.close()


class CollectiveExperienceQueue:
    """Learner-side :class:`ExperienceQueue` facade over the coordinator
    (arrival-ordered ``get``; producers commit through their own links)."""

    def __init__(self, coordinator: FleetCoordinator):
        self._coord = coordinator

    def get(self, timeout: Optional[float] = None) -> ExperienceChunk:
        return self._coord.get(timeout=timeout)

    def note_finalized(self, cursor: int) -> None:
        self._coord.note_finalized(cursor)

    @property
    def depth(self) -> int:
        return self._coord.depth

    def close(self) -> None:
        self._coord.close()


# ---------------------------------------------------------------------------
# actor-side fleet member
# ---------------------------------------------------------------------------


class FleetActorClient:
    """One fleet member: joins the tree, receives/relays param deltas,
    gates on staleness, leases work, and commits chunk payloads
    point-to-point. Exposes the actor half of BOTH transport seams — the
    :class:`WeightChannel` contract (``wait_ready``/``ready``/``fetch``)
    and the queue's producer contract (``put``)."""

    def __init__(
        self,
        address: Tuple[str, int],
        authkey: bytes,
        template: Any = None,
        mesh_descriptor: Optional[Dict[str, Any]] = None,
        bind_host: str = "127.0.0.1",
        relay: bool = True,
    ):
        self._template = template
        self._cond = threading.Condition(threading.RLock())
        self._closed = False  # guarded-by: _cond
        self._leaves: Optional[List[np.ndarray]] = None  # guarded-by: _cond
        self._version = -1  # guarded-by: _cond
        self._target = 0  # guarded-by: _cond
        self._announced_col = 0  # guarded-by: _cond
        self._cursor = 0  # guarded-by: _cond
        self._committed: set = set()  # guarded-by: _cond (header view)
        self._assigned: List[int] = []  # guarded-by: _cond
        self._params_cache: Tuple[int, Any] = (-2, None)  # guarded-by: _cond
        self._children: List[_Link] = []  # guarded-by: _cond
        self._resync_sent = -1  # guarded-by: _cond
        self._threads: List[threading.Thread] = []

        self._listener: Optional[Listener] = None
        listen_addr = None
        if relay:
            self._listener = Listener((bind_host, 0), authkey=authkey)
            _listener_timeout(self._listener, 0.2)
            listen_addr = self._listener.address
        self._conn = _Link(Client(tuple(address), authkey=authkey))
        self._conn.send(
            ("hello", {"listen": listen_addr, "mesh": mesh_descriptor,
                       "pid": os.getpid()})
        )
        if not self._conn.conn.poll(30):
            raise RuntimeError("fleet join timed out waiting for WELCOME")
        welcome = self._conn.recv()
        if not (isinstance(welcome, tuple) and welcome[0] == "welcome"):
            raise RuntimeError(f"fleet join failed: unexpected reply {welcome!r}")
        info = welcome[1]
        self.member_id = int(info["member_id"])
        self.slot = int(info["slot"])
        self.capacity = int(info["capacity"])
        self._target = int(info["target"])
        self._announced_col = int(info["collection"])
        self._cursor = int(info["cursor"])
        if info["params"] is not None:
            self._leaves = [arr for _i, arr in _decode_delta(info["params"])]
            self._version = int(info["version"])

        self._feed: Optional[_Link] = None
        if info["parent"] is not None:
            self._feed = _Link(Client(tuple(info["parent"]), authkey=authkey))
            self._feed.send(("feed", self.member_id))
            feed_thread = threading.Thread(
                target=self._recv_loop,
                args=(self._feed,),
                name=f"trlx-fleet-feed-{self.member_id}",
                daemon=True,
            )
            self._threads.append(feed_thread)
            feed_thread.start()
        ctrl = threading.Thread(
            target=self._recv_loop,
            args=(self._conn,),
            name=f"trlx-fleet-client-{self.member_id}",
            daemon=True,
        )
        self._threads.append(ctrl)
        ctrl.start()
        if self._listener is not None:
            serve = threading.Thread(
                target=self._serve_loop,
                name=f"trlx-fleet-serve-{self.member_id}",
                daemon=True,
            )
            self._threads.append(serve)
            serve.start()

    # -- receive + relay -------------------------------------------------

    def _serve_loop(self) -> None:
        while True:
            if self.closed:
                return
            try:
                conn = self._listener.accept()
            except Exception:
                continue  # accept timeout (shutdown beat) or closed
            try:
                if not conn.poll(5):
                    conn.close()
                    continue
                first = conn.recv()
            except (EOFError, OSError, TypeError):
                conn.close()
                continue
            if isinstance(first, tuple) and first and first[0] == "feed":
                child = _Link(conn)
                with self._cond:
                    if self._closed:
                        conn.close()
                        continue
                    self._children.append(child)
                    state = (
                        "sync", self._version, self._target,
                        self._announced_col, self._cursor,
                    )
                # hand the new child this node's current view immediately:
                # a child that attached mid-publish gap-detects against it
                # and resyncs instead of silently running one version behind
                try:
                    child.send(state)
                except (OSError, ValueError):
                    pass
            else:
                conn.close()

    def _recv_loop(self, link: _Link) -> None:
        while True:
            try:
                msg = link.recv(should_stop=lambda: self.closed)
            except (EOFError, OSError, TypeError, pickle.UnpicklingError):
                break
            if msg is None:
                return  # local shutdown
            kind = msg[0]
            if kind == "assign":
                with self._cond:
                    self._assigned.append(int(msg[1]))
                    self._cond.notify_all()
            elif kind == "done":
                self._mark_closed()
                self._relay(msg)
                return
            else:
                self._handle_tree(msg)
        # link lost: a dead parent (feed) falls back to nothing — the
        # control link is authoritative; a dead control link closes us
        if link is self._conn:
            self._mark_closed()
        elif link is self._feed:
            self._request_resync()

    def _handle_tree(self, msg: tuple) -> None:
        kind = msg[0]
        if kind == "params":
            header, blob = msg[1], msg[2]
            version = int(header["version"])
            need_resync = False
            with self._cond:
                if version <= self._version:
                    pass  # duplicate/old (e.g. resync raced a delta): ack
                elif header["full"] or (
                    header["base"] == self._version and self._leaves is not None
                ):
                    pairs = _decode_delta(blob)
                    if header["full"]:
                        self._leaves = [arr for _i, arr in pairs]
                    else:
                        for i, arr in pairs:
                            self._leaves[i] = arr
                    self._version = version
                    self._cond.notify_all()
                else:
                    # gap: this member missed a publish (joined mid-publish
                    # or its relay parent died) — ask the root for a full
                    # snapshot instead of applying a delta onto a stale base
                    need_resync = True
            if need_resync:
                self._request_resync()
            else:
                try:
                    self._conn.send(("ack", version))
                except (OSError, ValueError):
                    pass
        elif kind == "announce":
            with self._cond:
                self._target = int(msg[1])
                self._announced_col = int(msg[2])
                self._cursor = max(self._cursor, int(msg[3]))
                self._cond.notify_all()
        elif kind == "cursor":
            with self._cond:
                self._cursor = max(self._cursor, int(msg[1]))
                self._committed = {
                    i for i in self._committed if i >= self._cursor
                }
                self._cond.notify_all()
        elif kind == "header":
            with self._cond:
                self._committed.add(int(msg[1]["index"]))
                self._cursor = max(self._cursor, int(msg[1]["cursor"]))
                self._cond.notify_all()
        elif kind == "sync":
            # learner-starved heal beat: adopt announce/cursor state and
            # detect a missed publish (request a full resync on gap)
            version = int(msg[1])
            with self._cond:
                self._target = int(msg[2])
                self._announced_col = int(msg[3])
                self._cursor = max(self._cursor, int(msg[4]))
                behind = version > self._version
                self._cond.notify_all()
            if behind:
                self._request_resync()
        self._relay(msg)

    def _relay(self, msg: tuple) -> None:
        with self._cond:
            children = list(self._children)
        for child in children:
            try:
                child.send(msg)
            except (OSError, ValueError):
                with self._cond:
                    if child in self._children:
                        self._children.remove(child)
                child.close()

    def _request_resync(self) -> None:
        with self._cond:
            if self._closed or self._resync_sent >= self._version:
                return
            self._resync_sent = self._version
        try:
            self._conn.send(("resync",))
        except (OSError, ValueError):
            pass

    def _mark_closed(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- WeightChannel contract (actor half) -----------------------------

    def _gate(self, max_staleness: int, collection: int) -> bool:
        # the WeightChannel._gate math, verbatim (_cond is reentrant: the
        # wait loops call this while already holding it)
        with self._cond:
            if self._leaves is None or collection > self._announced_col:
                return False
            if collection < self._announced_col:
                return True
            return self._target - self._version <= max_staleness

    def ready(self, max_staleness: int, collection: int = 1) -> bool:
        with self._cond:
            return self._gate(max_staleness, collection)

    def wait_ready(
        self,
        max_staleness: int,
        collection: int = 1,
        stop: Optional[threading.Event] = None,
    ) -> bool:
        with self._cond:
            while True:
                if self._closed or (stop is not None and stop.is_set()):
                    return False
                if self._gate(max_staleness, collection):
                    return True
                self._cond.wait(timeout=0.05)

    def fetch(self, template: Any = None) -> Tuple[Any, int]:
        """Newest disseminated (params, version) assembled under the
        member's template; blocks until the first snapshot/delta lands.
        Assembly is memoized per version (the CB path fetches at every
        segment boundary)."""
        template = template if template is not None else self._template
        with self._cond:
            while self._leaves is None:
                if self._closed:
                    raise RuntimeError(
                        "fleet transport closed before first publish"
                    )
                self._cond.wait(timeout=0.1)
            version = self._version
            if self._params_cache[0] == version:
                return self._params_cache[1], version
            leaves = list(self._leaves)
        params = _assemble(leaves, template)
        with self._cond:
            if self._params_cache[0] != version:
                self._params_cache = (version, params)
            return self._params_cache[1], version

    # -- queue producer contract ----------------------------------------

    def put(
        self, chunk: ExperienceChunk, stop: Optional[threading.Event] = None
    ) -> None:
        """Commit one chunk: back-pressure against the learner's finalize
        cursor (rides the tree), then ship header + payload point-to-point
        on this member's own link."""
        with self._cond:
            while chunk.index - self._cursor >= self.capacity:
                if self._closed or (stop is not None and stop.is_set()):
                    raise QueueClosed("fleet transport closed")
                self._cond.wait(timeout=0.05)
            if self._closed:
                raise QueueClosed("fleet transport closed")
        blob = pickle.dumps(chunk.payload, protocol=4)
        header = {"index": chunk.index, "version": chunk.version,
                  "nbytes": len(blob)}
        try:
            self._conn.send(("chunk", header, blob))
        except (OSError, ValueError) as e:
            raise QueueClosed(f"fleet transport lost: {e}") from e

    # -- work leasing + membership view ---------------------------------

    def request_work(self, timeout: Optional[float] = None) -> Optional[int]:
        """Lease the next chunk index (blocks; ``None`` = the run drained
        and the fleet is shutting down)."""
        try:
            self._conn.send(("work",))
        except (OSError, ValueError):
            return None
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._assigned:
                if self._closed:
                    return None
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(
                    timeout=0.1 if remaining is None else min(remaining, 0.1)
                )
            return self._assigned.pop(0)

    def cursor_view(self) -> int:
        with self._cond:
            return self._cursor

    def committed_view(self) -> set:
        with self._cond:
            return set(self._committed)

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def close(self, graceful: bool = True) -> None:
        with self._cond:
            self._closed = True
            children = list(self._children)
            self._children = []
            self._cond.notify_all()
        if graceful:
            try:
                self._conn.send(("leave",))
            except (OSError, ValueError):
                pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:  # pragma: no cover - already torn down
                pass
        self._conn.close()
        if self._feed is not None:
            self._feed.close()
        for child in children:
            child.close()
        me = threading.current_thread()
        for thread in self._threads:
            if thread is not me:
                thread.join(timeout=10)


def make_member_factory(
    coordinator: FleetCoordinator,
    template_fn: Callable[[], Any],
) -> Callable[[int], FleetActorClient]:
    """Thread-mode member factory for the
    :class:`~trlx_tpu.async_rl.runtime.AsyncCollector`: each actor thread
    joins the fleet as its own member over loopback, so the in-process
    fleet exercises the identical wire protocol as a pod's."""

    def factory(actor_id: int) -> FleetActorClient:
        from trlx_tpu.parallel.mesh import get_global_mesh, mesh_descriptor

        mesh = get_global_mesh()
        return FleetActorClient(
            coordinator.address,
            coordinator.authkey,
            template=template_fn(),
            mesh_descriptor=mesh_descriptor(mesh) if mesh is not None else None,
        )

    return factory
