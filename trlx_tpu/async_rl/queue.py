"""The staleness-bounded experience queue: actors produce version-tagged
chunks, the learner consumes them in chunk-index order.

Two transports behind one contract:

- :class:`ExperienceQueue` — in-process (thread mode): a bounded deque +
  condition variable. ``put`` blocks while full (``block`` policy) or
  evicts the head (``drop_oldest``); ``get`` blocks until a chunk lands.
- :class:`FileExperienceQueue` — cross-process (process mode): a spool
  directory of atomically-committed ``chunk_<index>.npz`` files. The
  producer back-pressures against the consumer's ``CURSOR.json``; the
  consumer waits for the next index, loads, deletes, and advances the
  cursor. A crash mid-write leaves no partial chunk (tmp + rename), and a
  respawned actor derives "what is already committed" from the directory —
  the requeue-on-actor-death mechanism.

Chunks are opaque payload dicts (host numpy arrays + scalars) tagged with
the producing actor's params ``version`` — the learner computes staleness
as ``learner_version − chunk.version`` at consumption.
"""

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import numpy as np

__all__ = [
    "ExperienceChunk",
    "ExperienceQueue",
    "FileExperienceQueue",
    "QueueClosed",
]


class QueueClosed(RuntimeError):
    """Raised by blocked producers/consumers when the queue shuts down."""


@dataclass
class ExperienceChunk:
    """One produced rollout chunk: ``index`` is the global chunk position
    (the learner finalizes strictly in index order — reward running moments
    are order-sensitive), ``version`` the params version the chunk STARTED
    under (conservative under in-flight mid-chunk updates), ``payload`` the
    trainer-defined host arrays."""

    index: int
    version: int
    payload: Dict[str, Any] = field(default_factory=dict)


class ExperienceQueue:
    """Bounded in-process chunk buffer (thread mode).

    ``policy="block"`` back-pressures producers at ``capacity``;
    ``policy="drop_oldest"`` evicts the head instead (counted on ``metrics``
    as ``async/dropped_chunks``) and reports it through ``on_drop`` — the
    collector REGENERATES the evicted chunk from its spec under fresher
    params (the learner finalizes in strict index order, so an evicted
    index must reappear or the drain would wait forever). Freshness over
    staleness, never over completeness.
    """

    def __init__(
        self,
        capacity: int,
        policy: str = "block",
        metrics: Any = None,
        on_drop: Any = None,
    ):
        if capacity < 1:
            raise ValueError(f"queue capacity must be >= 1, got {capacity}")
        if policy not in ("block", "drop_oldest"):
            raise ValueError(f"unknown queue policy '{policy}' (block | drop_oldest)")
        if policy == "drop_oldest" and on_drop is None:
            raise ValueError(
                "drop_oldest requires an on_drop callback: evicted chunk "
                "indices must be regenerated (the learner drains in strict "
                "index order)"
            )
        self.capacity = capacity
        self.policy = policy
        self.metrics = metrics
        self.on_drop = on_drop
        self._cond = threading.Condition()
        self._chunks: deque = deque()  # guarded-by: _cond
        self._closed = False  # guarded-by: _cond

    @property
    def depth(self) -> int:
        with self._cond:
            return len(self._chunks)

    def put(self, chunk: ExperienceChunk) -> None:
        dropped = None
        with self._cond:
            while self.policy == "block" and len(self._chunks) >= self.capacity:
                if self._closed:
                    raise QueueClosed("experience queue closed")
                self._cond.wait(timeout=0.1)
            if self._closed:
                raise QueueClosed("experience queue closed")
            if self.policy == "drop_oldest" and len(self._chunks) >= self.capacity:
                dropped = self._chunks.popleft()
                if self.metrics is not None:
                    self.metrics.inc("async/dropped_chunks")
            self._chunks.append(chunk)
            self._cond.notify_all()
        if dropped is not None and self.on_drop is not None:
            self.on_drop(dropped)  # outside the lock: the callback requeues

    def get(self, timeout: Optional[float] = None) -> ExperienceChunk:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._chunks:
                if self._closed:
                    raise QueueClosed("experience queue closed")
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError("experience queue get timed out")
                self._cond.wait(timeout=0.1 if remaining is None else min(remaining, 0.1))
            chunk = self._chunks.popleft()
            self._cond.notify_all()
            return chunk

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


# ---------------------------------------------------------------------------
# cross-process spool
# ---------------------------------------------------------------------------


def _atomic_write_json(path: str, payload: Dict[str, Any]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def flatten_payload(payload: Dict[str, Any], prefix: str = "") -> Dict[str, np.ndarray]:
    """A (possibly nested) payload dict as flat ``a.b`` → ndarray pairs for
    npz round-tripping. Scalars become 0-d arrays; strings are rejected
    (chunk payloads are numeric by construction). Keys containing ``"."``
    are rejected outright: the dot is the nesting separator, so a dotted
    leaf key would silently round-trip through :func:`unflatten_payload`
    as a *nested dict*, corrupting the chunk structure."""
    out: Dict[str, np.ndarray] = {}
    for key, value in payload.items():
        if "." in key:
            raise ValueError(
                f"payload key {key!r} contains '.', the flatten separator — "
                "it would unflatten into a nested dict and corrupt the "
                "chunk structure; rename the field"
            )
        name = f"{prefix}{key}"
        if isinstance(value, dict):
            out.update(flatten_payload(value, prefix=f"{name}."))
            continue
        arr = np.asarray(value)
        if arr.dtype.kind == "V":  # bf16 etc. — widen exactly for npz
            arr = arr.astype(np.float32)
        out[name] = arr
    return out


def unflatten_payload(arrays: Dict[str, np.ndarray]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for name, arr in arrays.items():
        parts = name.split(".")
        node = out
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = arr.item() if arr.ndim == 0 else arr
    return out


class FileExperienceQueue:
    """Spool-directory chunk queue (process mode): one producer (actor
    fleet member), one consumer (the learner).

    Commit protocol: the producer writes ``chunk_<index>.npz`` via tmp +
    ``os.replace`` — a crash mid-write leaves nothing visible. The consumer
    deletes a chunk after loading it and advances ``CURSOR.json``; the
    producer back-pressures while ``next_index − cursor ≥ capacity``.
    """

    CURSOR = "CURSOR.json"
    DONE = "DONE"

    def __init__(
        self,
        root: str,
        capacity: int = 8,
        poll_interval_s: float = 0.02,
        metrics: Any = None,
    ):
        self.root = root
        self.capacity = max(1, int(capacity))
        self.poll = float(poll_interval_s)
        self.metrics = metrics
        os.makedirs(root, exist_ok=True)

    def _chunk_path(self, index: int) -> str:
        return os.path.join(self.root, f"chunk_{index:06d}.npz")

    def cursor(self) -> int:
        """The consumer's next expected index (0 before any consumption)."""
        try:
            with open(os.path.join(self.root, self.CURSOR)) as f:
                return int(json.load(f)["next"])
        except (OSError, ValueError, KeyError):
            return 0

    def committed_indices(self) -> set:
        """Produced-but-unconsumed chunk indices currently in the spool —
        a respawned actor skips these (and everything below the cursor).
        The scan is sorted: consumers today are order-free (membership
        tests), but directory order is filesystem-dependent and a future
        ordered consumer must not inherit it silently (GL903)."""
        out = set()
        for name in sorted(os.listdir(self.root)):
            if name.startswith("chunk_") and name.endswith(".npz"):
                try:
                    out.add(int(name[len("chunk_"):-len(".npz")]))
                except ValueError:
                    continue
        return out

    def mark_done(self) -> None:
        _atomic_write_json(os.path.join(self.root, self.DONE), {"done": True})

    @property
    def done(self) -> bool:
        return os.path.exists(os.path.join(self.root, self.DONE))

    def put(  # acquires: spool-chunk(object)
        self, chunk: ExperienceChunk, stop: Optional[threading.Event] = None
    ) -> None:
        """Commit one chunk, back-pressuring against the consumer cursor.

        Lifecycle (graftlint ownership registry, docs/STATIC_ANALYSIS.md):
        the tmp write is the *stage*, ``os.replace`` the *commit*; the chunk
        then exists in the spool until :meth:`get` consumes it — stage →
        commit → consume, owned by the spool directory between the two."""
        while chunk.index - self.cursor() >= self.capacity:
            if self.done or (stop is not None and stop.is_set()):
                raise QueueClosed("spool closed")
            time.sleep(self.poll)
        arrays = flatten_payload(chunk.payload)
        arrays["__version__"] = np.asarray(chunk.version, np.int64)
        path = self._chunk_path(chunk.index)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)

    def get(  # releases: spool-chunk(object)
        self, index: int, timeout: Optional[float] = None
    ) -> ExperienceChunk:
        """Consume chunk ``index``: wait for its file, load, delete, advance
        the cursor. ``timeout`` bounds the wait (actor-liveness guard)."""
        path = self._chunk_path(index)
        deadline = None if timeout is None else time.monotonic() + timeout
        while not os.path.exists(path):
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"no chunk {index} after {timeout:.0f}s — actor dead or "
                    f"stalled? (spool: {self.root})"
                )
            time.sleep(self.poll)
        with np.load(path) as data:
            arrays = {k: data[k] for k in data.files}
        version = int(arrays.pop("__version__"))
        os.remove(path)
        _atomic_write_json(os.path.join(self.root, self.CURSOR), {"next": index + 1})
        return ExperienceChunk(index=index, version=version, payload=unflatten_payload(arrays))

    @property
    def depth(self) -> int:
        return len(self.committed_indices())

    def close(self) -> None:
        self.mark_done()
