"""Process-mode generation actor: a standalone process that builds the same
trainer (its own JAX runtime, devices, and prompt stream), adopts learner
weights from the file channel, and spools experience chunks for the learner.

Launch one per actor slice alongside the learner::

    # learner process
    cfg = cfg.evolve(async_rl=dict(enabled=True, mode="process",
                                   root_dir="/shared/async"))
    trlx.train(reward_fn=reward_fn, prompts=prompts, config=cfg)

    # actor process(es), same config + callbacks
    from trlx_tpu.async_rl.actor import run_actor
    run_actor(cfg, reward_fn=reward_fn, prompts=prompts)

Determinism and crash recovery: the chunk stream (prompt batches + per-chunk
RNG) is derived from ``train.seed`` exactly as the learner's serial path
would derive it, so chunk ``i`` is reproducible by any actor incarnation. A
respawned actor fast-forwards past chunks already committed to the spool
(or consumed past the learner's cursor) and regenerates the one that died —
requeue-on-actor-death without any coordination beyond the spool directory.
The ``actor_crash@collection:N`` fault kills the process deterministically
(once — a marker file stops a respawned actor from re-firing it); the
supervisor relaunching the actor is deployment-specific (a shell loop in
the tests, a k8s restart policy in production).

The actor exits cleanly when the learner marks the spool DONE.
"""

import os
import sys
import time
from typing import Any, Callable, List, Optional

import numpy as np

from trlx_tpu.async_rl.channel import FileWeightChannel
from trlx_tpu.async_rl.queue import ExperienceChunk, FileExperienceQueue, QueueClosed
from trlx_tpu.async_rl.runtime import ChunkSpec
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

__all__ = ["run_actor"]


def chunks_per_collection(config: Any) -> int:
    """Chunks one collection consumes (deterministic collection tagging for
    ``actor_crash@collection:N``): ``ceil(num_rollouts / chunk_size)``."""
    rollouts = int(config.method.num_rollouts)
    chunk = max(1, int(config.method.chunk_size))
    return max(1, -(-rollouts // chunk))


def _maybe_crash(plan: Any, root_dir: str, spec: ChunkSpec) -> None:
    """The deterministic ``actor_crash@collection:N`` fault, process
    flavor: a marker file under the shared root stops a respawned (or
    surviving) actor from re-firing the same collection's crash."""
    if not plan:
        return
    marker = os.path.join(root_dir, f"actor_crash_fired_{spec.collection}")
    if os.path.exists(marker) or not plan.poll(
        "actor_crash", collection=spec.collection
    ):
        return
    with open(marker, "w") as f:
        f.write("fired\n")
    from trlx_tpu.resilience.faults import InjectedFault

    logger.warning(
        f"fault plan: actor crashing in collection {spec.collection} "
        f"(chunk {spec.index})"
    )
    raise InjectedFault(
        f"actor_crash@collection:{spec.collection} (chunk {spec.index})"
    )


def _run_actor_collective(
    trainer: Any,
    config: Any,
    max_chunks: Optional[int],
) -> int:
    """Collective-transport actor main loop: join the fleet (HELLO →
    WELCOME param snapshot + tree position), lease chunk indices from the
    coordinator, and commit payloads in-fabric. The spec stream (prompt
    batches + per-chunk RNG) is still seed-derived and index-addressed, so
    ANY member can regenerate ANY chunk — a lease requeued from a departed
    member lands on a survivor and produces the identical chunk. Specs are
    cached from the local draw position down to the learner's broadcast
    finalize cursor (requeues below the cursor are impossible), so the
    cache stays bounded by the production window."""
    import jax

    from trlx_tpu.async_rl.transport import FleetActorClient, read_endpoint
    from trlx_tpu.parallel.mesh import get_global_mesh, mesh_descriptor

    acfg = config.async_rl
    plan = trainer.resilience.plan
    per_collection = chunks_per_collection(config)
    max_staleness = max(0, int(acfg.max_staleness))
    address, authkey = read_endpoint(
        acfg.root_dir,
        timeout_s=acfg.actor_timeout_s,
        poll_interval_s=acfg.poll_interval_s,
    )
    mesh = get_global_mesh()
    client = FleetActorClient(
        address,
        authkey,
        template=trainer.state.params,
        mesh_descriptor=mesh_descriptor(mesh) if mesh is not None else None,
        bind_host=acfg.bind_host,
    )
    rng = trainer._rollout_rng
    produced = 0
    local_pos = 0
    cache = {}
    try:
        while max_chunks is None or produced < max_chunks:
            index = client.request_work()
            if index is None:
                break  # drained: the coordinator is shutting the fleet down
            # advance the deterministic spec stream to the assigned index —
            # every index's draws are burned exactly once, in order, so the
            # stream position matches the serial path's regardless of which
            # indices this member ends up producing
            while local_pos <= index:
                batch = next(trainer.prompt_iterator)
                rng, chunk_rng = jax.random.split(rng)
                cache[local_pos] = (
                    np.asarray(batch["input_ids"], np.int32),
                    np.asarray(batch["attention_mask"], np.int32),
                    chunk_rng,
                )
                local_pos += 1
            ids, mask, chunk_rng = cache[index]
            cursor = client.cursor_view()
            for stale in [k for k in sorted(cache) if k < cursor and k != index]:
                del cache[stale]
            spec = ChunkSpec(
                index=index,
                collection=index // per_collection + 1,
                prompt_ids=ids,
                prompt_mask=mask,
                rng=chunk_rng,
            )
            if not client.wait_ready(max_staleness, spec.collection):
                break
            params, version = client.fetch()
            _maybe_crash(plan, acfg.root_dir, spec)
            payload = trainer._async_produce_chunk(spec, params, version, client)
            try:
                client.put(ExperienceChunk(spec.index, version, payload))
            except QueueClosed:
                break
            trainer.obs.metrics.inc("async/chunks")
            produced += 1
    finally:
        # a crash (e.g. the injected actor_crash fault) must read as a
        # member DEATH at the coordinator (fleet shrink + lease requeue),
        # not a polite leave
        client.close(graceful=sys.exc_info()[0] is None)
    return produced


def run_actor(
    config: Any,
    reward_fn: Optional[Callable] = None,
    prompts: Optional[List[str]] = None,
    stop_sequences: Optional[List[str]] = None,
    max_chunks: Optional[int] = None,
) -> int:
    """Run one generation actor until the learner marks the spool DONE (or
    ``max_chunks`` commits). Returns the number of chunks produced."""
    from trlx_tpu.trlx import initialize_runtime

    initialize_runtime()
    import importlib

    for module in ("trlx_tpu.pipeline.offline_pipeline", "trlx_tpu.trainer.ppo",
                   "trlx_tpu.trainer.grpo"):
        importlib.import_module(module)
    from trlx_tpu.pipeline import get_pipeline
    from trlx_tpu.trainer import get_trainer
    from trlx_tpu.utils import set_seed

    acfg = config.async_rl
    if not acfg.root_dir:
        raise ValueError("async_rl.root_dir is required in process mode")
    set_seed(config.train.seed)
    trainer = get_trainer(config.train.trainer)(
        config=config,
        reward_fn=reward_fn,
        metric_fn=None,
        stop_sequences=stop_sequences or [],
        **config.train.trainer_kwargs,
    )
    batch_size = config.train.batch_size
    max_prompt_length = (
        config.train.seq_length - config.method.gen_kwargs["max_new_tokens"]
    )
    prompts = prompts or [trainer.tokenizer.bos_token] * batch_size
    trainer.add_prompt_pipeline(
        get_pipeline(config.train.pipeline)(prompts, max_prompt_length, trainer.tokenizer)
    )

    if acfg.transport == "collective":
        return _run_actor_collective(trainer, config, max_chunks)

    queue = FileExperienceQueue(
        os.path.join(acfg.root_dir, "spool"),
        capacity=trainer._async_queue_capacity(),
        poll_interval_s=acfg.poll_interval_s,
    )
    channel = FileWeightChannel(
        os.path.join(acfg.root_dir, "weights"),
        poll_interval_s=acfg.poll_interval_s,
        fetch_timeout_s=acfg.fetch_timeout_s,
    )
    plan = trainer.resilience.plan
    per_collection = chunks_per_collection(config)
    max_staleness = max(0, int(acfg.max_staleness))

    import jax

    rng = trainer._rollout_rng
    produced = 0
    index = 0
    while not queue.done and (max_chunks is None or produced < max_chunks):
        # the draw stream advances for EVERY index — committed chunks are
        # skipped but their prompt/RNG draws are burned, so a respawned
        # actor's stream position matches the original's
        batch = next(trainer.prompt_iterator)
        rng, chunk_rng = jax.random.split(rng)
        committed = queue.committed_indices()
        cursor = queue.cursor()
        if index < cursor or index in committed:
            index += 1
            continue
        spec = ChunkSpec(
            index=index,
            collection=index // per_collection + 1,
            prompt_ids=np.asarray(batch["input_ids"], np.int32),
            prompt_mask=np.asarray(batch["attention_mask"], np.int32),
            rng=chunk_rng,
        )
        # staleness gate: wait until starting this collection's chunk under
        # the newest payload satisfies the bound, and never run more than
        # one collection ahead of the learner's announcements (bail out if
        # the learner finishes first)
        while not channel.ready(max_staleness, spec.collection):
            if queue.done:
                return produced
            time.sleep(channel.poll)
        params, version = channel.fetch(template=trainer.state.params)
        _maybe_crash(plan, acfg.root_dir, spec)
        payload = trainer._async_produce_chunk(spec, params, version, channel)
        try:
            queue.put(ExperienceChunk(spec.index, version, payload))
        except QueueClosed:
            break
        trainer.obs.metrics.inc("async/chunks")
        produced += 1
        index += 1
    return produced
