"""Public API: the single ``train()`` entry point.

Contract-compatible with the reference dispatcher (``trlx/trlx.py:15-123``):
a ``reward_fn`` selects online RL (PPO), ``samples`` + ``rewards`` selects
offline RL (ILQL), ``samples`` alone selects SFT. The user callback contracts
are preserved exactly:

- ``reward_fn(samples, prompts, outputs) -> List[float]``
- ``metric_fn(samples, prompts, outputs) -> Dict[str, List[float]]``
"""

import os
import warnings
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.data.default_configs import (
    default_ilql_config,
    default_ppo_config,
    default_sft_config,
)
from trlx_tpu.utils import set_seed

_runtime_initialized = False


def initialize_runtime() -> None:
    """Process-level JAX runtime setup, driven by environment variables.

    Called once at the top of :func:`train` (idempotent). Two concerns:

    - **Platform override** — ``TRLX_TPU_PLATFORM=cpu|tpu`` forces the JAX
      platform via ``jax.config`` (stronger than ``JAX_PLATFORMS``, which
      container boot shims can override).
    - **Multi-host initialization** — the TPU-native equivalent of the
      reference's ``torchrun``/NCCL process-group setup (SURVEY.md §2.3
      "Distributed communication backend"). On a TPU pod, launch the same
      script on every host with ``TRLX_TPU_MULTIHOST=1`` and
      ``jax.distributed.initialize()`` auto-detects coordinator/process
      topology from the TPU metadata; elsewhere (CPU/GPU clusters, tests)
      set ``TRLX_TPU_COORDINATOR=host:port``, ``TRLX_TPU_NUM_PROCESSES``,
      and ``TRLX_TPU_PROCESS_ID`` explicitly. After initialization every
      host runs the same SPMD program over one global mesh; host-local code
      (trackers, checkpoint writes, reward fns) is already gated on
      ``jax.process_index() == 0`` throughout the trainers.

    v4 pod launch sketch::

        # on every host of a v4-32 (4 hosts × 4 chips):
        TRLX_TPU_MULTIHOST=1 python examples/ppo_sentiments.py
    """
    global _runtime_initialized
    if _runtime_initialized:
        return
    _runtime_initialized = True

    platform = os.environ.get("TRLX_TPU_PLATFORM")
    if platform:
        import jax

        os.environ["JAX_PLATFORMS"] = platform
        try:
            jax.config.update("jax_platforms", platform)
        except Exception as e:
            from trlx_tpu.utils import logging

            logging.get_logger(__name__).warning(
                f"TRLX_TPU_PLATFORM={platform} could not be applied "
                f"(backend already initialized? {e})"
            )

    coordinator = os.environ.get("TRLX_TPU_COORDINATOR")
    if os.environ.get("TRLX_TPU_MULTIHOST") or coordinator:
        import jax

        requested = (platform or os.environ.get("JAX_PLATFORMS", "")).lower()
        if not requested or requested.startswith("cpu"):
            # CPU multiprocess collectives live behind an explicit backend
            # selection since jax 0.4.x ("Multiprocess computations aren't
            # implemented on the CPU backend" otherwise): gloo carries the
            # cross-process allgathers/psums the multihost harness (and the
            # coordinated-preemption flag exchange) relies on. Must be set
            # before the backend initializes. The empty case covers jax's
            # automatic CPU fallback (no accelerator, nothing requested) —
            # the first step-boundary preemption allgather would otherwise
            # die; when another platform wins auto-detection the setting
            # only configures the unused CPU client, so it is harmless.
            try:
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except Exception as e:  # pragma: no cover - jax version drift
                from trlx_tpu.utils import logging

                logging.get_logger(__name__).warning(
                    f"could not enable gloo CPU collectives ({e}); "
                    "cross-process collectives may be unavailable"
                )
        kwargs = {}
        if coordinator:
            kwargs = dict(
                coordinator_address=coordinator,
                num_processes=int(os.environ["TRLX_TPU_NUM_PROCESSES"]),
                process_id=int(os.environ["TRLX_TPU_PROCESS_ID"]),
            )
        jax.distributed.initialize(**kwargs)


def train(  # noqa: C901
    model_path: Optional[str] = None,
    reward_fn: Optional[Callable[[List[str], List[str], List[str]], List[float]]] = None,
    dataset: Optional[Iterable[Tuple[str, float]]] = None,
    samples: Optional[List[str]] = None,
    rewards: Optional[List[float]] = None,
    prompts: Optional[List[str]] = None,
    eval_prompts: Optional[List[str]] = None,
    metric_fn: Optional[Callable[[List[str], List[str], List[str]], Dict[str, List[float]]]] = None,
    config: Optional[TRLConfig] = None,
    stop_sequences: Optional[List[str]] = None,
    init_trainer_hook: Optional[Callable] = None,
):
    """Dispatch online RL, offline RL, or supervised fine-tuning.

    Args:
        model_path: HF checkpoint path, local directory, or ``builtin:*`` spec.
        reward_fn: rates batches of generated samples; called on host with
            ``(samples, prompts, outputs)``, returns per-sample rewards.
        dataset: deprecated; use ``samples`` and ``rewards``.
        samples: offline samples — strings, or interleaved
            ``(prompt_0, output_0, prompt_1, output_1, ...)`` lists.
        rewards: per-sample scalar rewards for offline (ILQL) training.
        prompts: prompts for online rollouts.
        eval_prompts: prompts for periodic validation.
        metric_fn: computes named per-sample statistics at eval.
        config: a :class:`TRLConfig`; a method-appropriate default is used
            (with a warning) when omitted.
        stop_sequences: strings at which generations are trimmed.
        init_trainer_hook: called with the constructed trainer before any
            rollout collection or training — e.g. to transplant warm-start
            weights into the policy and its frozen KL reference (the offline
            analogue of starting from a pretrained checkpoint).
    """
    # Import for registration side effects (trainers/pipelines register here).
    import importlib

    initialize_runtime()

    for module in (
        "trlx_tpu.pipeline.offline_pipeline",
        "trlx_tpu.trainer.ppo",
        "trlx_tpu.trainer.ilql",
        "trlx_tpu.trainer.sft",
        "trlx_tpu.trainer.grpo",
        "trlx_tpu.trainer.dpo",
    ):
        importlib.import_module(module)
    from trlx_tpu.pipeline import get_pipeline
    from trlx_tpu.trainer import get_trainer

    if config is None:
        warnings.warn(
            "Passing the `config` argument implicitly is deprecated; adapt one "
            "from `trlx_tpu/data/default_configs.py` instead"
        )
        if reward_fn:
            config = default_ppo_config()
        elif rewards:
            config = default_ilql_config()
        else:
            config = default_sft_config()

    set_seed(config.train.seed)

    if dataset:
        warnings.warn("the `dataset` argument is deprecated, split it into `samples` and `rewards`")
        samples, rewards = dataset

    if model_path:
        config.model.model_path = model_path

    trainer = get_trainer(config.train.trainer)(
        config=config,
        reward_fn=reward_fn,
        metric_fn=metric_fn,
        stop_sequences=stop_sequences or [],
        **config.train.trainer_kwargs,
    )
    if init_trainer_hook is not None:
        init_trainer_hook(trainer)

    batch_size = config.train.batch_size
    max_prompt_length = config.train.seq_length - config.method.gen_kwargs["max_new_tokens"]

    if reward_fn:
        # Online RL: build the prompt pipeline and collect initial experience.
        prompts = prompts or [trainer.tokenizer.bos_token] * batch_size
        if eval_prompts is None:
            eval_prompts = prompts[:batch_size]

        pipeline = get_pipeline(config.train.pipeline)(
            prompts, max_prompt_length, trainer.tokenizer
        )
        trainer.add_prompt_pipeline(pipeline)
        # restore BEFORE collecting rollouts: PPO behavior logprobs must come
        # from the restored policy, not the freshly initialized one
        if hasattr(trainer, "maybe_resume"):
            trainer.maybe_resume()
        trainer.make_experience(config.method.num_rollouts)
    elif samples:
        if rewards is not None and len(samples) != len(rewards):
            raise ValueError(
                f"Number of samples {len(samples)} should match the number of rewards {len(rewards)}"
            )
        if eval_prompts is None:
            eval_prompts = [trainer.tokenizer.bos_token] * batch_size
        if rewards is not None:
            trainer.make_experience(samples, rewards, config.train.seq_length)
        else:
            trainer.make_experience(samples, config.train.seq_length)
    else:
        raise ValueError("Either `samples` or `reward_fn` should be given for training")

    eval_pipeline = get_pipeline(config.train.pipeline)(
        eval_prompts, max_prompt_length, trainer.tokenizer
    )
    trainer.add_eval_pipeline(eval_pipeline)

    trainer.learn()
    return trainer
