"""Public API: the single ``train()`` entry point.

Contract-compatible with the reference dispatcher (``trlx/trlx.py:15-123``):
a ``reward_fn`` selects online RL (PPO), ``samples`` + ``rewards`` selects
offline RL (ILQL), ``samples`` alone selects SFT. The user callback contracts
are preserved exactly:

- ``reward_fn(samples, prompts, outputs) -> List[float]``
- ``metric_fn(samples, prompts, outputs) -> Dict[str, List[float]]``
"""

import warnings
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.data.default_configs import (
    default_ilql_config,
    default_ppo_config,
    default_sft_config,
)
from trlx_tpu.utils import set_seed


def train(  # noqa: C901
    model_path: Optional[str] = None,
    reward_fn: Optional[Callable[[List[str], List[str], List[str]], List[float]]] = None,
    dataset: Optional[Iterable[Tuple[str, float]]] = None,
    samples: Optional[List[str]] = None,
    rewards: Optional[List[float]] = None,
    prompts: Optional[List[str]] = None,
    eval_prompts: Optional[List[str]] = None,
    metric_fn: Optional[Callable[[List[str], List[str], List[str]], Dict[str, List[float]]]] = None,
    config: Optional[TRLConfig] = None,
    stop_sequences: Optional[List[str]] = None,
):
    """Dispatch online RL, offline RL, or supervised fine-tuning.

    Args:
        model_path: HF checkpoint path, local directory, or ``builtin:*`` spec.
        reward_fn: rates batches of generated samples; called on host with
            ``(samples, prompts, outputs)``, returns per-sample rewards.
        dataset: deprecated; use ``samples`` and ``rewards``.
        samples: offline samples — strings, or interleaved
            ``(prompt_0, output_0, prompt_1, output_1, ...)`` lists.
        rewards: per-sample scalar rewards for offline (ILQL) training.
        prompts: prompts for online rollouts.
        eval_prompts: prompts for periodic validation.
        metric_fn: computes named per-sample statistics at eval.
        config: a :class:`TRLConfig`; a method-appropriate default is used
            (with a warning) when omitted.
        stop_sequences: strings at which generations are trimmed.
    """
    # Import for registration side effects (trainers/pipelines register here).
    import importlib

    for module in (
        "trlx_tpu.pipeline.offline_pipeline",
        "trlx_tpu.trainer.ppo",
        "trlx_tpu.trainer.ilql",
        "trlx_tpu.trainer.sft",
    ):
        importlib.import_module(module)
    from trlx_tpu.pipeline import get_pipeline
    from trlx_tpu.trainer import get_trainer

    if config is None:
        warnings.warn(
            "Passing the `config` argument implicitly is deprecated; adapt one "
            "from `trlx_tpu/data/default_configs.py` instead"
        )
        if reward_fn:
            config = default_ppo_config()
        elif rewards:
            config = default_ilql_config()
        else:
            config = default_sft_config()

    set_seed(config.train.seed)

    if dataset:
        warnings.warn("the `dataset` argument is deprecated, split it into `samples` and `rewards`")
        samples, rewards = dataset

    if model_path:
        config.model.model_path = model_path

    trainer = get_trainer(config.train.trainer)(
        config=config,
        reward_fn=reward_fn,
        metric_fn=metric_fn,
        stop_sequences=stop_sequences or [],
        **config.train.trainer_kwargs,
    )

    batch_size = config.train.batch_size
    max_prompt_length = config.train.seq_length - config.method.gen_kwargs["max_new_tokens"]

    if reward_fn:
        # Online RL: build the prompt pipeline and collect initial experience.
        prompts = prompts or [trainer.tokenizer.bos_token] * batch_size
        if eval_prompts is None:
            eval_prompts = prompts[:batch_size]

        pipeline = get_pipeline(config.train.pipeline)(
            prompts, max_prompt_length, trainer.tokenizer
        )
        trainer.add_prompt_pipeline(pipeline)
        trainer.make_experience(config.method.num_rollouts)
    elif samples:
        if rewards is not None and len(samples) != len(rewards):
            raise ValueError(
                f"Number of samples {len(samples)} should match the number of rewards {len(rewards)}"
            )
        if eval_prompts is None:
            eval_prompts = [trainer.tokenizer.bos_token] * batch_size
        if rewards is not None:
            trainer.make_experience(samples, rewards, config.train.seq_length)
        else:
            trainer.make_experience(samples, config.train.seq_length)
    else:
        raise ValueError("Either `samples` or `reward_fn` should be given for training")

    eval_pipeline = get_pipeline(config.train.pipeline)(
        eval_prompts, max_prompt_length, trainer.tokenizer
    )
    trainer.add_eval_pipeline(eval_pipeline)

    trainer.learn()
    return trainer
