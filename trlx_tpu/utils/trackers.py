"""Experiment trackers: JSONL (always available), TensorBoard, W&B.

Reference equivalent: ``AccelerateRLTrainer.__init__`` tracker setup
(``trlx/trainer/accelerate_base_trainer.py:69-119``) — W&B with a composed
run name, or TensorBoard with a flattened config. Here the default is a plain
JSONL stats stream (offline-friendly); W&B/TensorBoard attach when their
packages exist. All trackers log only from process 0.
"""

import json
import os
import time
from typing import Any, Dict, Optional

import jax

from trlx_tpu.utils import filter_non_scalars, get_git_tag, significant


class Tracker:
    """Null tracker: drops everything. Also the context-manager contract
    every tracker shares (``with make_tracker(cfg) as tracker: ...``)."""

    def log(self, stats: Dict[str, Any], step: int) -> None:
        pass

    def finish(self) -> None:
        pass

    def __enter__(self) -> "Tracker":
        return self

    def __exit__(self, *exc_info) -> None:
        self.finish()


class JSONLTracker(Tracker):
    """Appends one JSON object per log call to ``<dir>/stats.jsonl``.

    With ``flush_every=1`` (the safe default) the stats file is opened in
    **line-buffered append** mode: each record lands on disk as one line
    even if the process dies mid-run. ``flush_every=N`` switches to block
    buffering with an explicit flush every N records — for high-frequency
    logging where the per-line write syscall shows up; at most N-1 records
    are at risk on a hard crash. ``finish()`` is idempotent, and a
    ``log()`` after ``finish()`` transparently reopens the append handle —
    trainers and benchmark harnesses share tracker instances across phases
    and must never crash on a closed file.
    """

    def __init__(
        self,
        logging_dir: str,
        config_dict: Optional[Dict] = None,
        flush_every: int = 1,
    ):
        os.makedirs(logging_dir, exist_ok=True)
        self.path = os.path.join(logging_dir, "stats.jsonl")
        self.flush_every = max(1, int(flush_every))
        self._since_flush = 0
        if config_dict is not None:
            with open(os.path.join(logging_dir, "config.json"), "w") as f:
                json.dump(config_dict, f, indent=2, default=str)
        self._f = self._open()

    def _open(self):
        # line-buffered when flushing every record (a crash loses at most
        # the current partial line); block-buffered when the flush_every
        # knob batches — line buffering would defeat the batching
        return open(self.path, "a", buffering=1 if self.flush_every == 1 else -1)

    def _handle(self):
        if self._f is None or self._f.closed:
            self._f = self._open()
        return self._f

    def log(self, stats: Dict[str, Any], step: int) -> None:
        record = {"step": step, "time": time.time()}
        record.update(
            {k: significant(v) for k, v in filter_non_scalars(stats).items()}
        )
        f = self._handle()
        f.write(json.dumps(record) + "\n")
        self._since_flush += 1
        if self._since_flush >= self.flush_every:
            f.flush()
            self._since_flush = 0

    def finish(self) -> None:
        if self._f is not None and not self._f.closed:
            self._f.close()


class TensorBoardTracker(Tracker):
    def __init__(self, logging_dir: str, config_dict: Optional[Dict] = None):
        from torch.utils.tensorboard import SummaryWriter

        self.writer = SummaryWriter(logging_dir)
        if config_dict is not None:
            from trlx_tpu.utils import flatten_dict

            flat = {
                k: str(v) for k, v in flatten_dict(config_dict, sep=".").items()
            }
            self.writer.add_hparams(
                {k: v for k, v in flat.items() if isinstance(v, (int, float, str))},
                {},
                run_name=".",
            )

    def log(self, stats: Dict[str, Any], step: int) -> None:
        for k, v in filter_non_scalars(stats).items():
            self.writer.add_scalar(k, v, step)

    def finish(self) -> None:
        self.writer.close()


class WandbTracker(Tracker):
    def __init__(
        self,
        project: str,
        run_name: str,
        entity: Optional[str] = None,
        group: Optional[str] = None,
        tags=None,
        config_dict: Optional[Dict] = None,
        logging_dir: Optional[str] = None,
    ):
        import wandb

        self.run = wandb.init(
            project=project,
            name=run_name,
            entity=entity,
            group=group,
            tags=tags,
            config=config_dict,
            dir=logging_dir,
            mode=os.environ.get("WANDB_MODE", "online"),
        )

    def log(self, stats: Dict[str, Any], step: int) -> None:
        self.run.log(filter_non_scalars(stats), step=step)

    def finish(self) -> None:
        self.run.finish()


def run_name_for(config) -> str:
    """``<model>/<n>devices:<git branch>`` — the reference composes script/
    model/ngpus:branch (``accelerate_base_trainer.py:69-102``)."""
    model = os.path.basename(config.model.model_path.rstrip("/")).replace(":", "-")
    branch, _ = get_git_tag()
    return f"{model}/{jax.device_count()}devices:{branch}"


def make_tracker(config) -> Tracker:
    """Build the tracker named by ``config.train.tracker``.

    ``None`` → JSONL into ``logging_dir`` (or null tracker if no dir);
    ``"wandb"`` / ``"tensorboard"`` fall back to JSONL with a warning when
    the package is unavailable. Non-zero processes always get the null
    tracker (single-writer, like the reference's main-process gating).
    """
    if jax.process_index() != 0:
        return Tracker()
    name = config.train.tracker
    logging_dir = config.train.logging_dir or os.path.join(
        config.train.checkpoint_dir, "logs"
    )
    config_dict = config.to_dict()
    if name in (None, "jsonl"):
        if name is None and config.train.logging_dir is None and config.train.checkpoint_dir is None:
            return Tracker()
        return JSONLTracker(logging_dir, config_dict)
    if name == "tensorboard":
        try:
            return TensorBoardTracker(logging_dir, config_dict)
        except ImportError:
            pass
    elif name == "wandb":
        try:
            return WandbTracker(
                project=config.train.project_name,
                run_name=run_name_for(config),
                entity=config.train.entity_name,
                group=config.train.group_name,
                tags=list(config.train.tags) + ["trlx_tpu"],
                config_dict=config_dict,
                logging_dir=logging_dir,
            )
        except ImportError:
            # real wandb failures (auth, bad entity, network) must surface;
            # only a missing package downgrades to JSONL
            pass
    else:
        raise ValueError(f"Unknown tracker '{name}' (use jsonl|tensorboard|wandb)")
    from trlx_tpu.utils.logging import get_logger

    get_logger(__name__).warning(
        f"tracker '{name}' unavailable; falling back to JSONL at {logging_dir}"
    )
    return JSONLTracker(logging_dir, config_dict)
