"""Experiment trackers: JSONL (always available), TensorBoard, W&B.

Reference equivalent: ``AccelerateRLTrainer.__init__`` tracker setup
(``trlx/trainer/accelerate_base_trainer.py:69-119``) — W&B with a composed
run name, or TensorBoard with a flattened config. Here the default is a plain
JSONL stats stream (offline-friendly); W&B/TensorBoard attach when their
packages exist. All trackers log only from process 0.
"""

import json
import os
import time
from typing import Any, Dict, Optional

import jax

from trlx_tpu.utils import filter_non_scalars, get_git_tag, significant


class Tracker:
    """Null tracker: drops everything."""

    def log(self, stats: Dict[str, Any], step: int) -> None:
        pass

    def finish(self) -> None:
        pass


class JSONLTracker(Tracker):
    """Appends one JSON object per log call to ``<dir>/stats.jsonl``."""

    def __init__(self, logging_dir: str, config_dict: Optional[Dict] = None):
        os.makedirs(logging_dir, exist_ok=True)
        self.path = os.path.join(logging_dir, "stats.jsonl")
        if config_dict is not None:
            with open(os.path.join(logging_dir, "config.json"), "w") as f:
                json.dump(config_dict, f, indent=2, default=str)
        self._f = open(self.path, "a")

    def log(self, stats: Dict[str, Any], step: int) -> None:
        record = {"step": step, "time": time.time()}
        record.update(
            {k: significant(v) for k, v in filter_non_scalars(stats).items()}
        )
        self._f.write(json.dumps(record) + "\n")
        self._f.flush()

    def finish(self) -> None:
        self._f.close()


class TensorBoardTracker(Tracker):
    def __init__(self, logging_dir: str, config_dict: Optional[Dict] = None):
        from torch.utils.tensorboard import SummaryWriter

        self.writer = SummaryWriter(logging_dir)
        if config_dict is not None:
            from trlx_tpu.utils import flatten_dict

            flat = {
                k: str(v) for k, v in flatten_dict(config_dict, sep=".").items()
            }
            self.writer.add_hparams(
                {k: v for k, v in flat.items() if isinstance(v, (int, float, str))},
                {},
                run_name=".",
            )

    def log(self, stats: Dict[str, Any], step: int) -> None:
        for k, v in filter_non_scalars(stats).items():
            self.writer.add_scalar(k, v, step)

    def finish(self) -> None:
        self.writer.close()


class WandbTracker(Tracker):
    def __init__(
        self,
        project: str,
        run_name: str,
        entity: Optional[str] = None,
        group: Optional[str] = None,
        tags=None,
        config_dict: Optional[Dict] = None,
        logging_dir: Optional[str] = None,
    ):
        import wandb

        self.run = wandb.init(
            project=project,
            name=run_name,
            entity=entity,
            group=group,
            tags=tags,
            config=config_dict,
            dir=logging_dir,
            mode=os.environ.get("WANDB_MODE", "online"),
        )

    def log(self, stats: Dict[str, Any], step: int) -> None:
        self.run.log(filter_non_scalars(stats), step=step)

    def finish(self) -> None:
        self.run.finish()


def run_name_for(config) -> str:
    """``<model>/<n>devices:<git branch>`` — the reference composes script/
    model/ngpus:branch (``accelerate_base_trainer.py:69-102``)."""
    model = os.path.basename(config.model.model_path.rstrip("/")).replace(":", "-")
    branch, _ = get_git_tag()
    return f"{model}/{jax.device_count()}devices:{branch}"


def make_tracker(config) -> Tracker:
    """Build the tracker named by ``config.train.tracker``.

    ``None`` → JSONL into ``logging_dir`` (or null tracker if no dir);
    ``"wandb"`` / ``"tensorboard"`` fall back to JSONL with a warning when
    the package is unavailable. Non-zero processes always get the null
    tracker (single-writer, like the reference's main-process gating).
    """
    if jax.process_index() != 0:
        return Tracker()
    name = config.train.tracker
    logging_dir = config.train.logging_dir or os.path.join(
        config.train.checkpoint_dir, "logs"
    )
    config_dict = config.to_dict()
    if name in (None, "jsonl"):
        if name is None and config.train.logging_dir is None and config.train.checkpoint_dir is None:
            return Tracker()
        return JSONLTracker(logging_dir, config_dict)
    if name == "tensorboard":
        try:
            return TensorBoardTracker(logging_dir, config_dict)
        except ImportError:
            pass
    elif name == "wandb":
        try:
            return WandbTracker(
                project=config.train.project_name,
                run_name=run_name_for(config),
                entity=config.train.entity_name,
                group=config.train.group_name,
                tags=list(config.train.tags) + ["trlx_tpu"],
                config_dict=config_dict,
                logging_dir=logging_dir,
            )
        except ImportError:
            # real wandb failures (auth, bad entity, network) must surface;
            # only a missing package downgrades to JSONL
            pass
    else:
        raise ValueError(f"Unknown tracker '{name}' (use jsonl|tensorboard|wandb)")
    from trlx_tpu.utils.logging import get_logger

    get_logger(__name__).warning(
        f"tracker '{name}' unavailable; falling back to JSONL at {logging_dir}"
    )
    return JSONLTracker(logging_dir, config_dict)
