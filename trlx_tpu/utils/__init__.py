"""General utilities: seeding, timing, pytree helpers, optax factories.

Functional parity targets in the reference: ``trlx/utils/__init__.py``
(``set_seed:39``, optimizer/scheduler getters ``:78-141``, ``Clock:144``,
``tree_map:185``, ``significant:26``, ``filter_non_scalars:206``,
``infinite_dataloader:235``). Optimizers/schedulers map onto optax instead of
torch.optim; seeding returns a ``jax.random.PRNGKey`` rather than mutating
global state.
"""

import math
import random
import subprocess
import time
from enum import Enum
from numbers import Number
from typing import Any, Dict, Iterable, Iterator, Mapping, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


def significant(x: Any, ndigits: int = 2) -> Any:
    """Cut the number to its ``ndigits`` most significant figures."""
    if not isinstance(x, Number) or x == 0 or not math.isfinite(x):
        return x
    return round(x, ndigits - 1 - int(math.floor(math.log10(abs(x)))))


def set_seed(seed: int, process_offset: bool = True) -> jax.Array:
    """Seed host-side RNGs and return a root PRNG key.

    The reference offsets the seed by the process rank
    (``trlx/utils/__init__.py:39-47``) so data orders differ per replica; the
    same offset is applied to the host-side RNGs here. The returned JAX key is
    *not* offset — under a global mesh all processes must fold identical keys
    into the same compiled program.
    """
    offset = jax.process_index() if process_offset else 0
    random.seed(seed + offset)
    np.random.seed(seed + offset)
    return jax.random.PRNGKey(seed)


class Clock:
    """Tracks wall time per processed sample (reference ``Clock:144-182``)."""

    def __init__(self):
        self.start = time.time()
        self.total_time = 0.0
        self.total_samples = 0

    def tick(self, samples: int = 0) -> float:
        """Returns seconds since last tick; accumulates sample throughput."""
        end = time.time()
        delta = end - self.start
        self.start = end
        if samples != 0:
            self.total_time += delta
            self.total_samples += samples
        return delta

    def get_stat(self, n_samp: int = 1000, reset: bool = False) -> float:
        """Seconds per ``n_samp`` samples."""
        stat = self.total_time * n_samp / max(self.total_samples, 1)
        if reset:
            self.total_time = 0.0
            self.total_samples = 0
        return stat


def filter_non_scalars(xs: Mapping[str, Any]) -> Dict[str, Any]:
    """Keep only scalar-convertible entries of a flat stats dict."""
    ys = {}
    for k, v in xs.items():
        try:
            ys[k] = float(v)
        except (TypeError, ValueError):
            continue
    return ys


def flatten_dict(d: Mapping, parent_key: str = "", sep: str = "/") -> Dict[str, Any]:
    """Flatten a nested mapping into ``a/b/c`` keys."""
    items = []
    for k, v in d.items():
        key = parent_key + sep + str(k) if parent_key else str(k)
        if isinstance(v, Mapping):
            items.extend(flatten_dict(v, key, sep).items())
        else:
            items.append((key, v))
    return dict(items)


def to_host(tree: Any) -> Any:
    """Device→host: fetch a pytree of jax arrays as numpy (scalars as floats)."""
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if hasattr(x, "shape") else x, jax.device_get(tree)
    )


def get_git_tag() -> Tuple[str, str]:
    """Current (branch, commit-hash) of the working directory, if a repo."""
    try:
        output = subprocess.check_output(
            "git log --format='%h/%as' -n1".split(), stderr=subprocess.DEVNULL
        )
        branch = (
            subprocess.check_output(
                "git rev-parse --abbrev-ref HEAD".split(), stderr=subprocess.DEVNULL
            )
            .decode()
            .strip()
        )
        return branch, output.decode().strip().replace("'", "")
    except (subprocess.CalledProcessError, FileNotFoundError):
        return "unknown", "unknown"


def infinite_loader(loader: Iterable) -> Iterator:
    """Cycle a dataloader forever (reference ``infinite_dataloader:235``)."""
    while True:
        yield from loader


# ---------------------------------------------------------------------------
# Optimizer / scheduler factories (optax)
# ---------------------------------------------------------------------------


class OptimizerName(str, Enum):
    ADAM = "adam"
    ADAMW = "adamw"
    ADAFACTOR = "adafactor"
    LION = "lion"
    SGD = "sgd"
    # int8 blockwise-quantized moments (trlx_tpu/utils/quantized_opt.py);
    # the bnb-suffixed name is accepted for reference config compatibility
    ADAMW_8BIT = "adamw_8bit"
    ADAMW_8BIT_BNB = "adamw_8bit_bnb"


class SchedulerName(str, Enum):
    COSINE_ANNEALING = "cosine_annealing"
    LINEAR = "linear"
    CONSTANT = "constant"
    WARMUP_COSINE = "warmup_cosine"


def get_scheduler(
    name: str, kwargs: Dict[str, Any], default_lr: float = None
) -> optax.Schedule:
    """Build an optax schedule from a config name + kwargs.

    ``cosine_annealing(T_max, eta_min)`` follows torch semantics used by the
    reference configs. The base/peak LR comes from scheduler ``lr`` or from
    ``default_lr`` (trainers pass the optimizer's lr, matching torch's
    CosineAnnealingLR which reads the base LR off the optimizer).
    """
    name = SchedulerName(name.lower())
    kwargs = dict(kwargs)
    lr = kwargs.pop("lr", None)
    if lr is None:
        lr = default_lr
    if name == SchedulerName.COSINE_ANNEALING:
        t_max = int(kwargs.pop("T_max", 10_000))
        eta_min = float(kwargs.pop("eta_min", 0.0))
        if lr is None:
            raise ValueError(
                "cosine_annealing needs a base LR: put `lr` in scheduler kwargs "
                "or pass default_lr (the optimizer's lr)"
            )
        # torch CosineAnnealingLR: lr(t) = eta_min + (lr-eta_min)*(1+cos(pi t/T))/2
        # computed in f32 (T_max can exceed int32, e.g. the 1e12 presets)
        return lambda step: eta_min + (lr - eta_min) * 0.5 * (
            1
            + jnp.cos(
                jnp.pi
                * jnp.minimum(jnp.asarray(step, jnp.float32), float(t_max))
                / float(t_max)
            )
        )
    if name == SchedulerName.LINEAR:
        if lr is None:
            lr = kwargs.pop("init_value", None)
        return optax.linear_schedule(
            init_value=lr if lr is not None else kwargs.pop("start", 1e-4),
            end_value=kwargs.pop("end_value", kwargs.pop("end", 0.0)),
            transition_steps=int(kwargs.pop("total_steps", kwargs.pop("transition_steps", 10_000))),
        )
    if name == SchedulerName.CONSTANT:
        if lr is None:
            lr = kwargs.pop("init_value", None)
        return optax.constant_schedule(lr if lr is not None else 1e-4)
    if name == SchedulerName.WARMUP_COSINE:
        # `init_value` here is the warmup *start* LR, distinct from the peak
        # (`lr`/`peak_value`) — do not conflate the two.
        peak = kwargs.pop("peak_value", lr)
        return optax.warmup_cosine_decay_schedule(
            init_value=kwargs.pop("init_value", 0.0),
            peak_value=peak if peak is not None else 1e-4,
            warmup_steps=int(kwargs.pop("warmup_steps", 100)),
            decay_steps=int(kwargs.pop("decay_steps", 10_000)),
            end_value=kwargs.pop("end_value", 0.0),
        )
    raise ValueError(f"Unknown scheduler {name}")


def _layerwise_freeze(vector: np.ndarray) -> optax.GradientTransformation:
    """Multiply updates by a per-layer 0/1 vector broadcast over the leading
    (stacked-layer) dim of every leaf. Used on both sides of the inner
    optimizer for ``scan_layers`` partial freezing: zeroing incoming grads
    keeps the moments clean, zeroing outgoing updates kills weight decay on
    frozen layers."""
    vec = jnp.asarray(vector)

    def init_fn(params):
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        def mask_leaf(u):
            return u * vec.reshape((-1,) + (1,) * (u.ndim - 1)).astype(u.dtype)

        return jax.tree_util.tree_map(mask_leaf, updates), state

    return optax.GradientTransformation(init_fn, update_fn)


def get_optimizer(
    name: str,
    kwargs: Dict[str, Any],
    schedule: optax.Schedule = None,
    mask: Any = None,
) -> optax.GradientTransformation:
    """Build an optax optimizer from a config name + kwargs.

    ``mask`` (a pytree matching params) freezes parameters the way the
    reference does with ``requires_grad_`` (``trlx/utils/modeling.py:34-66``).
    Leaves are bools (fully trainable / fully frozen → ``optax.set_to_zero``)
    or per-layer 0/1 vectors for ``scan_layers`` stacked blocks, which get
    the inner optimizer wrapped in a layer-wise freeze.
    """
    name = OptimizerName(name.lower())
    kwargs = dict(kwargs)
    lr = kwargs.pop("lr", 1e-4)
    learning_rate = schedule if schedule is not None else lr
    betas = kwargs.pop("betas", None)
    # betas → b1/b2 only for optimizers that take them; others ignore betas
    # (configs often keep betas when switching the optimizer name)
    if betas is not None and name in (
        OptimizerName.ADAM,
        OptimizerName.ADAMW,
        OptimizerName.ADAMW_8BIT,
        OptimizerName.ADAMW_8BIT_BNB,
        OptimizerName.LION,
    ):
        kwargs.setdefault("b1", betas[0])
        kwargs.setdefault("b2", betas[1])

    if name == OptimizerName.ADAMW:
        opt = optax.adamw(learning_rate, **kwargs)
    elif name in (OptimizerName.ADAMW_8BIT, OptimizerName.ADAMW_8BIT_BNB):
        from trlx_tpu.utils.quantized_opt import adamw_8bit

        opt = adamw_8bit(learning_rate, **kwargs)
    elif name == OptimizerName.ADAM:
        kwargs.pop("weight_decay", None)
        opt = optax.adam(learning_rate, **kwargs)
    elif name == OptimizerName.ADAFACTOR:
        kwargs.pop("eps", None)
        opt = optax.adafactor(learning_rate, **kwargs)
    elif name == OptimizerName.LION:
        kwargs.pop("eps", None)
        opt = optax.lion(learning_rate, **kwargs)
    elif name == OptimizerName.SGD:
        kwargs.pop("eps", None)
        wd = kwargs.pop("weight_decay", 0.0)
        opt = optax.sgd(learning_rate, **kwargs)
        if wd:
            opt = optax.chain(optax.add_decayed_weights(wd), opt)
    else:
        raise ValueError(f"Unknown optimizer {name}")

    if mask is not None:
        transforms: Dict[Any, optax.GradientTransformation] = {
            "train": opt,
            "freeze": optax.set_to_zero(),
        }
        vectors: Dict[Tuple, str] = {}

        def to_label(leaf):
            if isinstance(leaf, (bool, np.bool_)):
                return "train" if leaf else "freeze"
            key = tuple(np.asarray(leaf).tolist())
            if key not in vectors:
                label = f"partial_{len(vectors)}"
                vectors[key] = label
                transforms[label] = optax.chain(
                    _layerwise_freeze(np.asarray(leaf)),
                    opt,
                    _layerwise_freeze(np.asarray(leaf)),
                )
            return vectors[key]

        labels = jax.tree_util.tree_map(to_label, mask)
        opt = optax.multi_transform(transforms, labels)
    return opt
