"""8-bit AdamW: blockwise-quantized optimizer moments in pure JAX.

The reference exposes bitsandbytes' ``adamw_8bit_bnb`` (CUDA kernels,
``trlx/utils/__init__.py:99-118``) to halve-ish optimizer memory; this is the
TPU-native equivalent: both Adam moments are stored as int8 with per-block
fp32 scales (dynamic blockwise absmax quantization, the same scheme bnb
uses), dequantized/requantized inside the jitted update. For a parameter
tensor of n elements the optimizer state is 2·n bytes + 2·n/block fp32
scales instead of 8·n bytes — a 4× reduction, which at 20B params is ~120GB
of HBM back.

Everything is elementwise + reshapes, so XLA fuses the (de)quantization into
the update loop; there is no kernel to hand-write.

Numerics: absmax int8 quantization of ``exp_avg`` (signed) and sqrt-space
quantization of ``exp_avg_sq`` (non-negative; storing sqrt halves the
relative error where it matters, near the Adam denominator). Tiny tensors
(≤ one block) stay fp32 — same policy as bnb's ``min_8bit_size``.
"""

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

BLOCK = 2048
MIN_8BIT_SIZE = 4096  # tensors smaller than this keep fp32 moments


class _Quantized(NamedTuple):
    """Blockwise-quantized tensor: int8 codes + per-block fp32 absmax."""

    codes: jax.Array  # int8 [n_blocks, BLOCK] (padded)
    scales: jax.Array  # f32 [n_blocks, 1]


def _quantize(x: jax.Array) -> _Quantized:
    flat = x.reshape(-1)
    pad = (-flat.size) % BLOCK
    flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(-1, BLOCK)
    scales = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    safe = jnp.where(scales > 0, scales, 1.0)
    codes = jnp.clip(jnp.round(blocks / safe * 127.0), -127, 127).astype(jnp.int8)
    return _Quantized(codes, scales)


def _dequantize(q: _Quantized, shape) -> jax.Array:
    blocks = q.codes.astype(jnp.float32) / 127.0 * q.scales
    n = 1
    for d in shape:
        n *= d
    return blocks.reshape(-1)[:n].reshape(shape)


class Adam8bitState(NamedTuple):
    count: jax.Array
    mu: Any  # pytree of _Quantized | f32 arrays (small leaves)
    nu: Any  # pytree of _Quantized (sqrt-space) | f32 arrays


def adamw_8bit(
    learning_rate,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """AdamW with int8 blockwise-quantized moments (reference:
    bitsandbytes ``AdamW8bit``; here the quantization is fused by XLA)."""

    def is_small(p) -> bool:
        return p.size < MIN_8BIT_SIZE

    def init_fn(params):
        def init_mu(p):
            if is_small(p):
                return jnp.zeros_like(p, jnp.float32)
            return _quantize(jnp.zeros(p.shape, jnp.float32))

        mu = jax.tree_util.tree_map(init_mu, params)
        nu = jax.tree_util.tree_map(init_mu, params)
        return Adam8bitState(jnp.zeros((), jnp.int32), mu, nu)

    def update_fn(updates, state, params):
        if params is None:
            raise ValueError("adamw_8bit requires params (for weight decay)")
        count = state.count + 1
        b1c = 1.0 - b1 ** count.astype(jnp.float32)
        b2c = 1.0 - b2 ** count.astype(jnp.float32)
        lr = learning_rate(state.count) if callable(learning_rate) else learning_rate

        def leaf(g, mu_q, nu_q, p):
            g = g.astype(jnp.float32)
            if is_small(p):
                mu = b1 * mu_q + (1 - b1) * g
                nu = b2 * nu_q + (1 - b2) * g * g
                new_mu, new_nu = mu, nu
            else:
                mu = b1 * _dequantize(mu_q, g.shape) + (1 - b1) * g
                # nu stored in sqrt space: nu = (stored)^2
                nu_prev = _dequantize(nu_q, g.shape) ** 2
                nu = b2 * nu_prev + (1 - b2) * g * g
                new_mu, new_nu = _quantize(mu), _quantize(jnp.sqrt(nu))
            m_hat = mu / b1c
            v_hat = nu / b2c
            step = m_hat / (jnp.sqrt(v_hat) + eps)
            if weight_decay:
                step = step + weight_decay * p.astype(jnp.float32)
            return (-lr * step).astype(p.dtype), new_mu, new_nu

        flat_u, treedef = jax.tree_util.tree_flatten(updates)
        flat_mu = treedef.flatten_up_to(state.mu)
        flat_nu = treedef.flatten_up_to(state.nu)
        flat_p = treedef.flatten_up_to(params)
        outs = [leaf(g, m, n, p) for g, m, n, p in zip(flat_u, flat_mu, flat_nu, flat_p)]
        new_updates = treedef.unflatten([o[0] for o in outs])
        new_mu = treedef.unflatten([o[1] for o in outs])
        new_nu = treedef.unflatten([o[2] for o in outs])
        return new_updates, Adam8bitState(count, new_mu, new_nu)

    return optax.GradientTransformation(init_fn, update_fn)
