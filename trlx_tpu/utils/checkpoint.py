"""Checkpointing: Orbax-backed sharded pytree save/restore + HF-style export.

Reference equivalents: ``AccelerateRLTrainer.save/load`` delegate to
``accelerator.save_state/load_state`` (``accelerate_base_trainer.py:274-280``)
and ``save_pretrained`` exports an HF-format directory (``:256-272``). Here
the full train state (params + optimizer state + step) goes through Orbax —
sharded arrays save/restore in their mesh layout without gathering to one
host — and ``save_pretrained`` writes a flax msgpack + config JSON.
"""

import json
import os
import shutil
from typing import Any, Dict, Optional

import jax
import numpy as np

# Process-wide async checkpointer: device arrays are snapshotted
# synchronously but serialization/IO runs on background threads, so the
# train loop resumes immediately (the reference's accelerator.save_state
# blocks; at multi-GB states that is seconds-to-minutes per interval).
_ASYNC_CKPTR = None


def _async_checkpointer():
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        import orbax.checkpoint as ocp

        _ASYNC_CKPTR = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    return _ASYNC_CKPTR


def wait_for_saves() -> None:
    """Block until every in-flight async save has committed to disk. Called
    before reads/overwrites of checkpoint directories and at end of
    training — an unawaited final save could otherwise be lost with the
    process."""
    if _ASYNC_CKPTR is not None:
        _ASYNC_CKPTR.wait_until_finished()


def save_state(
    directory: str, state: Any, extra: Optional[Dict] = None, async_save: bool = True
) -> None:
    """Save a train-state pytree (+ small JSON ``extra``) to ``directory``.

    ``async_save`` returns as soon as the device arrays are snapshotted;
    IO completes in the background (``wait_for_saves`` joins it).
    """
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    tree_dir = os.path.join(directory, "state")
    # never rmtree under an in-flight write to the same tree
    wait_for_saves()
    if os.path.exists(tree_dir):
        shutil.rmtree(tree_dir)
    os.makedirs(directory, exist_ok=True)
    if async_save:
        _async_checkpointer().save(tree_dir, state)
    else:
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(tree_dir, state)
    if extra is not None:
        with open(os.path.join(directory, "trainer_state.json"), "w") as f:
            json.dump(extra, f)


def restore_state(directory: str, template: Any) -> Any:
    """Restore a pytree saved by :func:`save_state`.

    ``template`` (the current in-memory state) supplies structure, dtypes,
    and shardings, so restored arrays land directly on the mesh.
    """
    import orbax.checkpoint as ocp

    wait_for_saves()  # the checkpoint being restored may still be in flight
    directory = os.path.abspath(directory)
    tree_dir = os.path.join(directory, "state")

    def as_restore_type(x):
        if isinstance(x, jax.Array) and hasattr(x, "sharding"):
            return ocp.type_handlers.ArrayRestoreArgs(
                sharding=x.sharding, global_shape=x.shape, dtype=x.dtype
            )
        return ocp.type_handlers.RestoreArgs()

    restore_args = jax.tree_util.tree_map(as_restore_type, template)
    with ocp.PyTreeCheckpointer() as ckptr:
        return ckptr.restore(tree_dir, item=template, restore_args=restore_args)


def read_extra(directory: str) -> Dict:
    path = os.path.join(directory, "trainer_state.json")
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)


def save_pretrained(
    directory: str,
    params: Any,
    transformer_config,
    tokenizer_path: Optional[str] = None,
) -> None:
    """Export model weights + architecture config in an interoperable layout:
    ``flax_model.msgpack`` (full param tree, host-gathered, fp32-preserving),
    ``trlx_tpu_config.json`` (the TransformerConfig fields), and — for
    architectures with an HF family mapping — a transformers-loadable
    ``pytorch_model.bin`` + ``config.json`` with heads merged under their
    reference prefixes (``accelerate_base_trainer.py:256-272``)."""
    import dataclasses

    from flax import serialization

    os.makedirs(directory, exist_ok=True)
    host_params = jax.tree_util.tree_map(lambda x: np.asarray(x), jax.device_get(params))
    with open(os.path.join(directory, "flax_model.msgpack"), "wb") as f:
        f.write(serialization.to_bytes(host_params))
    cfg = {
        k: (str(v) if k in ("param_dtype", "dtype") else v)
        for k, v in dataclasses.asdict(transformer_config).items()
    }
    cfg["framework"] = "trlx_tpu"
    if tokenizer_path is not None:
        cfg["tokenizer_path"] = tokenizer_path
    with open(os.path.join(directory, "trlx_tpu_config.json"), "w") as f:
        json.dump(cfg, f, indent=2)

    # HF torch export (reference save_pretrained contract) whenever the
    # architecture maps to a transformers family; writes pytorch_model.bin +
    # config.json with value/Q heads merged under their reference prefixes.
    # torch/transformers are optional deps — the native msgpack export above
    # must survive without them.
    if getattr(transformer_config, "model_type", None) is not None:
        try:
            from trlx_tpu.models.hf_interop import UnsupportedHFExport, save_pretrained_hf

            try:
                save_pretrained_hf(
                    directory, host_params, transformer_config, tokenizer_path
                )
            except UnsupportedHFExport as e:
                # no transformers family mapping — the native msgpack export
                # above stands alone; genuine conversion bugs still propagate
                from trlx_tpu.utils import logging

                logging.get_logger(__name__).warning(
                    f"Skipping HF-format export ({e}); flax_model.msgpack was written"
                )
        except ImportError as e:
            from trlx_tpu.utils import logging

            logging.get_logger(__name__).warning(
                f"Skipping HF-format export (torch/transformers unavailable: {e}); "
                f"flax_model.msgpack was written"
            )


def push_to_hub(
    repo_id: str,
    params: Any,
    transformer_config,
    tokenizer_path: Optional[str] = None,
    private: bool = True,
    commit_message: str = "Upload trlx_tpu model",
    token: Optional[str] = None,
    staging_dir: Optional[str] = None,
    uploader=None,
) -> str:
    """Publish a ``save_pretrained`` export to the Hugging Face Hub
    (reference capability: ``modeling_base.py:30`` inherits
    ``transformers.utils.PushToHubMixin`` so wrapped models can
    ``push_to_hub``).

    Offline-safe by construction: the payload is always staged locally via
    :func:`save_pretrained` first (``staging_dir``, or a temp dir), then
    uploaded in one ``upload_folder`` call. ``uploader`` — a callable
    ``(repo_id, staged_dir) -> url`` — replaces the network step for tests
    or custom transports; without it ``huggingface_hub`` is required and a
    missing install/token raises with a clear message instead of a partial
    upload.

    Returns the commit/repo URL reported by the upload step.
    """
    import shutil
    import tempfile

    api = None
    if uploader is None:
        # fail before the (potentially multi-GB, minutes-long) staging work,
        # not after it
        try:
            from huggingface_hub import HfApi
        except ImportError as e:
            raise RuntimeError(
                "push_to_hub needs the huggingface_hub package for the "
                f"upload step ({e}); install it, or pass uploader= to "
                "supply your own transport"
            ) from e
        api = HfApi(token=token)

    staged = staging_dir or tempfile.mkdtemp(prefix="trlx_tpu_hub_")
    cleanup = staging_dir is None
    try:
        save_pretrained(staged, params, transformer_config, tokenizer_path)
        if uploader is not None:
            return str(uploader(repo_id, staged))
        api.create_repo(repo_id, private=private, exist_ok=True)
        info = api.upload_folder(
            repo_id=repo_id, folder_path=staged, commit_message=commit_message
        )
        return str(getattr(info, "commit_url", info))
    except Exception:
        # keep the staged export for manual recovery instead of deleting the
        # very files the user would upload by hand
        cleanup = False
        from trlx_tpu.utils import logging

        logging.get_logger(__name__).error(
            f"push_to_hub failed after staging; export kept at {staged}"
        )
        raise
    finally:
        if cleanup:
            shutil.rmtree(staged, ignore_errors=True)


def load_pretrained_params(directory: str, template: Any) -> Any:
    """Load ``flax_model.msgpack`` into the structure of ``template``."""
    from flax import serialization

    with open(os.path.join(directory, "flax_model.msgpack"), "rb") as f:
        return serialization.from_bytes(template, f.read())
