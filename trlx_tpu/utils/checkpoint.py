"""Checkpointing: Orbax-backed sharded pytree save/restore + HF-style export.

Reference equivalents: ``AccelerateRLTrainer.save/load`` delegate to
``accelerator.save_state/load_state`` (``accelerate_base_trainer.py:274-280``)
and ``save_pretrained`` exports an HF-format directory (``:256-272``). Here
the full train state (params + optimizer state + step) goes through Orbax —
sharded arrays save/restore in their mesh layout without gathering to one
host — and ``save_pretrained`` writes a flax msgpack + config JSON.
"""

import json
import os
import shutil
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

# Process-wide async checkpointer: device arrays are snapshotted
# synchronously but serialization/IO runs on background threads, so the
# train loop resumes immediately (the reference's accelerator.save_state
# blocks; at multi-GB states that is seconds-to-minutes per interval).
_ASYNC_CKPTR = None

# Atomic-commit protocol (docs/RESILIENCE.md): a save stages into
# ``state.staging`` and only *replaces* ``state`` — rename + commit-marker
# write — after the (a)sync write has fully landed. The pre-existing tree is
# therefore restorable at every instant of a save; the old rmtree-before-
# write flow had a crash window with ZERO restorable checkpoints. For async
# saves the commit closure is deferred until the write is joined
# (``wait_for_saves`` — called by the next save, any restore, and end of
# training), so the hot loop still returns immediately.
COMMIT_MARKER = "COMMITTED"

_PENDING_COMMIT: Optional[Callable[[], None]] = None


def _is_primary() -> bool:
    """Multihost: exactly one process owns the host-side checkpoint files
    (extra JSON, topology manifest, commit marker, swap renames). The Orbax
    tree write itself is collective — every process writes its own shards —
    but the commit protocol must have a single author or the renames race."""
    return jax.process_index() == 0


def _commit_barrier(name: str) -> None:
    """Line up every process at a commit-protocol edge. No-op single
    process. All ``wait_for_saves`` call sites run in SPMD lockstep (save/
    restore/prune/end-of-learn), so the matching calls always pair up."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(f"trlx_tpu_ckpt_{name}")


def _async_checkpointer():
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        import orbax.checkpoint as ocp

        _ASYNC_CKPTR = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    return _ASYNC_CKPTR


def wait_for_saves() -> None:
    """Block until every in-flight async save has landed AND committed
    (staging renamed over ``state``, marker written). Called before reads/
    overwrites of checkpoint directories and at end of training — an
    unawaited final save could otherwise be lost with the process."""
    global _PENDING_COMMIT
    if _ASYNC_CKPTR is not None:
        _ASYNC_CKPTR.wait_until_finished()
    commit, _PENDING_COMMIT = _PENDING_COMMIT, None
    if commit is not None:
        commit()


def _recover_interrupted_swap(directory: str) -> None:
    """Heal a directory whose overwrite-commit crashed between the two
    renames: the previous tree sits complete in ``state.old`` with no
    ``state`` beside it — move it back so the checkpoint is restorable
    again. Called before any save into / restore from ``directory``."""
    tree_dir = os.path.join(os.path.abspath(directory), "state")
    old_dir = tree_dir + ".old"
    if os.path.isdir(old_dir) and not os.path.isdir(tree_dir):
        try:
            os.rename(old_dir, tree_dir)
        except OSError:  # a peer process healed it first (multihost restore)
            if not os.path.isdir(tree_dir):
                raise


def is_committed(directory: str) -> bool:
    """Does ``directory`` hold a complete, committed checkpoint?

    True when the commit marker is present alongside a complete tree —
    either ``state``, or ``state.old`` left by a crash mid-swap (healed by
    :func:`_recover_interrupted_swap` at the next save/restore) — or, for
    checkpoints written before the marker protocol, when the ``state`` tree
    exists with no staging/swap remnants beside it. Partial dirs (a crash
    mid-save) fail every test and must be skipped by resume/rollback."""
    directory = os.path.abspath(directory)
    tree_dir = os.path.join(directory, "state")
    has_tree = os.path.isdir(tree_dir) or os.path.isdir(tree_dir + ".old")
    if not has_tree:
        return False
    if os.path.exists(os.path.join(directory, COMMIT_MARKER)):
        return True
    # legacy (pre-marker) layout: the tree was written in place, so its
    # existence is the only signal — but staging/old remnants mean a newer
    # save died mid-swap and the tree's vintage is ambiguous
    return (
        os.path.isdir(tree_dir)
        and not os.path.exists(tree_dir + ".staging")
        and not os.path.exists(tree_dir + ".old")
    )


def _checkpoint_step_dirs(root: str) -> List[Tuple[int, str]]:
    """``(step, path)`` for every ``checkpoint_<int>`` dir under ``root``,
    numerically sorted (zero-padding width varies with total_steps). The
    directory scan itself is sorted too: the output order never depends on
    filesystem enumeration, even transiently (GL903)."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in sorted(os.listdir(root)):
        if not name.startswith("checkpoint_"):
            continue
        try:
            step = int(name.rsplit("_", 1)[1])
        except ValueError:
            continue
        path = os.path.join(root, name)
        if os.path.isdir(path):
            out.append((step, path))
    return sorted(out)


def newest_committed_checkpoint(root: str) -> Optional[str]:
    """The highest-step committed ``checkpoint_<int>`` dir under ``root``,
    or None. The update guard's rollback and ``maybe_resume`` both restore
    only from here — never from a partial save."""
    wait_for_saves()  # a same-process save may still be pending its commit
    for _step, path in reversed(_checkpoint_step_dirs(root)):
        if is_committed(path):
            return path
    return None


def prune_checkpoints(root: str, keep_last_n: int) -> List[str]:
    """Retention ring: delete committed ``checkpoint_<int>`` dirs beyond the
    newest ``keep_last_n``. Uncommitted/partial dirs and ``best_checkpoint``
    are never touched; 0 disables. Returns the pruned paths."""
    if keep_last_n <= 0:
        return []
    wait_for_saves()  # never prune under an in-flight save
    committed = [p for _s, p in _checkpoint_step_dirs(root) if is_committed(p)]
    pruned = committed[:-keep_last_n] if keep_last_n else []
    for path in pruned:
        shutil.rmtree(path, ignore_errors=True)
    return pruned


def save_state(  # acquires: ckpt-staging(object)
    directory: str, state: Any, extra: Optional[Dict] = None, async_save: bool = True
) -> None:
    """Save a train-state pytree (+ small JSON ``extra``) to ``directory``
    with an atomic commit: the previous checkpoint stays restorable until
    the replacement has fully landed.

    ``async_save`` returns as soon as the device arrays are snapshotted; IO
    completes in the background and the commit (staging → ``state`` rename,
    marker write) runs when the save is next joined (``wait_for_saves``).
    """
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    tree_dir = os.path.join(directory, "state")
    staging_dir = tree_dir + ".staging"
    # join + commit any in-flight save before touching shared paths
    wait_for_saves()
    primary = _is_primary()
    if primary:
        os.makedirs(directory, exist_ok=True)
        _recover_interrupted_swap(directory)
        if os.path.exists(staging_dir):  # leftover from a crashed save: garbage
            shutil.rmtree(staging_dir)
    # non-primary processes must not start writing shards into a staging
    # dir the primary is still clearing
    _commit_barrier("pre_stage")
    # extra JSON and the topology manifest stage alongside the tree: a
    # crash pre-commit must not mix a new iter_count (or a new mesh shape)
    # with the old params. Host-side files have a single author (primary).
    extra_path = os.path.join(directory, "trainer_state.json")
    if extra is not None and primary:
        with open(extra_path + ".staging", "w") as f:
            json.dump(extra, f)
    from trlx_tpu.resilience.elastic import MANIFEST_NAME, build_manifest

    # the manifest is authored (and consumed at commit) only by the primary;
    # peers skip the per-leaf tree walk
    manifest = build_manifest(state) if primary else None
    manifest_path = os.path.join(directory, MANIFEST_NAME)
    if manifest is not None and primary:
        with open(manifest_path + ".staging", "w") as f:
            json.dump(manifest, f)

    def commit() -> None:  # releases: ckpt-staging(object)
        from trlx_tpu.resilience.faults import InjectedFault, poll_fault

        # every process polls (identical plans keep counters in lockstep),
        # and every process raises — BEFORE the barrier, so an injected
        # crash can't strand a peer waiting on a dead primary
        if poll_fault("crash_save"):
            raise InjectedFault(
                f"fault plan: crash before checkpoint commit ({directory})"
            )
        _commit_barrier("pre_commit")  # all shards landed before any rename
        try:
            if primary:
                # Swap order keeps SOME complete tree recoverable at every
                # instant: the marker is never deleted (it vouches for
                # whichever complete tree is present), the old tree moves
                # aside intact, and a crash between the renames is healed by
                # _recover_interrupted_swap (old tree moved back) on the next
                # save/restore of this directory.
                marker = os.path.join(directory, COMMIT_MARKER)
                old_dir = tree_dir + ".old"
                if os.path.exists(old_dir):
                    shutil.rmtree(old_dir)
                if os.path.exists(tree_dir):
                    os.rename(tree_dir, old_dir)
                else:
                    old_dir = None
                os.rename(staging_dir, tree_dir)
                if extra is not None:
                    os.replace(extra_path + ".staging", extra_path)
                if manifest is not None:
                    os.replace(manifest_path + ".staging", manifest_path)
                elif os.path.exists(manifest_path):
                    # a manifest-less save over a manifested checkpoint: a
                    # stale topology record would mislead the next elastic
                    # restore
                    os.remove(manifest_path)
                with open(marker, "w") as f:
                    json.dump({"time": time.time()}, f)
                if old_dir is not None:
                    shutil.rmtree(old_dir)
        finally:
            # peers must not read (or exit) until the marker is down — and
            # the barrier must be reached even when the primary's commit IO
            # raises (disk full on a rename, marker write failure): peers
            # are already blocked in the timeout-less post_commit collective,
            # so a pre-barrier raise would hang the whole slice instead of
            # failing the job with the real error (the peers then die with
            # the primary via the coordination service)
            _commit_barrier("post_commit")

    if async_save:
        global _PENDING_COMMIT
        _async_checkpointer().save(staging_dir, state)
        _PENDING_COMMIT = commit
    else:
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(staging_dir, state)
        commit()


def restore_state(directory: str, template: Any) -> Any:
    """Restore a pytree saved by :func:`save_state`.

    ``template`` (the current in-memory state) supplies structure, dtypes,
    and shardings, so restored arrays land directly on the mesh.
    """
    import orbax.checkpoint as ocp

    wait_for_saves()  # the checkpoint being restored may still be in flight
    directory = os.path.abspath(directory)
    _recover_interrupted_swap(directory)
    tree_dir = os.path.join(directory, "state")

    def as_restore_type(x):
        if isinstance(x, jax.Array) and hasattr(x, "sharding"):
            return ocp.type_handlers.ArrayRestoreArgs(
                sharding=x.sharding, global_shape=x.shape, dtype=x.dtype
            )
        return ocp.type_handlers.RestoreArgs()

    restore_args = jax.tree_util.tree_map(as_restore_type, template)
    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(tree_dir, item=template, restore_args=restore_args)
    # Donation hazard: buffers handed out by the Orbax restore, when donated
    # into a train-step executable DESERIALIZED from the persistent compile
    # cache, corrupt the process heap (observed as a segfault/glibc abort in
    # the first post-restore step — the long-standing crash under
    # tests/test_trainers.py::test_auto_resume_from_checkpoint). Re-land
    # them as fresh standard device buffers, freeing each Orbax buffer as
    # soon as its copy lands so peak memory stays one-leaf-above the state
    # size (a whole-tree copy would transiently need 2× state HBM).
    import jax.numpy as jnp

    def reland(x):
        if not isinstance(x, jax.Array):
            return x
        y = jnp.copy(x)
        y.block_until_ready()
        x.delete()
        return y

    return jax.tree_util.tree_map(reland, restored)


def read_extra(directory: str) -> Dict:
    path = os.path.join(directory, "trainer_state.json")
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)


def save_pretrained(
    directory: str,
    params: Any,
    transformer_config,
    tokenizer_path: Optional[str] = None,
) -> None:
    """Export model weights + architecture config in an interoperable layout:
    ``flax_model.msgpack`` (full param tree, host-gathered, fp32-preserving),
    ``trlx_tpu_config.json`` (the TransformerConfig fields), and — for
    architectures with an HF family mapping — a transformers-loadable
    ``pytorch_model.bin`` + ``config.json`` with heads merged under their
    reference prefixes (``accelerate_base_trainer.py:256-272``)."""
    import dataclasses

    from flax import serialization

    os.makedirs(directory, exist_ok=True)
    host_params = jax.tree_util.tree_map(lambda x: np.asarray(x), jax.device_get(params))
    with open(os.path.join(directory, "flax_model.msgpack"), "wb") as f:
        f.write(serialization.to_bytes(host_params))
    cfg = {
        k: (str(v) if k in ("param_dtype", "dtype") else v)
        for k, v in dataclasses.asdict(transformer_config).items()
    }
    cfg["framework"] = "trlx_tpu"
    if tokenizer_path is not None:
        cfg["tokenizer_path"] = tokenizer_path
    with open(os.path.join(directory, "trlx_tpu_config.json"), "w") as f:
        json.dump(cfg, f, indent=2)

    # HF torch export (reference save_pretrained contract) whenever the
    # architecture maps to a transformers family; writes pytorch_model.bin +
    # config.json with value/Q heads merged under their reference prefixes.
    # torch/transformers are optional deps — the native msgpack export above
    # must survive without them.
    if getattr(transformer_config, "model_type", None) is not None:
        try:
            from trlx_tpu.models.hf_interop import UnsupportedHFExport, save_pretrained_hf

            try:
                save_pretrained_hf(
                    directory, host_params, transformer_config, tokenizer_path
                )
            except UnsupportedHFExport as e:
                # no transformers family mapping — the native msgpack export
                # above stands alone; genuine conversion bugs still propagate
                from trlx_tpu.utils import logging

                logging.get_logger(__name__).warning(
                    f"Skipping HF-format export ({e}); flax_model.msgpack was written"
                )
        except ImportError as e:
            from trlx_tpu.utils import logging

            logging.get_logger(__name__).warning(
                f"Skipping HF-format export (torch/transformers unavailable: {e}); "
                f"flax_model.msgpack was written"
            )


def push_to_hub(
    repo_id: str,
    params: Any,
    transformer_config,
    tokenizer_path: Optional[str] = None,
    private: bool = True,
    commit_message: str = "Upload trlx_tpu model",
    token: Optional[str] = None,
    staging_dir: Optional[str] = None,
    uploader=None,
) -> str:
    """Publish a ``save_pretrained`` export to the Hugging Face Hub
    (reference capability: ``modeling_base.py:30`` inherits
    ``transformers.utils.PushToHubMixin`` so wrapped models can
    ``push_to_hub``).

    Offline-safe by construction: the payload is always staged locally via
    :func:`save_pretrained` first (``staging_dir``, or a temp dir), then
    uploaded in one ``upload_folder`` call. ``uploader`` — a callable
    ``(repo_id, staged_dir) -> url`` — replaces the network step for tests
    or custom transports; without it ``huggingface_hub`` is required and a
    missing install/token raises with a clear message instead of a partial
    upload.

    Returns the commit/repo URL reported by the upload step.
    """
    import shutil
    import tempfile

    api = None
    if uploader is None:
        # fail before the (potentially multi-GB, minutes-long) staging work,
        # not after it
        try:
            from huggingface_hub import HfApi
        except ImportError as e:
            raise RuntimeError(
                "push_to_hub needs the huggingface_hub package for the "
                f"upload step ({e}); install it, or pass uploader= to "
                "supply your own transport"
            ) from e
        api = HfApi(token=token)

    staged = staging_dir or tempfile.mkdtemp(prefix="trlx_tpu_hub_")
    cleanup = staging_dir is None
    try:
        save_pretrained(staged, params, transformer_config, tokenizer_path)
        if uploader is not None:
            return str(uploader(repo_id, staged))
        api.create_repo(repo_id, private=private, exist_ok=True)
        info = api.upload_folder(
            repo_id=repo_id, folder_path=staged, commit_message=commit_message
        )
        return str(getattr(info, "commit_url", info))
    except Exception:
        # keep the staged export for manual recovery instead of deleting the
        # very files the user would upload by hand
        cleanup = False
        from trlx_tpu.utils import logging

        logging.get_logger(__name__).error(
            f"push_to_hub failed after staging; export kept at {staged}"
        )
        raise
    finally:
        if cleanup:
            shutil.rmtree(staged, ignore_errors=True)


def load_pretrained_params(directory: str, template: Any) -> Any:
    """Load ``flax_model.msgpack`` into the structure of ``template``."""
    from flax import serialization

    with open(os.path.join(directory, "flax_model.msgpack"), "rb") as f:
        return serialization.from_bytes(template, f.read())
