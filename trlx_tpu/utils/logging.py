"""Library-wide logging with per-process rank awareness.

Equivalent in behavior to the reference's logging subsystem
(``trlx/utils/logging.py:47-340``): a package-level verbosity controlled by the
``TRLX_TPU_VERBOSITY`` env var, loggers that prefix messages with the JAX
process index, and a ``ranks=`` kwarg to restrict a record to specific hosts.
"""

import logging
import os
import sys
import threading
from typing import List, Optional

_lock = threading.Lock()
_default_handler: Optional[logging.Handler] = None

CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARNING = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG
NOTSET = logging.NOTSET

_log_levels = {
    "critical": CRITICAL,
    "error": ERROR,
    "warning": WARNING,
    "info": INFO,
    "debug": DEBUG,
}

_default_log_level = logging.INFO


def _get_default_level() -> int:
    env = os.getenv("TRLX_TPU_VERBOSITY", None)
    if env:
        if env.lower() in _log_levels:
            return _log_levels[env.lower()]
        logging.getLogger().warning(
            f"Unknown TRLX_TPU_VERBOSITY={env}, must be one of {list(_log_levels)}"
        )
    return _default_log_level


def _root_name() -> str:
    return __name__.split(".")[0]  # "trlx_tpu"


def _configure_root():
    global _default_handler
    with _lock:
        if _default_handler:
            return
        _default_handler = logging.StreamHandler(sys.stdout)
        _default_handler.setFormatter(
            logging.Formatter(
                "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s",
                datefmt="%H:%M:%S",
            )
        )
        root = logging.getLogger(_root_name())
        root.addHandler(_default_handler)
        root.setLevel(_get_default_level())
        root.propagate = False


def _process_index() -> int:
    # Cheap: prefer env (set before jax.distributed init) over importing jax.
    for var in ("TRLX_TPU_PROCESS_ID", "JAX_PROCESS_INDEX", "RANK"):
        if var in os.environ:
            try:
                return int(os.environ[var])
            except ValueError:
                pass
    # Read the distributed-runtime state WITHOUT initializing a backend:
    # ``jax.process_index()`` would trigger backend init, which on a
    # contended/wedged TPU blocks for minutes — a log prefix must never
    # touch the accelerator (bit the sweep CLI: its first log line hung).
    try:
        from jax._src import distributed

        pid = distributed.global_state.process_id
        return int(pid) if pid is not None else 0
    except Exception:
        return 0


class MultiProcessAdapter(logging.LoggerAdapter):
    """Logs only on selected processes; prefixes messages with the rank.

    ``logger.info(msg, ranks=[0])`` emits on process 0 only (default).
    ``ranks=[-1]`` emits everywhere.
    """

    def log(self, level, msg, *args, **kwargs):
        ranks = kwargs.pop("ranks", [0])
        idx = _process_index()
        if idx in ranks or -1 in ranks:
            if self.isEnabledFor(level):
                msg, kwargs = self.process(f"[RANK {idx}] {msg}", kwargs)
                self.logger.log(level, msg, *args, **kwargs)


def get_logger(name: Optional[str] = None) -> MultiProcessAdapter:
    """Return a rank-aware logger under the trlx_tpu namespace."""
    _configure_root()
    if name is None:
        name = _root_name()
    elif not name.startswith(_root_name()):
        name = f"{_root_name()}.{name}"
    return MultiProcessAdapter(logging.getLogger(name), {})


def get_verbosity() -> int:
    _configure_root()
    return logging.getLogger(_root_name()).getEffectiveLevel()


def set_verbosity(verbosity: int) -> None:
    _configure_root()
    logging.getLogger(_root_name()).setLevel(verbosity)


def set_verbosity_debug():
    set_verbosity(DEBUG)


def set_verbosity_info():
    set_verbosity(INFO)


def set_verbosity_warning():
    set_verbosity(WARNING)


def set_verbosity_error():
    set_verbosity(ERROR)


def enable_explicit_format() -> None:
    _configure_root()


def disable_progress_bars() -> bool:
    os.environ["TRLX_TPU_NO_TQDM"] = "1"
    return True


def progress_bars_disabled() -> bool:
    return os.environ.get("TRLX_TPU_NO_TQDM", "0") == "1"


def tqdm(*args, **kwargs):
    """Verbosity-aware progress bar (reference ``_tqdm_cls``,
    ``trlx/utils/logging.py:305-330``); honors ``TRLX_TPU_NO_TQDM``."""
    from tqdm import auto

    kwargs["disable"] = bool(kwargs.get("disable")) or progress_bars_disabled()
    return auto.tqdm(*args, **kwargs)
