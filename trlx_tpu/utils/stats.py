"""Statistics helpers: masked moments, whitening, running moments, logprobs.

Reference equivalents: ``trlx/utils/modeling.py`` — ``get_global_statistics:190``,
``whiten:205``, ``logprobs_of_labels:218``, ``get_tensor_stats:243``,
``RunningMoments:256``. The reference's explicit ``dist.all_reduce`` cross-rank
reductions disappear here: under a global mesh the arrays are already global,
so a plain ``jnp.mean`` *is* the distributed mean. ``RunningMoments`` runs
host-side on the reward stream (the one inherently-host part of the pipeline).
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def masked_mean(xs: jax.Array, mask: Optional[jax.Array] = None) -> jax.Array:
    if mask is None:
        return jnp.mean(xs)
    mask = mask.astype(xs.dtype)
    return jnp.sum(xs * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def masked_var(
    xs: jax.Array, mask: Optional[jax.Array] = None, ddof: int = 0
) -> jax.Array:
    mean = masked_mean(xs, mask)
    n = mask.sum() if mask is not None else float(np.prod(xs.shape))
    sq = masked_mean(jnp.square(xs - mean), mask)
    if ddof:
        sq = sq * (n / jnp.maximum(n - ddof, 1.0))
    return sq


def whiten(
    xs: jax.Array, mask: Optional[jax.Array] = None, shift_mean: bool = True
) -> jax.Array:
    """Normalize to zero mean / unit variance (masked, globally under pjit).

    Uses the unbiased (``ddof=1``) variance, matching the reference's
    *single-process* convention (``trlx/utils/modeling.py:205-215`` whitens
    with ``torch.var_mean``, Bessel-corrected by default) — pinned by
    ``tests/test_parity_golden.py``. Parity is with that single-process path
    only: the reference's distributed branch (``get_global_statistics:190``,
    taken under ``dist.is_initialized()``) accumulates a *biased* variance
    across ranks, so multi-GPU reference runs whiten slightly differently.
    Under a global mesh there is exactly one code path — this one.
    """
    mean = masked_mean(xs, mask)
    var = masked_var(xs, mask, ddof=1)
    whitened = (xs - mean) * jax.lax.rsqrt(var + 1e-8)
    if not shift_mean:
        whitened = whitened + mean
    return whitened


def logprobs_of_labels(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Log-probabilities of ``labels`` under ``logits``: [B, T, V],[B, T]→[B, T].

    Matches reference semantics (``trlx/utils/modeling.py:218-226``): caller is
    responsible for the one-position shift between logits and labels.
    """
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logprobs, labels[..., None], axis=-1)[..., 0]


def get_tensor_stats(xs: jax.Array, mask: jax.Array, n: jax.Array) -> dict:
    """Mean/min/max/std of a masked tensor, as a flat dict of scalars."""
    mean = jnp.sum(xs * mask) / n
    minimum = jnp.min(jnp.where(mask > 0, xs, jnp.inf))
    maximum = jnp.max(jnp.where(mask > 0, xs, -jnp.inf))
    std = jnp.sqrt(jnp.sum(jnp.square(xs - mean) * mask) / jnp.maximum(n, 1.0))
    return dict(mean=mean, min=minimum, max=maximum, std=std)


class RunningMoments:
    """Streaming mean/std over reward batches (Chan et al. parallel variance).

    Host-side numpy; in multi-host runs pass the *globally gathered* rewards
    (every host must fold identical statistics into the compiled program).
    Reference: ``trlx/utils/modeling.py:256-288``.
    """

    def __init__(self):
        self.mean = 0.0
        self.std = 1.0
        self.var = 1.0
        self.count = 1e-24

    def update(self, xs: np.ndarray) -> Tuple[float, float]:
        """Fold a batch in; returns (batch_mean, batch_std-with-Bessel)."""
        xs = np.asarray(xs, dtype=np.float64).reshape(-1)
        xs_count = xs.size
        xs_mean = float(xs.mean())
        xs_var = float(xs.var())

        delta = xs_mean - self.mean
        tot_count = self.count + xs_count

        new_sum = xs_var * xs_count
        old_sum = self.var * self.count + delta**2 * self.count * xs_count / tot_count
        tot_sum = old_sum + new_sum

        self.mean += delta * xs_count / tot_count
        self.var = tot_sum / tot_count
        self.std = float(np.sqrt(self.var * tot_count / max(tot_count - 1, 1)))
        self.count = tot_count

        return xs_mean, float(np.sqrt(xs_var * xs_count / max(xs_count - 1, 1)))
