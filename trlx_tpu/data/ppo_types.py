"""PPO batch datatypes.

Reference: ``trlx/data/ppo_types.py``. Host-side elements are numpy (ragged,
per-sample); device batches are fixed-shape jax arrays with masks — the
TPU redesign of the reference's ragged tensors (static shapes for jit).
"""

from dataclasses import dataclass
from typing import NamedTuple, Optional

import jax
import numpy as np


@dataclass
class PPORLElement:
    """One collected experience (host side, ragged numpy).

    :param query_tensor: prompt token ids [Q]
    :param response_tensor: sampled response ids [R]
    :param logprobs: proximal-anchor logprobs per response token [R] (the
        scoring forward; on the serial path these ARE the behavior policy's)
    :param values: value predictions per response token [R]
    :param rewards: per-token rewards (KL penalty + score at end) [R]
    :param behavior_logprobs: the sampler's exact per-token logprobs [R] —
        only populated by async collection with ``method.iw_correction``
        on, where in-flight weight sync makes them a param-version mixture
        distinct from ``logprobs`` (docs/ASYNC_RL.md). None elsewhere.
    """

    query_tensor: np.ndarray
    response_tensor: np.ndarray
    logprobs: np.ndarray
    values: np.ndarray
    rewards: np.ndarray
    behavior_logprobs: Optional[np.ndarray] = None


class PPORLBatch(NamedTuple):
    """A fixed-shape batch of experiences (device side).

    query_tensors are left-padded, response_tensors right-padded, matching the
    reference collator (``trlx/pipeline/ppo_pipeline.py:43-71``); masks carry
    the ragged structure.
    """

    query_tensors: jax.Array  # [B, Q] int32, left-padded
    response_tensors: jax.Array  # [B, R] int32, right-padded
    logprobs: jax.Array  # [B, R] float32
    values: jax.Array  # [B, R] float32
    rewards: jax.Array  # [B, R] float32
    query_mask: jax.Array  # [B, Q] 1 on real prompt tokens
    response_mask: jax.Array  # [B, R] 1 on real response tokens
    # None unless async collection recorded distinct behavior logprobs
    # (train_step's array filter drops a None transparently)
    behavior_logprobs: Optional[jax.Array] = None
