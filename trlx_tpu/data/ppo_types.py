"""PPO batch datatypes.

Reference: ``trlx/data/ppo_types.py``. Host-side elements are numpy (ragged,
per-sample); device batches are fixed-shape jax arrays with masks — the
TPU redesign of the reference's ragged tensors (static shapes for jit).
"""

from dataclasses import dataclass
from typing import NamedTuple

import jax
import numpy as np


@dataclass
class PPORLElement:
    """One collected experience (host side, ragged numpy).

    :param query_tensor: prompt token ids [Q]
    :param response_tensor: sampled response ids [R]
    :param logprobs: behavior-policy logprobs per response token [R]
    :param values: value predictions per response token [R]
    :param rewards: per-token rewards (KL penalty + score at end) [R]
    """

    query_tensor: np.ndarray
    response_tensor: np.ndarray
    logprobs: np.ndarray
    values: np.ndarray
    rewards: np.ndarray


class PPORLBatch(NamedTuple):
    """A fixed-shape batch of experiences (device side).

    query_tensors are left-padded, response_tensors right-padded, matching the
    reference collator (``trlx/pipeline/ppo_pipeline.py:43-71``); masks carry
    the ragged structure.
    """

    query_tensors: jax.Array  # [B, Q] int32, left-padded
    response_tensors: jax.Array  # [B, R] int32, right-padded
    logprobs: jax.Array  # [B, R] float32
    values: jax.Array  # [B, R] float32
    rewards: jax.Array  # [B, R] float32
    query_mask: jax.Array  # [B, Q] 1 on real prompt tokens
    response_mask: jax.Array  # [B, R] 1 on real response tokens
