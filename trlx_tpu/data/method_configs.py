"""Method (algorithm) config registry.

Mirrors the public contract of the reference's method-config registry
(``trlx/data/method_configs.py:9-56``): algorithm hyperparameters live in a
dataclass registered by name, so new RL methods plug in without touching the
config system.
"""

from dataclasses import dataclass, fields
from typing import Any, Callable, Dict

# name (lowercase) -> MethodConfig subclass
_METHODS: Dict[str, type] = {}


def strict_from_dict(cls, config: Dict[str, Any]):
    """Construct a dataclass from a dict, rejecting unknown keys."""
    known = {f.name for f in fields(cls)}
    unknown = set(config) - known
    if unknown:
        raise ValueError(
            f"Unknown keys {sorted(unknown)} for {cls.__name__}; known: {sorted(known)}"
        )
    return cls(**config)


def register_method(name: Any = None) -> Callable:
    """Decorator registering a MethodConfig subclass under ``name``.

    Usable bare (``@register_method``) or with a string name
    (``@register_method("ppo")``).
    """

    def register_cls(cls, registered_name: str):
        _METHODS[registered_name.lower()] = cls
        setattr(cls, "name", registered_name)
        return cls

    if isinstance(name, type):  # bare decorator
        return register_cls(name, name.__name__)

    def wrap(cls):
        return register_cls(cls, name if isinstance(name, str) else cls.__name__)

    return wrap


@dataclass
@register_method
class MethodConfig:
    """Base config for an RL method.

    :param name: registry name of the method (e.g. ``"PPOConfig"``).
    :param dist_sketches: emit on-device distribution sketches of training
        dynamics from the loss (``dist/*_hist`` — observability/dynamics.py).
        Sketches are stop-gradient'd and ride the existing stats fetch, so
        disabling buys nothing but a few histogram scatters per step.
    """

    name: str = "MethodConfig"
    dist_sketches: bool = True

    @classmethod
    def from_dict(cls, config: Dict[str, Any]):
        return strict_from_dict(cls, config)

    def to_dict(self) -> Dict[str, Any]:
        return {f.name: getattr(self, f.name) for f in fields(self)}


def get_method(name: str) -> type:
    """Return the MethodConfig class registered under ``name``."""
    name = name.lower()
    if name in _METHODS:
        return _METHODS[name]
    raise ValueError(
        f"Unknown method config '{name}'. Registered: {sorted(_METHODS)}"
    )
