"""Tokenizer abstraction.

The framework needs only a small tokenizer surface (encode/decode/specials/
padding sides). Three providers:

- :class:`ByteTokenizer` — offline-friendly byte-level tokenizer (no vocab
  files needed); ids 0..255 are raw bytes, then bos/eos/pad.
- :class:`CharTokenizer` — tiny fixed-vocabulary tokenizer for synthetic
  tasks (the randomwalks example; reference:
  ``examples/randomwalks/randomwalks.py``).
- :class:`HFTokenizer` — thin adapter over ``transformers.AutoTokenizer``
  (used when checkpoints/vocab files are available locally).

``from_config`` dispatches on the ``tokenizer_path`` spec:
``"builtin:bytes"``, ``"builtin:chars:<alphabet>"``, else HF.
"""

from typing import Dict, List, Optional, Sequence, Union


class Tokenizer:
    """Minimal tokenizer interface used across the framework."""

    bos_token: str
    eos_token: str
    pad_token: str
    bos_token_id: int
    eos_token_id: int
    pad_token_id: int
    padding_side: str = "left"
    truncation_side: str = "right"
    vocab_size: int

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        raise NotImplementedError

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        raise NotImplementedError

    def batch_decode(self, batch: Sequence[Sequence[int]], skip_special_tokens: bool = True) -> List[str]:
        return [self.decode(ids, skip_special_tokens) for ids in batch]

    def __call__(
        self,
        text: Union[str, List[str]],
        truncation: bool = False,
        max_length: Optional[int] = None,
        add_special_tokens: bool = False,
        **_,
    ) -> Dict[str, list]:
        """HF-style call: returns dict with input_ids (+ attention_mask for
        batch input), truncating according to ``truncation_side``."""
        if isinstance(text, str):
            ids = self.encode(text, add_special_tokens)
            if truncation and max_length is not None:
                ids = self._truncate(ids, max_length)
            return {"input_ids": ids}
        outs = [self(t, truncation, max_length, add_special_tokens) for t in text]
        return {
            "input_ids": [o["input_ids"] for o in outs],
            "attention_mask": [[1] * len(o["input_ids"]) for o in outs],
        }

    def _truncate(self, ids: List[int], max_length: int) -> List[int]:
        if len(ids) <= max_length:
            return ids
        if self.truncation_side == "left":
            return ids[len(ids) - max_length :]
        return ids[:max_length]


class ByteTokenizer(Tokenizer):
    """UTF-8 byte-level tokenizer: ids 0..255 = bytes, 256 = bos, 257 = eos,
    258 = pad. Needs no vocabulary files — the offline default."""

    def __init__(self, padding_side: str = "left", truncation_side: str = "right"):
        self.bos_token_id = 256
        self.eos_token_id = 257
        self.pad_token_id = 258
        self.vocab_size = 259
        self.bos_token = "<|bos|>"
        self.eos_token = "<|eos|>"
        self.pad_token = "<|pad|>"
        self.padding_side = padding_side
        self.truncation_side = truncation_side
        self._specials = {
            self.bos_token: self.bos_token_id,
            self.eos_token: self.eos_token_id,
            self.pad_token: self.pad_token_id,
        }

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        ids: List[int] = []
        rest = text
        while rest:
            # scan for special-token strings embedded in the text
            next_special, next_pos = None, len(rest)
            for tok in self._specials:
                pos = rest.find(tok)
                if pos != -1 and pos < next_pos:
                    next_special, next_pos = tok, pos
            ids.extend(rest[:next_pos].encode("utf-8"))
            if next_special is None:
                break
            ids.append(self._specials[next_special])
            rest = rest[next_pos + len(next_special) :]
        if add_special_tokens:
            ids = [self.bos_token_id] + ids
        return ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        out: List[str] = []
        buf: List[int] = []

        def flush():
            if buf:
                out.append(bytes(buf).decode("utf-8", errors="replace"))
                buf.clear()

        rev = {v: k for k, v in self._specials.items()}
        for i in ids:
            i = int(i)
            if i < 256:
                buf.append(i)
            else:
                flush()
                if not skip_special_tokens and i in rev:
                    out.append(rev[i])
        flush()
        return "".join(out)


class CharTokenizer(Tokenizer):
    """Fixed-alphabet character tokenizer for synthetic tasks: one id per
    character of ``alphabet``, then bos/eos/pad."""

    def __init__(
        self,
        alphabet: str,
        padding_side: str = "left",
        truncation_side: str = "right",
    ):
        self.alphabet = alphabet
        self._char_to_id = {c: i for i, c in enumerate(alphabet)}
        n = len(alphabet)
        self.bos_token_id = n
        self.eos_token_id = n + 1
        self.pad_token_id = n + 2
        self.vocab_size = n + 3
        self.bos_token = "<|bos|>"
        self.eos_token = "<|eos|>"
        self.pad_token = "<|pad|>"
        self.padding_side = padding_side
        self.truncation_side = truncation_side

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        ids: List[int] = []
        rest = text
        while rest:
            if rest.startswith(self.bos_token):
                ids.append(self.bos_token_id)
                rest = rest[len(self.bos_token) :]
            elif rest.startswith(self.eos_token):
                ids.append(self.eos_token_id)
                rest = rest[len(self.eos_token) :]
            elif rest.startswith(self.pad_token):
                ids.append(self.pad_token_id)
                rest = rest[len(self.pad_token) :]
            else:
                c = rest[0]
                if c not in self._char_to_id:
                    raise ValueError(f"Character {c!r} not in alphabet {self.alphabet!r}")
                ids.append(self._char_to_id[c])
                rest = rest[1:]
        if add_special_tokens:
            ids = [self.bos_token_id] + ids
        return ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        out = []
        for i in ids:
            i = int(i)
            if i < len(self.alphabet):
                out.append(self.alphabet[i])
            elif not skip_special_tokens:
                out.append(
                    {self.bos_token_id: self.bos_token, self.eos_token_id: self.eos_token}.get(
                        i, self.pad_token
                    )
                )
        return "".join(out)


class HFTokenizer(Tokenizer):
    """Adapter over a ``transformers`` tokenizer (local files only in this
    environment). Delegates everything; fills pad from eos if missing, as the
    reference does (``accelerate_base_trainer.py:60-66``)."""

    def __init__(self, path: str, padding_side: str = "left", truncation_side: str = "right"):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path)
        self._tok.padding_side = padding_side
        self._tok.truncation_side = truncation_side
        if self._tok.pad_token is None:
            self._tok.pad_token = "<|padding|>"
        self.padding_side = padding_side
        self.truncation_side = truncation_side

    def __getattr__(self, name):
        return getattr(self._tok, name)

    @property
    def vocab_size(self) -> int:  # include added tokens
        return len(self._tok)

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        return self._tok(text, add_special_tokens=add_special_tokens).input_ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode(ids, skip_special_tokens=skip_special_tokens)

    def __call__(self, text, truncation=False, max_length=None, add_special_tokens=False, **kw):
        return self._tok(
            text,
            truncation=truncation,
            max_length=max_length,
            add_special_tokens=add_special_tokens,
            **kw,
        )


def from_config(config) -> Tokenizer:
    """Build a tokenizer from a :class:`TokenizerConfig`."""
    path = config.tokenizer_path
    if path.startswith("builtin:"):
        spec = path.split(":", 1)[1]
        if spec == "bytes":
            return ByteTokenizer(config.padding_side, config.truncation_side)
        if spec.startswith("chars:"):
            return CharTokenizer(spec[len("chars:") :], config.padding_side, config.truncation_side)
        raise ValueError(f"Unknown builtin tokenizer spec: {path}")
    return HFTokenizer(path, config.padding_side, config.truncation_side)
