"""ILQL batch datatypes.

Reference: ``trlx/data/ilql_types.py``. The reference stores ragged
``actions_ixs``/``states_ixs`` index lists; here every element is padded to
fixed [B, T]/[B, A]/[B, S] blocks with ``dones`` doubling as the validity
mask, so batches trace into static-shape XLA programs.
"""

from dataclasses import dataclass, fields
from typing import NamedTuple

import jax
import numpy as np


def flatten_dataclass(cls: type):
    """dataclass/NamedTuple instance → tuple of fields (for PP transport)."""
    cls_fields = [f.name for f in fields(cls)] if hasattr(cls, "__dataclass_fields__") else list(cls._fields)

    def flatten(x) -> tuple:
        return tuple(getattr(x, f) for f in cls_fields)

    return flatten


def unflatten_dataclass(cls: type):
    """tuple of fields → dataclass/NamedTuple instance."""

    def unflatten(x: tuple):
        return cls(*x)

    return unflatten


@dataclass
class ILQLElement:
    """One offline experience (host side, ragged numpy)."""

    input_ids: np.ndarray  # [T]
    attention_mask: np.ndarray  # [T]
    rewards: np.ndarray  # [A]
    states_ixs: np.ndarray  # [S]
    actions_ixs: np.ndarray  # [A]
    dones: np.ndarray  # [S]


class ILQLBatch(NamedTuple):
    """Fixed-shape ILQL training batch (device side)."""

    input_ids: jax.Array  # [B, T]
    attention_mask: jax.Array  # [B, T]
    rewards: jax.Array  # [B, A]
    states_ixs: jax.Array  # [B, S] (S = A + 1)
    actions_ixs: jax.Array  # [B, A]
    dones: jax.Array  # [B, S]


@dataclass
class ILQLSeq2SeqElement:
    input_ids: np.ndarray
    attention_mask: np.ndarray
    decoder_input_ids: np.ndarray
    rewards: np.ndarray
    states_ixs: np.ndarray
    actions_ixs: np.ndarray
    dones: np.ndarray


class ILQLSeq2SeqBatch(NamedTuple):
    input_ids: jax.Array
    attention_mask: jax.Array
    decoder_input_ids: jax.Array
    rewards: jax.Array
    states_ixs: jax.Array
    actions_ixs: jax.Array
    dones: jax.Array
