"""GRPO batch datatypes (same host-ragged / device-fixed split as
``ppo_types``; no value or per-token reward fields — GRPO carries one
group-relative advantage per sequence and the frozen-reference logprobs for
the in-loss KL)."""

from dataclasses import dataclass
from typing import NamedTuple, Optional

import jax
import numpy as np


@dataclass
class GRPORLElement:
    """One collected experience (host side, ragged numpy)."""

    query_tensor: np.ndarray  # [Q]
    response_tensor: np.ndarray  # [R]
    logprobs: np.ndarray  # [R] proximal-anchor logprobs (scoring forward)
    ref_logprobs: np.ndarray  # [R] frozen-reference logprobs
    advantage: float  # group-relative, per sequence
    # sampler's exact behavior logprobs — async collection with
    # method.iw_correction on only (docs/ASYNC_RL.md); None elsewhere
    behavior_logprobs: Optional[np.ndarray] = None


class GRPORLBatch(NamedTuple):
    """A fixed-shape batch of experiences (device side)."""

    query_tensors: jax.Array  # [B, Q] int32, left-padded
    response_tensors: jax.Array  # [B, R] int32, right-padded
    logprobs: jax.Array  # [B, R] float32
    ref_logprobs: jax.Array  # [B, R] float32
    advantages: jax.Array  # [B] float32
    query_mask: jax.Array  # [B, Q]
    response_mask: jax.Array  # [B, R]
    # None unless async collection recorded distinct behavior logprobs
    behavior_logprobs: Optional[jax.Array] = None
