"""Canonical PPO/ILQL/SFT hyperparameter presets.

Hyperparameter-parity with the reference presets
(``trlx/data/default_configs.py:15-119``), with offline-friendly builtin model
paths (swap ``model_path``/``tokenizer_path`` for HF names in real runs).
"""

from trlx_tpu.data.configs import (
    ModelConfig,
    OptimizerConfig,
    ParallelConfig,
    SchedulerConfig,
    TokenizerConfig,
    TrainConfig,
    TRLConfig,
)
from trlx_tpu.models.dpo import DPOConfig
from trlx_tpu.models.grpo import GRPOConfig
from trlx_tpu.models.ilql import ILQLConfig
from trlx_tpu.models.ppo import PPOConfig
from trlx_tpu.models.sft import SFTConfig


def default_ppo_config() -> TRLConfig:
    return TRLConfig(
        train=TrainConfig(
            seq_length=1024,
            epochs=100,
            total_steps=10000,
            batch_size=32,
            checkpoint_interval=10000,
            eval_interval=100,
            pipeline="PromptPipeline",
            trainer="PPOTrainer",
        ),
        model=ModelConfig(model_path="builtin:gpt2-small", num_layers_unfrozen=2),
        tokenizer=TokenizerConfig(tokenizer_path="builtin:bytes", truncation_side="right"),
        optimizer=OptimizerConfig(
            name="adamw",
            kwargs=dict(lr=3e-5, betas=(0.9, 0.95), eps=1.0e-8, weight_decay=1.0e-6),
        ),
        scheduler=SchedulerConfig(
            name="cosine_annealing", kwargs=dict(T_max=1e12, eta_min=3e-5, lr=3e-5)
        ),
        method=PPOConfig(
            name="PPOConfig",
            num_rollouts=128,
            chunk_size=128,
            ppo_epochs=4,
            init_kl_coef=0.001,
            target=None,
            horizon=10000,
            gamma=1.0,
            lam=0.95,
            cliprange=0.2,
            cliprange_value=0.2,
            vf_coef=1.0,
            scale_reward="ignored",
            ref_mean=None,
            ref_std=None,
            cliprange_reward=10,
            gen_kwargs=dict(max_new_tokens=40, top_k=0, top_p=1.0, do_sample=True),
        ),
        parallel=ParallelConfig(),
    )


def default_ilql_config() -> TRLConfig:
    return TRLConfig(
        train=TrainConfig(
            seq_length=64,
            batch_size=128,
            epochs=100,
            total_steps=1000,
            checkpoint_interval=1000,
            eval_interval=100,
            pipeline="PromptPipeline",
            trainer="ILQLTrainer",
        ),
        model=ModelConfig(model_path="builtin:gpt2-small", num_layers_unfrozen=-1),
        tokenizer=TokenizerConfig(tokenizer_path="builtin:bytes", truncation_side="right"),
        optimizer=OptimizerConfig(
            name="adamw",
            kwargs=dict(lr=5.0e-5, betas=(0.9, 0.95), eps=1.0e-8, weight_decay=1.0e-6),
        ),
        scheduler=SchedulerConfig(
            name="cosine_annealing", kwargs=dict(T_max=1e12, eta_min=5.0e-5, lr=5.0e-5)
        ),
        method=ILQLConfig(
            name="ILQLConfig",
            tau=0.7,
            gamma=0.99,
            cql_scale=0.1,
            awac_scale=1.0,
            alpha=0.001,
            beta=0.0,
            steps_for_target_q_sync=5,
            two_qs=True,
            gen_kwargs=dict(max_new_tokens=56, top_k=20, beta=1.0, temperature=1.0),
        ),
        parallel=ParallelConfig(),
    )


def default_sft_config() -> TRLConfig:
    return TRLConfig(
        train=TrainConfig(
            seq_length=1024,
            epochs=100,
            total_steps=1000,
            batch_size=8,
            checkpoint_interval=10000,
            eval_interval=100,
            pipeline="PromptPipeline",
            trainer="SFTTrainer",
        ),
        model=ModelConfig(model_path="builtin:gpt2-small", num_layers_unfrozen=-1),
        tokenizer=TokenizerConfig(tokenizer_path="builtin:bytes", truncation_side="right"),
        optimizer=OptimizerConfig(
            name="adamw",
            kwargs=dict(lr=1.0e-4, betas=(0.9, 0.95), eps=1.0e-8, weight_decay=1.0e-6),
        ),
        scheduler=SchedulerConfig(
            name="cosine_annealing", kwargs=dict(T_max=1e12, eta_min=1.0e-4, lr=1.0e-4)
        ),
        method=SFTConfig(
            name="SFTConfig",
            gen_kwargs=dict(max_new_tokens=40, top_k=0, top_p=1.0, do_sample=True),
        ),
        parallel=ParallelConfig(),
    )


def default_grpo_config() -> TRLConfig:
    """GRPO preset (beyond the reference, which ships PPO/ILQL/SFT):
    DeepSeekMath-style defaults — group of 8, fixed in-loss KL beta, no
    value function."""
    return TRLConfig(
        train=TrainConfig(
            seq_length=1024,
            epochs=100,
            total_steps=10000,
            batch_size=32,
            checkpoint_interval=10000,
            eval_interval=100,
            pipeline="PromptPipeline",
            trainer="GRPOTrainer",
        ),
        model=ModelConfig(model_path="builtin:gpt2-small", num_layers_unfrozen=2),
        tokenizer=TokenizerConfig(tokenizer_path="builtin:bytes", truncation_side="right"),
        optimizer=OptimizerConfig(
            name="adamw",
            kwargs=dict(lr=1e-5, betas=(0.9, 0.95), eps=1.0e-8, weight_decay=1.0e-6),
        ),
        scheduler=SchedulerConfig(
            name="cosine_annealing", kwargs=dict(T_max=1e12, eta_min=1e-5, lr=1e-5)
        ),
        method=GRPOConfig(
            name="GRPOConfig",
            num_rollouts=128,
            chunk_size=128,
            ppo_epochs=2,
            group_size=8,
            beta=0.04,
            scale_advantage=True,
            cliprange=0.2,
            cliprange_reward=10,
            gen_kwargs=dict(max_new_tokens=40, top_k=0, top_p=1.0, do_sample=True),
        ),
        parallel=ParallelConfig(),
    )


def default_dpo_config() -> TRLConfig:
    """DPO preset (beyond the reference): direct preference optimization on
    (prompt, chosen, rejected) triples — no rollouts, no reward model."""
    return TRLConfig(
        train=TrainConfig(
            seq_length=1024,
            epochs=100,
            total_steps=2000,
            batch_size=16,
            checkpoint_interval=10000,
            eval_interval=100,
            pipeline="PromptPipeline",
            trainer="DPOTrainer",
        ),
        model=ModelConfig(model_path="builtin:gpt2-small", num_layers_unfrozen=-1),
        tokenizer=TokenizerConfig(tokenizer_path="builtin:bytes", truncation_side="left"),
        optimizer=OptimizerConfig(
            name="adamw",
            kwargs=dict(lr=5e-6, betas=(0.9, 0.95), eps=1.0e-8, weight_decay=1.0e-6),
        ),
        scheduler=SchedulerConfig(
            name="cosine_annealing", kwargs=dict(T_max=1e12, eta_min=5e-6, lr=5e-6)
        ),
        method=DPOConfig(
            name="DPOConfig",
            beta=0.1,
            label_smoothing=0.0,
            gen_kwargs=dict(max_new_tokens=40, top_k=0, top_p=1.0, do_sample=True),
        ),
        parallel=ParallelConfig(),
    )
