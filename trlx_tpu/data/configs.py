"""Typed config tree for trlx_tpu.

Public contract mirrors the reference (``trlx/data/configs.py:38-328``):
``TRLConfig`` with ``method/model/optimizer/scheduler/tokenizer/train``
sections, YAML loading, dot-path ``update`` and nested ``evolve``.

TPU-native addition: a ``parallel`` section (``ParallelConfig``) describing the
device mesh and numerics — what the reference pushes out to Accelerate/DeepSpeed
YAMLs (``configs/accelerate/*.yaml``) and NeMo Megatron YAMLs
(``configs/nemo_configs/*.yaml``) is a first-class, typed part of the config
here, because the mesh shapes the whole compiled program.
"""

import json
from copy import deepcopy
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

import yaml

from trlx_tpu.data.method_configs import MethodConfig, get_method, strict_from_dict

_strict_from_dict = strict_from_dict


def _merge_dicts(base: Dict, update: Dict) -> Dict:
    """Recursively merge ``update`` into a deep copy of ``base``."""
    base = deepcopy(base)
    for k, v in update.items():
        if isinstance(v, dict) and isinstance(base.get(k), dict):
            base[k] = _merge_dicts(base[k], v)
        else:
            base[k] = v
    return base


def _merge_strict(base: Dict, update: Dict, path: str = "") -> Dict:
    """Merge ``update`` into ``base`` in place, raising on any leaf path in
    ``update`` that does not already exist in ``base`` (typo protection —
    stricter than the reference, which only checks top-level section names).
    Exception: keys inside free-form ``kwargs``/``gen_kwargs`` dicts are
    accepted as-is."""
    free_form = path.endswith("kwargs") or path.endswith("gen_experience_kwargs")
    for k, v in update.items():
        here = f"{path}.{k}" if path else k
        if k not in base:
            if free_form:
                base[k] = v
                continue
            raise ValueError(
                f"parameter {here} is not present in the config (typo or wrong config)"
            )
        if isinstance(v, dict) and isinstance(base[k], dict):
            _merge_strict(base[k], v, here)
        else:
            base[k] = v
    return base


@dataclass
class ModelConfig:
    """Which model to train and how much of it to unfreeze.

    :param model_path: HF-style path/name, local directory, or a builtin spec
        string like ``"builtin:gpt2-small"`` (random-init, offline-friendly).
    :param model_arch_type: ``"causal"`` or ``"seq2seq"``.
    :param num_layers_unfrozen: trainable top-layer count; -1 = all layers.
        When >0, the frozen reference for PPO's KL is a *hydra branch*: the
        trunk is shared and only the top-k layers are duplicated (frozen), as
        in the reference's hydra heads (``trlx/models/modeling_ppo.py:331-427``).
    :param peft_kwargs: optional LoRA config, e.g. ``{"peft_type": "lora",
        "r": 8, "alpha": 16, "target_modules": ["attn_qkv", "attn_out"]}``
        (reference: OpenDelta kwargs, ``trlx/utils/modeling.py:389-450``).
    """

    model_path: str
    model_arch_type: str = "causal"
    num_layers_unfrozen: int = -1
    peft_kwargs: Optional[Dict[str, Any]] = None
    # Extra kwargs forwarded to the model builder (vocab override etc.)
    model_extra_kwargs: Dict[str, Any] = field(default_factory=dict)
    # Speculative decoding for rollout generation: a small same-vocab draft
    # model proposes ``draft_gamma`` tokens per round and the policy verifies
    # them in one forward (lossless — the sampled distribution is the
    # policy's; ``trlx_tpu/ops/speculative.py``). None disables.
    draft_model_path: Optional[str] = None
    draft_gamma: int = 4
    draft_model_extra_kwargs: Dict[str, Any] = field(default_factory=dict)

    from_dict = classmethod(_strict_from_dict)


@dataclass
class TokenizerConfig:
    """Tokenizer path and padding/truncation behavior.

    ``tokenizer_path`` may be an HF path or ``"builtin:bytes"`` for the
    offline byte-level tokenizer.
    """

    tokenizer_path: str
    padding_side: str = "left"
    truncation_side: str = "right"

    from_dict = classmethod(_strict_from_dict)


@dataclass
class OptimizerConfig:
    """Optax optimizer by name (``adamw``, ``adam``, ``sgd``, ``lion``,
    ``adafactor``) plus kwargs (lr, betas/b1/b2, eps, weight_decay)."""

    name: str
    kwargs: Dict[str, Any] = field(default_factory=dict)

    from_dict = classmethod(_strict_from_dict)


@dataclass
class SchedulerConfig:
    """LR schedule by name (``cosine_annealing``, ``linear``, ``constant``,
    ``warmup_cosine``) plus kwargs (warmup_steps, T_max, eta_min, ...)."""

    name: str
    kwargs: Dict[str, Any] = field(default_factory=dict)

    from_dict = classmethod(_strict_from_dict)


@dataclass
class ParallelConfig:
    """TPU mesh + numerics. The compiled-program analogue of the reference's
    Accelerate/DeepSpeed + NeMo parallelism YAMLs (``configs/accelerate/``,
    ``configs/nemo_configs/``).

    Mesh axes (product must equal the device count; -1 = infer one axis):

    :param data: pure data-parallel replicas (DDP analogue).
    :param fsdp: parameter/optimizer sharding axis (ZeRO-3/FSDP analogue —
        falls out of GSPMD sharding, no runtime machinery needed).
    :param pipe: pipeline-parallel stages (the reference's Apex/Megatron
        pipeline engine, ``trlx/models/modeling_nemo_ilql.py:426-442``,
        PP=4 for 65B ``configs/nemo_configs/megatron_65b.yaml:50``). Requires
        ``scan_layers``: the stacked block params shard their layer dim over
        this axis and a GPipe microbatch schedule rotates activations
        through the stages (``trlx_tpu/parallel/pipeline.py``).
    :param model: tensor-parallel axis (Megatron TP analogue).
    :param sequence: context/sequence-parallel axis for ring attention over
        long sequences (beyond the reference, which has only Megatron SP).
    :param expert: expert-parallel axis for mixture-of-experts models
        (mixtral family): expert weights shard here and token dispatch rides
        all_to_alls over this axis (beyond the reference, which has no MoE).
    :param pipe_microbatches: microbatches per pipeline round (GPipe schedule
        fill; the reference's NeMo micro-vs-global batch split,
        ``megatron_20b.yaml:51-52``). 0 = auto (one per stage, capped at the
        batch size).

    :param param_dtype: storage dtype of parameters.
    :param compute_dtype: activation/matmul dtype (bf16 keeps the MXU busy).
    :param remat: activation checkpointing policy: ``"none"``, ``"minimal"``
        (checkpoint dots with no batch dims saveable), or ``"full"``
        (checkpoint every block).
    :param scan_layers: roll transformer blocks into one ``lax.scan`` (faster
        compiles at scale, required for very deep models).
    :param dcn_data_parallelism: data-parallel replication factor across
        slices/hosts (DCN); intra-slice axes above ride ICI.
    """

    data: int = -1
    fsdp: int = 1
    pipe: int = 1
    model: int = 1
    sequence: int = 1
    expert: int = 1
    pipe_microbatches: int = 0

    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    remat: str = "none"
    scan_layers: bool = False
    dcn_data_parallelism: int = 1

    from_dict = classmethod(_strict_from_dict)


@dataclass
class ResilienceConfig:
    """Fault-tolerance knobs (``trlx_tpu/resilience/``, docs/RESILIENCE.md).

    Preemption, non-finite updates, and flaky host calls are routine at
    fleet scale; this section decides how the run survives each.

    :param handle_preemption: install SIGTERM/SIGINT handlers for the
        duration of ``learn()``: the signal requests an emergency checkpoint
        at the next step boundary, the run commits it and exits cleanly, and
        a relaunch with ``train.resume_from_checkpoint`` continues
        bit-identically to an uninterrupted run.
    :param preemption_signals: which signals request preemption.
    :param update_guard: non-finite (NaN/inf) update policy — ``"off"``
        (default: the pre-guard train step, byte-for-byte), ``"skip"``
        (on-device: keep the old params/opt-state, drop the poison batch —
        NOTE the keep-old select holds both state versions live, defeating
        donation's in-place update: ≈2× train-step temp memory), or
        ``"rollback"`` / ``"halt"`` (restore the newest committed
        checkpoint / raise — flag-only on device, no memory cost). The
        finiteness check is fused into the train step (no extra host sync).
    :param max_consecutive_nonfinite: escalate skip/rollback to halt after
        this many consecutive non-finite updates (true divergence).
    :param keep_last_n: interval-checkpoint retention ring: after each
        interval save, prune committed ``checkpoint_*`` dirs beyond the
        newest N (0 = keep everything; ``best_checkpoint`` is never pruned).
    :param reward_retries: retry a failing ``reward_fn`` call this many
        times (exponential backoff with deterministic jitter) before the
        ``reward_fallback`` policy applies.
    :param reward_backoff_s: base backoff; attempt k waits
        ``min(max, base * 2**k) * U[0.5, 1)``.
    :param reward_backoff_max_s: backoff cap.
    :param reward_timeout_s: per-attempt timeout (worker thread); a hung
        endpoint counts as a failed attempt. None = no timeout.
    :param reward_fallback: ``"raise"`` (re-raise after retries — the old
        behavior) or ``"neutral"`` (zero rewards for the batch; the run
        continues and ``resilience/reward_fallbacks`` counts it).
    :param reward_max_consecutive_fallbacks: escalate ``"neutral"`` back to
        raising after this many consecutive fallbacks — a reward_fn that
        fails EVERY call is a deterministic bug, not a transient outage,
        and must not silently train on zero rewards to ``total_steps``.
        0 disables the cap.
    :param elastic: reshard-on-restore (docs/RESILIENCE.md "Elastic
        restore"): checkpoints carry a topology manifest, and a restore
        whose live mesh differs from the saved one (an n=4 checkpoint on an
        n=2 slice, or a changed process count) loads leaves host-side and
        re-places them under the live mesh's shardings — values
        byte-preserved, post-resume trajectory bit-identical to an
        uninterrupted run on the destination topology. False = strict:
        a topology mismatch fails with a clear diagnostic instead.
    :param coordinate_preemption: multihost jobs only — allgather the
        preemption flag at every step boundary so a SIGTERM on ONE host
        makes ALL processes commit the same emergency-checkpoint step
        (process 0 writes the marker). Without it, one host exits while the
        peers keep stepping and no consistent restorable state exists.
        Cost: one scalar allgather per update when ``process_count > 1``;
        no-op single-process.
    :param publish_retries: tracker/hub publish retries; after exhaustion
        the record is *dropped* (logging never kills training).
    :param publish_backoff_s: base backoff for publish retries.
    :param fault_plan: deterministic fault-injection plan string
        (``"sigterm@step:5; reward_raise@call:3*2"`` — syntax in
        docs/RESILIENCE.md). ``TRLX_TPU_FAULT_PLAN`` overrides. None = no
        injected faults.
    """

    handle_preemption: bool = True
    preemption_signals: List[str] = field(
        default_factory=lambda: ["SIGTERM", "SIGINT"]
    )
    update_guard: str = "off"
    max_consecutive_nonfinite: int = 25
    keep_last_n: int = 0
    elastic: bool = True
    coordinate_preemption: bool = True
    reward_retries: int = 3
    reward_backoff_s: float = 0.5
    reward_backoff_max_s: float = 30.0
    reward_timeout_s: Optional[float] = None
    reward_fallback: str = "raise"
    reward_max_consecutive_fallbacks: int = 20
    publish_retries: int = 2
    publish_backoff_s: float = 0.2
    fault_plan: Optional[str] = None

    from_dict = classmethod(_strict_from_dict)


@dataclass
class EngineConfig:
    """Generation-engine knobs (``trlx_tpu/engine/``, docs/PERFORMANCE.md).

    Selects the KV backend behind the unified Engine interface the
    trainers' rollout collection runs on (``train.continuous_batching``
    routes through it; the serial path is always the dense reference).

    :param backend: ``"dense"`` (default: the per-slot ``[B, S]`` KV cache,
        byte-for-byte the PR-3 engine) or ``"paged"`` (block-pool KV with
        per-slot block tables — persistent KV HBM tracks *live tokens*
        instead of ``slots × max_length``; bit-identical outputs, pinned by
        ``tests/test_engine.py``).
    :param kv_block_size: cache columns per KV block. Smaller blocks track
        live tokens tighter and share shorter prefixes, at more table/
        gather overhead; larger blocks amortize bookkeeping. Power of two
        recommended; must be ≤ the padded prompt width for prefix hits to
        exist.
    :param max_kv_blocks: pool size in blocks (including the reserved
        zero block). 0 = auto: enough for every slot at full length, plus
        an equal prefix-cache working set when ``prefix_cache`` is on.
        Under-provisioned pools evict prefix entries first and raise a
        clear error only when live rows themselves cannot be backed.
    :param prefix_cache: share committed full prompt blocks between rows
        whose *padded* prompts agree from column 0 (GRPO group members,
        repeated eval prompts): hits prefill only the unshared suffix.
        Requires ``backend: paged``. Auto-disabled (with a warning) for
        MoE policies: expert-capacity coupling across a row's tokens
        breaks the suffix-prefill bit-equality the cache relies on.
    :param prefix_cache_blocks: entry cap for the prefix cache (0 = only
        pool pressure evicts).
    :param decode_kernel: compute path for the paged decode segments.
        ``"xla"`` (default) is the gather → dense compute → scatter
        reference; ``"pallas"`` runs the in-place Pallas paged-attention
        decode kernel + fused top-k/top-p/temperature sampling
        (``ops/paged_attention.py``) — K/V read and written through the
        block table with no transient dense view, deleting the
        per-segment gather tax (docs/PERFORMANCE.md "Pallas kernels").
        Bit-identical outputs by contract (``tests/test_paged_attention
        .py``); off-TPU the kernels run under the Pallas interpreter.
        Requires ``backend: paged``.
    :param prefill_kernel: compute path for the paged refill *prefills*.
        ``"xla"`` (default) is gather → dense prefill → scatter — the last
        dense-view copy on the generation hot path; ``"pallas"`` runs the
        in-place Pallas paged-prefill kernel (``ops/paged_prefill.py``):
        prompt K/V commits through the block table and attention reads
        pool blocks straight into VMEM — refill gather/scatter bytes drop
        to exactly 0 (``benchmarks/ENGINE_PREFILL_cpu.json``).
        Bit-identical to the gather path by contract; the parity reference
        is the dense einsum attention (models whose
        ``resolved_attention_impl()`` is pallas-flash prefill through the
        flash kernel on the gather path — same masking semantics,
        flash-vs-dense numerics; docs/PERFORMANCE.md). Requires
        ``backend: paged``.
    :param prefill_chunk: chunked-prefill scheduling (0 = off): admitted
        prompts prefill at most this many columns per engine step,
        interleaved with decode segments, so a long prompt can never
        stall live decode slots longer than one chunk's prefill — the
        measured ``rollout/decode_stall_p50/p95/max`` gauges bound it.
        Harvests stay bit-identical across chunk sizes. Requires
        ``backend: paged``.
    :param speculative: speculative continuous batching (0 = off): each
        decode segment runs draft-propose → verify ROUNDS in which the
        draft model (``model.draft_model_path``) proposes this many
        tokens per live slot and the target verifies all of them in one
        paged forward — committing 1..k+1 tokens per row per round while
        every harvested sequence stays bit-identical to a solo
        ``ops/speculative.py`` run of that row (``tests/test_spec_engine
        .py``). Requires ``backend: paged``, ``model.draft_model_path``,
        and per-row RNG (always on under continuous batching). Composes
        with the in-place kernels: under ``decode_kernel: pallas`` the
        verify forward runs the multi-position Pallas verify kernel
        (``ops/paged_attention.py::paged_verify_attention``), and under
        ``prefill_kernel: pallas`` spec refills keep the zero-copy paged
        prefill — ``engine/spec_verify_kernel_pallas`` stamps which
        verify compute ran. Acceptance lands in the ``engine/spec_*``
        gauges.
    """

    backend: str = "dense"
    kv_block_size: int = 16
    max_kv_blocks: int = 0
    prefix_cache: bool = False
    prefix_cache_blocks: int = 0
    decode_kernel: str = "xla"
    prefill_kernel: str = "xla"
    prefill_chunk: int = 0
    speculative: int = 0

    from_dict = classmethod(_strict_from_dict)


@dataclass
class AsyncRLConfig:
    """Disaggregated async RL knobs (``trlx_tpu/async_rl/``,
    docs/ASYNC_RL.md).

    Splits training into one learner and N generation actors connected by a
    staleness-bounded experience queue and an in-flight weight-sync channel
    — collection k+1 is generated while the learner optimizes on
    collection k, instead of the alternating single-program loop.

    :param enabled: route PPO/GRPO experience collection through the
        actor/learner split. False = the alternating reference loop,
        byte-for-byte unchanged.
    :param mode: ``"thread"`` (actors are in-process threads over the
        existing Engine paths — single host) or ``"process"`` (actors are
        separate processes with their own JAX runtime, connected through
        the ``root_dir`` filesystem transport — launch them with
        ``trlx_tpu.async_rl.actor.run_actor``).
    :param num_actors: actor threads (thread mode). Process-mode fleet size
        is however many ``run_actor`` processes you launch.
    :param max_staleness: how many learner updates a chunk's producing
        params may lag its consumption. 0 = fully synchronous — the actor
        gate degenerates to the alternating loop and the store is
        bit-identical to the serial reference under a fixed seed. Larger
        values buy generation/optimization overlap at bounded
        off-policyness (pair with ``method.iw_correction``).
    :param queue_capacity: experience-queue bound in chunks. 0 = auto
        (2 × the chunks one collection consumes).
    :param queue_policy: ``"block"`` back-pressures actors at capacity;
        ``"drop_oldest"`` evicts the stalest queued chunk instead (counted
        as ``async/dropped_chunks``; trades rollouts for freshness).
    :param sync_every: publish learner params every N optimizer updates
        (1 = every update; phase boundaries always force a publish).
    :param root_dir: process-mode transport root (weight files + chunk
        spool) — a directory shared between learner and actors.
    :param actor_timeout_s: process mode — how long the learner waits for
        the next chunk before declaring the actor fleet dead.
    :param poll_interval_s: process-mode file polling interval.
    :param max_actor_restarts: thread mode — dead actors are respawned
        (their in-flight chunk requeued) up to this many times before the
        underlying error propagates to the learner. With the collective
        transport, exhausting restarts while OTHER actors survive shrinks
        the fleet instead (elastic membership): the dead actor's chunks
        requeue onto survivors and the run continues.
    :param transport: ``"file"`` (the PR-9 spool/weights-file transport —
        the degraded/fallback mode; thread mode uses the equivalent
        in-memory channel) or ``"collective"`` (the fleet fabric:
        param-dissemination tree with unchanged-leaf delta skipping,
        in-fabric chunk commits, elastic join/leave —
        ``async_rl/transport.py``, docs/ASYNC_RL.md "Transports").
        Rank-uniform: on a multihost learner every rank must agree (the
        fleet gauges ride the telemetry beat; graftlint GL704 registry).
    :param fanout: dissemination-tree fanout (collective transport). The
        learner sends each param delta to at most ``fanout`` direct
        children; actors relay to theirs. Rank-uniform (see above).
    :param bind_host: host/interface the collective transport's listeners
        bind (learner root and actor relay nodes). Default loopback; set
        to the pod-routable interface for a real fleet.
    :param fetch_timeout_s: file transport — how long an actor's
        ``fetch`` retries reading a mid-replace weights file before
        declaring the writer dead. The learner's npz write grows with the
        model, so this is a deadline (default 60s), not an attempt count.
    """

    enabled: bool = False
    mode: str = "thread"
    num_actors: int = 1
    max_staleness: int = 0
    queue_capacity: int = 0
    queue_policy: str = "block"
    sync_every: int = 1
    root_dir: Optional[str] = None
    actor_timeout_s: float = 300.0
    poll_interval_s: float = 0.02
    max_actor_restarts: int = 3
    transport: str = "file"
    fanout: int = 2
    bind_host: str = "127.0.0.1"
    fetch_timeout_s: float = 60.0

    from_dict = classmethod(_strict_from_dict)


@dataclass
class ServeConfig:
    """Serving-frontend knobs (``trlx_tpu/serve/``, docs/SERVING.md).

    Puts an HTTP streaming frontend with SLO-aware admission, priority
    scheduling, multi-tenant prefix isolation, and host-RAM KV tiering in
    front of a :class:`~trlx_tpu.engine.core.ContinuousEngine` — including
    serve-while-training: PPO's ``learn()`` serves interactive requests
    between optimizer steps off the freshly published params.

    :param enabled: stand up the serving frontend inside ``learn()``.
        Requires ``engine.backend: paged`` + ``train.continuous_batching``
        (streaming snapshots and segment-boundary preemption are
        block-table operations).
    :param host: HTTP bind interface; default loopback.
    :param port: HTTP port (0 = ephemeral; read it back from
        ``ServeServer.port``).
    :param slots: serving-engine slot batch (its compiled width is
        independent of the collection engines').
    :param max_new_tokens: serving-engine decode budget per request.
    :param default_tenant: prefix-cache namespace + quota identity for
        requests that don't name one.
    :param default_class: priority class for requests that don't name one
        (``interactive`` | ``eval`` | ``actor``; engine ``SERVE_CLASSES``).
    :param slo_interactive_s / slo_eval_s / slo_actor_s: per-class
        queue-wait SLOs in seconds (0 = no admission gate for that class).
        Admission rejects with 429 + Retry-After only when the EWMA
        service-time model *proves* the SLO blown for a new arrival.
    :param max_queue: hard admitted-but-unfinished depth cap (memory
        bound; rejections past it are 429s regardless of SLO evidence).
    :param reserve_slots: engine slots only interactive traffic may take
        when the batch classes have the rest saturated.
    :param stream_buffer: per-request undelivered-delta bound — a consumer
        stalled past it is dropped (the engine slot keeps decoding;
        ``slow_client@request:N``, docs/RESILIENCE.md).
    :param drain_timeout_s: graceful-drain window on shutdown/SIGTERM —
        new admissions 503 immediately, in-flight requests get this long
        to finish before being failed.
    :param host_tier_blocks: host-RAM KV tier capacity in blocks (0 =
        tiering off): evicted prefix-cache blocks spill host-side and
        re-land device-side instead of re-prefilling (bit-identical by
        construction; ``serve/tiering.py``).
    :param tenant_quota_blocks: per-tenant KV block budgets
        (``{"team-a": 64}``); an allocation past the quota evicts only
        that tenant's prefix entries, then fails only that request.
    :param retain_param_versions: keep the newest N published param trees
        for ``ServeServer.params_for_version`` — the serve-while-training
        bit-equality probe's reference (0 = keep none).
    """

    enabled: bool = False
    host: str = "127.0.0.1"
    port: int = 0
    slots: int = 2
    max_new_tokens: int = 16
    default_tenant: str = "default"
    default_class: str = "interactive"
    slo_interactive_s: float = 0.0
    slo_eval_s: float = 0.0
    slo_actor_s: float = 0.0
    max_queue: int = 64
    reserve_slots: int = 0
    stream_buffer: int = 64
    drain_timeout_s: float = 5.0
    host_tier_blocks: int = 0
    tenant_quota_blocks: Dict[str, int] = field(default_factory=dict)
    retain_param_versions: int = 0

    from_dict = classmethod(_strict_from_dict)


@dataclass
class TrainConfig:
    """Run-level knobs for the shared learn loop
    (reference: ``trlx/data/configs.py:142-230``)."""

    total_steps: int
    seq_length: int
    epochs: int
    batch_size: int

    checkpoint_interval: int
    eval_interval: int

    pipeline: str  # a registered pipeline name
    trainer: str  # a registered trainer name
    trainer_kwargs: Dict[str, Any] = field(default_factory=dict)

    project_name: str = "trlx_tpu"
    entity_name: Optional[str] = None
    group_name: Optional[str] = None

    checkpoint_dir: str = "ckpts"
    rollout_logging_dir: Optional[str] = None
    save_best: bool = True

    tracker: Optional[str] = None
    logging_dir: Optional[str] = None
    tags: List[str] = field(default_factory=list)

    seed: int = 1000

    # Number of eval prompts generated/scored per evaluate() call; None = all.
    eval_batch_size: Optional[int] = None

    # Gradient accumulation: microbatches per optimizer step. batch_size must
    # be divisible; grads are averaged over the ``lax.scan`` of microbatch
    # passes inside the one jitted step, so global batch is no longer capped
    # by per-device memory (reference gets this from DeepSpeed / NeMo's
    # micro-vs-global batch, ``megatron_20b.yaml:51-52``).
    grad_accum: int = 1

    # When set, a jax.profiler trace of optimization steps 2-5 (XLA ops,
    # device timelines; viewable in XProf/TensorBoard) is written here — the
    # TPU-native counterpart of the reference's Nsight hooks
    # (``megatron_20b.yaml:127-132``; SURVEY.md §5 tracing).
    profile_dir: Optional[str] = None

    # Crash/preemption recovery: when True, learn() restores the newest
    # interval checkpoint under checkpoint_dir (full TrainState + iteration
    # counter) before training — relaunch the same command and the run
    # continues (reference analogues: Ray session restore,
    # ``accelerate_base_trainer.py:452-460``; NeMo ``resume_if_exists``).
    resume_from_checkpoint: bool = False

    # Background-thread batch prefetch depth for the training loader (the
    # reference's torch DataLoader num_workers/prefetch_factor capability):
    # up to this many collated batches are prepared ahead while the device
    # runs the current step. 0 disables.
    prefetch_batches: int = 2

    # Software-pipelined experience collection: up to this many rollout
    # chunks' host work (string decode, reward_fn, device→host fetches) may
    # be in flight on a background worker while the device generates the
    # next chunk. Within one make_experience call the params never change,
    # so the overlap is exactly equivalent to the serial schedule — the
    # store is bit-identical under a fixed seed (docs/PERFORMANCE.md).
    # 0 = the serial reference path.
    rollout_pipeline_depth: int = 2

    # Continuous-batching rollout generation (docs/PERFORMANCE.md): decode
    # runs as fixed-size segments over per-slot state; finished sequences
    # are harvested at segment boundaries (shipped individually into the
    # rollout pipeline's host stage) and their freed KV-cache slots refill
    # from the prompt queue — the device batch stays full instead of every
    # chunk draining at the pace of its longest row. Wins grow with
    # response-length *variance*. Rollout sampling switches to per-row RNG
    # streams (required for slot invariance), so sampled tokens differ from
    # the serial path's batch-wide stream; per-sequence they are
    # bit-identical to plain generate under per-row RNG
    # (tests/test_continuous_batching.py). Causal-LM PPO/GRPO only
    # (seq2seq and speculative decoding keep the serial path).
    # False = the serial chunked reference path, byte-for-byte unchanged.
    continuous_batching: bool = False

    # Decode steps per compiled segment between harvest/refill points.
    # Smaller segments harvest/refill sooner (higher slot utilization,
    # lower completion latency) at the cost of more host round-trips and
    # refill prefills per collection.
    continuous_batching_segment: int = 8

    from_dict = classmethod(_strict_from_dict)


@dataclass
class TRLConfig:
    """Top-level config: method/model/optimizer/scheduler/tokenizer/train
    (+ TPU ``parallel``)."""

    method: MethodConfig
    model: ModelConfig
    optimizer: OptimizerConfig
    scheduler: SchedulerConfig
    tokenizer: TokenizerConfig
    train: TrainConfig
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    engine: EngineConfig = field(default_factory=EngineConfig)
    async_rl: AsyncRLConfig = field(default_factory=AsyncRLConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)

    @classmethod
    def load_yaml(cls, yml_fp: str) -> "TRLConfig":
        with open(yml_fp, mode="r") as f:
            return cls.from_dict(yaml.safe_load(f))

    def to_dict(self) -> Dict[str, Any]:
        def listify(x):
            if isinstance(x, tuple):
                return [listify(v) for v in x]
            if isinstance(x, list):
                return [listify(v) for v in x]
            if isinstance(x, dict):
                return {k: listify(v) for k, v in x.items()}
            return x

        return listify({
            "method": asdict(self.method),
            "model": asdict(self.model),
            "optimizer": asdict(self.optimizer),
            "scheduler": asdict(self.scheduler),
            "tokenizer": asdict(self.tokenizer),
            "train": asdict(self.train),
            "parallel": asdict(self.parallel),
            "resilience": asdict(self.resilience),
            "engine": asdict(self.engine),
            "async_rl": asdict(self.async_rl),
            "serve": asdict(self.serve),
        })

    @classmethod
    def from_dict(cls, config: Dict[str, Any]) -> "TRLConfig":
        return cls(
            method=get_method(config["method"]["name"]).from_dict(config["method"]),
            model=ModelConfig.from_dict(config["model"]),
            tokenizer=TokenizerConfig.from_dict(config["tokenizer"]),
            optimizer=OptimizerConfig.from_dict(config["optimizer"]),
            scheduler=SchedulerConfig.from_dict(config["scheduler"]),
            train=TrainConfig.from_dict(config["train"]),
            parallel=ParallelConfig.from_dict(config.get("parallel", {})),
            resilience=ResilienceConfig.from_dict(config.get("resilience", {})),
            engine=EngineConfig.from_dict(config.get("engine", {})),
            async_rl=AsyncRLConfig.from_dict(config.get("async_rl", {})),
            serve=ServeConfig.from_dict(config.get("serve", {})),
        )

    def evolve(self, **kwargs) -> "TRLConfig":
        """Return a new config with nested overrides applied.

        >>> config = config.evolve(method=dict(gamma=0.99))
        """
        return TRLConfig.from_dict(_merge_dicts(self.to_dict(), kwargs))

    @classmethod
    def update(cls, baseconfig, config: Dict[str, Any]) -> "TRLConfig":
        """Apply dot-path overrides (``{"train.seed": 1}``) to a base config,
        erroring on keys that do not exist anywhere in the base tree."""
        update: Dict[str, Any] = {}
        for name, value in config.items():
            if isinstance(value, dict):
                update[name] = value
            else:
                *layers, var = name.split(".")
                d = update
                for layer in layers:
                    d = d.setdefault(layer, {})
                d[var] = value

        if not isinstance(baseconfig, dict):
            baseconfig = baseconfig.to_dict()

        merged = _merge_strict(baseconfig, update)
        return cls.from_dict(merged)

    def __str__(self) -> str:
        return json.dumps(self.to_dict(), indent=4)
