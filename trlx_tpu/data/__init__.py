"""Data types and config system.

Reference: ``trlx/data/__init__.py`` (GeneralElement/RLElement/BatchElement)
and ``trlx/data/accelerate_base_datatypes.py`` (PromptBatch).
"""

from dataclasses import dataclass
from typing import Any, Iterable, List

import numpy as np


@dataclass
class GeneralElement:
    """General element for any pipeline."""

    pass


@dataclass
class RLElement(GeneralElement):
    """A state/action pair."""

    state: Any = None
    action: Any = None


@dataclass
class PromptElement(GeneralElement):
    """A tokenized prompt."""

    text: str = ""
    tokens: np.ndarray = None


@dataclass
class PromptBatch:
    """A batch of tokenized prompts."""

    text: List[str] = None
    tokens: np.ndarray = None  # [B, T]
    attention_mask: np.ndarray = None  # [B, T]
