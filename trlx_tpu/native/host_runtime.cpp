// Native host runtime: the hot host-side data-path primitives.
//
// The reference delegates its host data path to native code in dependencies
// (torch's C++ DataLoader/collate machinery; SURVEY.md §2.4). Here the
// equivalent is explicit: ragged→padded batch collation (every rollout store
// and pipeline funnels through it, once per training batch) implemented in
// C++ and bound via ctypes (no pybind11 in the image). The Python fallback
// in trlx_tpu/pipeline/offline_pipeline.py stays behaviorally identical.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 (driven by trlx_tpu/native/__init__.py).

#include <algorithm>
#include <cstdint>
#include <cstring>

namespace {

template <typename T>
void pad_rows_impl(const T* flat, const int64_t* lengths, int64_t n_rows,
                   int64_t length, T pad_value, int left, T* out,
                   int32_t* mask) {
  int64_t offset = 0;
  for (int64_t i = 0; i < n_rows; ++i) {
    const int64_t len = lengths[i];
    const T* src = flat + offset;
    offset += len;
    const int64_t keep = std::min(len, length);
    // truncation keeps the side adjacent to the content: left-padding keeps
    // the END of the row, right-padding keeps the start
    const T* kept = left ? src + (len - keep) : src;
    T* orow = out + i * length;
    int32_t* mrow = mask + i * length;
    std::fill(orow, orow + length, pad_value);
    std::fill(mrow, mrow + length, 0);
    const int64_t start = left ? (length - keep) : 0;
    std::memcpy(orow + start, kept, sizeof(T) * static_cast<size_t>(keep));
    std::fill(mrow + start, mrow + start + keep, 1);
  }
}

}  // namespace

extern "C" {

void pad_rows_i32(const int32_t* flat, const int64_t* lengths, int64_t n_rows,
                  int64_t length, int32_t pad_value, int left, int32_t* out,
                  int32_t* mask) {
  pad_rows_impl<int32_t>(flat, lengths, n_rows, length, pad_value, left, out, mask);
}

void pad_rows_f32(const float* flat, const int64_t* lengths, int64_t n_rows,
                  int64_t length, float pad_value, int left, float* out,
                  int32_t* mask) {
  pad_rows_impl<float>(flat, lengths, n_rows, length, pad_value, left, out, mask);
}

}  // extern "C"
