"""ctypes bindings for the native host runtime (``host_runtime.cpp``).

Compiled lazily with g++ on first use (content-hashed cache under
``$TRLX_TPU_NATIVE_CACHE`` or the system temp dir) and loaded via ctypes —
the image ships no pybind11, and a 2-function C ABI needs none. Every
call-site must tolerate :func:`available` being False (no compiler /
sandboxed FS) and fall back to the numpy path.
"""

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional, Sequence, Tuple

import numpy as np

_LIB: Optional[ctypes.CDLL] = None
_LOAD_FAILED = False

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "host_runtime.cpp")

_I64P = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_I32P = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_F32P = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _LOAD_FAILED
    if _LIB is not None or _LOAD_FAILED:
        return _LIB
    try:
        # per-user, mode-0700 cache by default: a world-writable shared path
        # would let another local user pre-plant a library at the predictable
        # name that ctypes would then load into the training process. An
        # explicit TRLX_TPU_NATIVE_CACHE is taken as-is (it may deliberately
        # be a group-shared build cache — don't rewrite its permissions).
        cache_dir = os.environ.get("TRLX_TPU_NATIVE_CACHE")
        if cache_dir is None:
            cache_dir = os.path.join(
                tempfile.gettempdir(), f"trlx_tpu_native_{os.getuid()}"
            )
            os.makedirs(cache_dir, exist_ok=True)
            os.chmod(cache_dir, 0o700)
        else:
            os.makedirs(cache_dir, exist_ok=True)
        tag = hashlib.sha1(open(_SRC, "rb").read()).hexdigest()[:12]
        so_path = os.path.join(cache_dir, f"host_runtime_{tag}.so")
        if not os.path.exists(so_path):
            tmp = f"{so_path}.{os.getpid()}.tmp"
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, so_path)
        if os.stat(so_path).st_uid != os.getuid():
            raise RuntimeError(f"refusing to load {so_path}: not owned by this user")
        lib = ctypes.CDLL(so_path)
        lib.pad_rows_i32.argtypes = [
            _I32P, _I64P, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int, _I32P, _I32P,
        ]
        lib.pad_rows_f32.argtypes = [
            _F32P, _I64P, ctypes.c_int64, ctypes.c_int64, ctypes.c_float,
            ctypes.c_int, _F32P, _I32P,
        ]
        _LIB = lib
    except Exception:
        _LOAD_FAILED = True
    return _LIB


def available() -> bool:
    return _load() is not None


def pad_rows_native(
    rows: Sequence[np.ndarray],
    pad_value,
    side: str,
    length: int,
    dtype,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Ragged rows → ([B, length] padded, [B, length] int32 mask), or None
    when the native library is unavailable (caller falls back to numpy)."""
    lib = _load()
    if lib is None:
        return None
    dtype = np.dtype(dtype)
    if dtype == np.int32:
        fn, ctype = lib.pad_rows_i32, np.int32
    elif dtype == np.float32:
        fn, ctype = lib.pad_rows_f32, np.float32
    else:
        return None
    n = len(rows)
    arrays = [np.ascontiguousarray(np.asarray(r, ctype).reshape(-1)) for r in rows]
    lengths = np.asarray([a.shape[0] for a in arrays], np.int64)
    flat = (
        np.concatenate(arrays)
        if arrays
        else np.zeros((0,), ctype)
    )
    if flat.size == 0:
        flat = np.zeros((1,), ctype)  # valid pointer for the empty case
    out = np.empty((n, length), ctype)
    mask = np.empty((n, length), np.int32)
    fn(flat, lengths, n, length, pad_value, 1 if side == "left" else 0, out, mask)
    return out, mask
