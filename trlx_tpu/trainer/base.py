"""Shared TPU trainer: model/optimizer setup, jitted train step, generation,
eval loop, checkpointing, trackers.

Behavioral parity target: ``AccelerateRLTrainer``
(``trlx/trainer/accelerate_base_trainer.py:39-574``) — same control flow
(epochs → batches → n updates per batch, interval checkpoints, best-reward
checkpoint, eval with optional gen-kwarg sweep, stop-sequence trimming), but
the torch/Accelerate machinery is replaced by the TPU-native stack: one
global ``Mesh``, GSPMD-sharded params, a jitted ``value_and_grad`` step with
donated train state, and jitted KV-cache generation (``trlx_tpu/ops/sampling``).

The reference's per-rank device dance (``pad_across_processes``/``gather``/
``scatter``, ``accelerate_ppo_trainer.py:292-327``) does not exist here:
arrays are globally sharded, so "gather to rank 0" is just ``jax.device_get``
at the host boundary (reward/metric fns), and per-rank scatter is
``shard_batch`` placement.
"""

import json
import os
from abc import abstractmethod
from time import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.data.tokenizer import from_config as tokenizer_from_config
from trlx_tpu.models.builder import build_causal_lm, trainable_mask
from trlx_tpu.models.transformer import make_kv_cache
from trlx_tpu.ops.sampling import (
    GenerationConfig,
    GenerationOutput,
    generate,
    generate_seq2seq,
)
from trlx_tpu.parallel import make_mesh, set_global_mesh, shard_batch, shard_params
from trlx_tpu.pipeline import BasePipeline
from trlx_tpu.trainer import BaseRLTrainer
from trlx_tpu.utils import (
    Clock,
    filter_non_scalars,
    get_optimizer,
    get_scheduler,
    significant,
    to_host,
)
from trlx_tpu.utils import logging
from trlx_tpu.utils.checkpoint import (
    is_committed,
    newest_committed_checkpoint,
    prune_checkpoints,
    read_extra,
    save_pretrained,
    save_state,
    wait_for_saves,
)
from trlx_tpu.observability import Observability, train_step_flops
from trlx_tpu.observability import mfu as obs_mfu
from trlx_tpu.resilience import UPDATE_OK_KEY, Resilience, TrainingPreempted
from trlx_tpu.utils.trackers import make_tracker

logger = logging.get_logger(__name__)

# Bad-batch triage bounds (docs/OBSERVABILITY.md "Training dynamics"): cap
# rows per dump and dumps per run so a persistently-tripping detector can't
# fill the disk with repro artifacts.
TRIAGE_MAX_ROWS = 64
TRIAGE_MAX_DUMPS = 8


@flax.struct.dataclass
class TrainState:
    """Functional train state threaded through the jitted step."""

    params: Any
    opt_state: Any
    step: jax.Array  # scalar int32
    rng: jax.Array


def _optimizer_state_shardings(mesh, params: Any, abstract_opt: Any) -> Any:
    """Sharding pytree for an optimizer state, matched *structurally*: optax
    moment trees mirror the params pytree, so an opt-state leaf whose path
    suffix is a param path (and whose shape agrees) takes that param's
    sharding. Blockwise-quantized int8 moments (``codes``/``scales`` under a
    param path) shard their block dim over the largest dividing combination
    of the fsdp/model axes; everything else (counts, schedules) replicates.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    from trlx_tpu.parallel.sharding import _axis_size, path_keys

    replicated = NamedSharding(mesh, PartitionSpec())
    param_by_path = {
        path_keys(path): (leaf.shape, leaf.sharding)
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
    }

    def leaf_sharding(path, leaf):
        keys = path_keys(path)
        # longest suffix first: the full opt path carries wrapper prefixes
        # (inner_states/<label>/0/mu/...) before the mirrored param path
        for start in range(len(keys)):
            hit = param_by_path.get(keys[start:])
            if hit is not None and hit[0] == leaf.shape:
                return hit[1]
        if keys and keys[-1] in ("codes", "scales") and len(leaf.shape) == 2:
            for axes in (("fsdp", "model"), ("fsdp",), ("model",)):
                size = _axis_size(mesh, axes)
                if size > 1 and leaf.shape[0] % size == 0:
                    spec = axes if len(axes) > 1 else axes[0]
                    return NamedSharding(mesh, PartitionSpec(spec, None))
        return replicated

    return jax.tree_util.tree_map_with_path(leaf_sharding, abstract_opt)


class TPUBaseTrainer(BaseRLTrainer):
    """Shared learn-loop trainer over a global device mesh.

    Subclasses define:

    - ``model_head``: ``None`` | ``"value"`` | ``"ilql"`` — which wrapper to
      build;
    - ``loss_fn(params, batch, rng) -> (loss, stats)``: a *pure* function of
      the param tree and a dict-of-arrays batch (closed over configs/module);
    - ``prepare_learning()``: set ``train_dataloader``, ``eval_dataloader``,
      ``n_updates_per_batch``, ``total_steps``;
    - optionally ``post_backward_callback`` / ``post_epoch_callback`` and
      ``adjust_logits_fn`` (on-device sampling-logit reshaping, e.g. ILQL).
    """

    model_head: Optional[str] = None

    def __init__(
        self,
        config: TRLConfig,
        reward_fn: Optional[Callable] = None,
        metric_fn: Optional[Callable] = None,
        stop_sequences: Optional[List[str]] = None,
        abstract_init: bool = False,
        **kwargs,
    ):
        # abstract_init: build the trainer with ShapeDtypeStruct weights —
        # no parameter/optimizer arrays are ever materialized, but every
        # jitted program (train step, generate, score) can still be lowered
        # and compiled for cost/memory analysis (trlx_tpu/perf.py). Such a
        # trainer can trace but never execute.
        self.abstract_init = abstract_init
        super().__init__(config, reward_fn, metric_fn, stop_sequences, **kwargs)
        if config.train.batch_size % max(1, config.train.grad_accum) != 0:
            raise ValueError(
                f"train.batch_size ({config.train.batch_size}) must be divisible "
                f"by train.grad_accum ({config.train.grad_accum})"
            )
        if config.engine.prefix_cache and config.engine.backend != "paged":
            # fail at construction, not at the first rollout collection
            # (and never silently: with continuous_batching off this knob
            # would otherwise just do nothing)
            raise ValueError(
                "engine.prefix_cache: true requires engine.backend: paged — "
                "dense per-slot KV caches cannot share blocks"
            )
        if config.engine.decode_kernel not in ("xla", "pallas"):
            raise ValueError(
                f"unknown engine.decode_kernel "
                f"'{config.engine.decode_kernel}' (xla | pallas)"
            )
        if (
            config.engine.decode_kernel == "pallas"
            and config.engine.backend != "paged"
        ):
            raise ValueError(
                "engine.decode_kernel: pallas is the in-place *paged* "
                "decode kernel (ops/paged_attention.py) — it requires "
                "engine.backend: paged"
            )
        if config.engine.prefill_kernel not in ("xla", "pallas"):
            raise ValueError(
                f"unknown engine.prefill_kernel "
                f"'{config.engine.prefill_kernel}' (xla | pallas)"
            )
        if (
            config.engine.prefill_kernel == "pallas"
            and config.engine.backend != "paged"
        ):
            raise ValueError(
                "engine.prefill_kernel: pallas is the in-place *paged* "
                "prefill kernel (ops/paged_prefill.py) — it requires "
                "engine.backend: paged"
            )
        if int(config.engine.prefill_chunk) < 0:
            raise ValueError(
                f"engine.prefill_chunk {config.engine.prefill_chunk} "
                "must be >= 0 (0 = monolithic prefill)"
            )
        if int(config.engine.prefill_chunk) and config.engine.backend != "paged":
            raise ValueError(
                "engine.prefill_chunk (chunked-prefill scheduling) "
                "requires engine.backend: paged — the chunk programs "
                "commit prompt spans through the block table"
            )
        if int(config.engine.speculative) < 0:
            raise ValueError(
                f"engine.speculative {config.engine.speculative} must be "
                ">= 0 (0 = off, k = draft tokens proposed per verify round)"
            )
        if int(config.engine.speculative):
            # each requirement its own error: the composition has three
            # independent preconditions and "speculative engine misconfigured"
            # would send users grepping
            if not config.model.draft_model_path:
                raise ValueError(
                    "engine.speculative (speculative continuous batching) "
                    "requires model.draft_model_path — the engine needs a "
                    "draft model to propose tokens for the target to verify"
                )
            if config.engine.backend != "paged":
                raise ValueError(
                    "engine.speculative requires engine.backend: paged — "
                    "the verify pass commits accepted K/V through the "
                    "block table with drop-mode writes"
                )
            # NOTE: no decode_kernel restriction — the spec segment's verify
            # pass runs the multi-position paged kernel in place
            # (ops/paged_attention.py::paged_verify_attention), so
            # engine.speculative composes with decode_kernel: pallas
        lk = str(getattr(config.method, "loss_kernel", "xla"))
        if lk not in ("xla", "pallas"):
            raise ValueError(
                f"unknown method.loss_kernel '{lk}' (xla | pallas)"
            )
        hostable = getattr(type(config.method), "LOSS_KERNELS", ("xla",))
        if lk == "pallas" and "pallas" not in hostable:
            raise ValueError(
                f"method.loss_kernel: pallas is the fused GAE + whitening + "
                f"clipped-loss learner kernel (ops/fused_loss.py) — "
                f"{type(config.method).__name__} has no GAE/value-head loss "
                f"to fuse (hostable kernels: {list(hostable)})"
            )
        if config.serve.enabled:
            # each precondition its own error (docs/SERVING.md): the
            # serving frontend is built on block-table operations
            if config.engine.backend != "paged":
                raise ValueError(
                    "serve.enabled requires engine.backend: paged — token "
                    "streaming snapshots and priority preemption are "
                    "block-table operations"
                )
            if not getattr(config.train, "continuous_batching", False):
                raise ValueError(
                    "serve.enabled requires train.continuous_batching: "
                    "true — the serving engine is a ContinuousEngine built "
                    "through the slot-refill program cache"
                )
            if int(config.serve.slots) < 1:
                raise ValueError(
                    f"serve.slots {config.serve.slots} must be >= 1"
                )
            if not 0 <= int(config.serve.reserve_slots) < int(config.serve.slots):
                raise ValueError(
                    f"serve.reserve_slots {config.serve.reserve_slots} must "
                    f"leave at least one unreserved slot of serve.slots "
                    f"{config.serve.slots}"
                )
            if float(config.serve.drain_timeout_s) <= 0:
                raise ValueError(
                    f"serve.drain_timeout_s {config.serve.drain_timeout_s} "
                    "must be > 0 (the graceful-drain window)"
                )
            if int(config.serve.host_tier_blocks) and not config.engine.prefix_cache:
                raise ValueError(
                    "serve.host_tier_blocks requires engine.prefix_cache: "
                    "true — only committed prefix entries ever spill to the "
                    "host tier"
                )
            from trlx_tpu.engine.core import SERVE_CLASSES as _SC

            if config.serve.default_class not in _SC:
                raise ValueError(
                    f"unknown serve.default_class "
                    f"{config.serve.default_class!r} (expected one of {_SC})"
                )
        # the serving frontend (trlx_tpu/serve/, docs/SERVING.md); built in
        # learn() when serve.enabled, drained in _shutdown_collectors
        self._serve = None
        self.mesh = make_mesh(config.parallel)
        set_global_mesh(self.mesh)  # model code reads this for sequence-parallel ops
        # NOTE: the global mesh is process-wide; entry points re-assert it so
        # two trainers in one process don't trace against each other's mesh
        self.tokenizer = tokenizer_from_config(config.tokenizer)

        two_qs = bool(getattr(config.method, "two_qs", True))
        # seq2seq (T5) vs causal arch selection (reference ``get_arch``,
        # ``accelerate_ppo_trainer.py:120-134``)
        self.is_seq2seq = config.model.model_arch_type == "seq2seq"
        if self.is_seq2seq:
            from trlx_tpu.models.builder import build_seq2seq_lm, seq2seq_trainable_mask

            build, mask_fn = build_seq2seq_lm, seq2seq_trainable_mask
        else:
            build, mask_fn = build_causal_lm, trainable_mask
        self.module, params, self.tcfg = build(
            config.model,
            config.parallel,
            head=self.model_head,
            two_qs=two_qs,
            seed=config.train.seed,
            abstract=abstract_init,
        )
        if not abstract_init:
            params = shard_params(params, self.mesh)
        self.param_mask = mask_fn(params, self.tcfg, config.model.num_layers_unfrozen)
        self.draft_module = self.draft_params = self.draft_tcfg = None
        self.last_spec_stats: Dict[str, float] = {}
        self.last_generate_time = 0.0
        if config.model.draft_model_path and self.is_seq2seq:
            logger.warning(
                "model.draft_model_path is ignored for seq2seq models: "
                "speculative decoding is implemented for causal LMs only"
            )
        elif config.model.draft_model_path:
            from trlx_tpu.data.configs import ModelConfig as _MC

            # the draft always runs UNPIPELINED: under a pipe>1 mesh it
            # computes replicated across stages while the pipelined target
            # verifies its proposals (per-row cache depths flow through the
            # microbatch schedule via parallel/pipeline.py's cache_index
            # slicing)
            draft_extra = dict(config.model.draft_model_extra_kwargs)
            draft_extra["ignore_pipe_mesh"] = True
            self.draft_module, draft_params, self.draft_tcfg = build_causal_lm(
                _MC(
                    model_path=config.model.draft_model_path,
                    model_extra_kwargs=draft_extra,
                ),
                config.parallel,
                head=None,
                seed=config.train.seed + 1,
                abstract=abstract_init,
            )
            if self.draft_tcfg.vocab_size != self.tcfg.vocab_size:
                raise ValueError(
                    f"draft vocab {self.draft_tcfg.vocab_size} != policy vocab "
                    f"{self.tcfg.vocab_size}: speculative decoding needs a "
                    "same-tokenizer draft"
                )
            self.draft_params = (
                draft_params if abstract_init else shard_params(draft_params, self.mesh)
            )

        default_lr = config.optimizer.kwargs.get("lr")
        self.schedule = get_scheduler(
            config.scheduler.name, dict(config.scheduler.kwargs), default_lr=default_lr
        )
        self.optimizer = get_optimizer(
            config.optimizer.name,
            dict(config.optimizer.kwargs),
            schedule=self.schedule,
            mask=self.param_mask,
        )
        # Optimizer state gets *explicit* shardings: moment tensors follow
        # their parameter's sharding (FSDP: ZeRO-sharded optimizer state),
        # quantized int8 moments shard their block dim, scalars/bookkeeping
        # replicate. Without out_shardings the compiler may leave the whole
        # state on one device — and checkpoint restore then commits that
        # placement, breaking later steps.
        if abstract_init:
            opt_state = jax.eval_shape(self.optimizer.init, params)
        else:
            opt_shardings = _optimizer_state_shardings(
                self.mesh, params, jax.eval_shape(self.optimizer.init, params)
            )
            opt_state = jax.jit(self.optimizer.init, out_shardings=opt_shardings)(params)
        from jax.sharding import NamedSharding, PartitionSpec

        from trlx_tpu.parallel.sharding import put_global

        replicated = NamedSharding(self.mesh, PartitionSpec())
        rng = jax.random.PRNGKey(config.train.seed)
        rollout_rng, state_rng = jax.random.split(rng)
        self.state = TrainState(
            params=params,
            opt_state=opt_state,
            step=put_global(jnp.zeros((), jnp.int32), replicated),
            rng=put_global(state_rng, replicated),
        )
        self._rollout_rng = rollout_rng

        # generation settings (reference: accelerate_base_trainer.py:176-198)
        self.generate_kwargs = dict(config.method.gen_kwargs)
        self.generate_experience_kwargs = (
            dict(config.method.gen_experience_kwargs)
            if getattr(config.method, "gen_experience_kwargs", None)
            else None
        )
        self._generate_fns: Dict[Any, Callable] = {}
        self._train_step_fn: Optional[Callable] = None
        self._last_batch_host: Any = None
        self._last_batch_sharded: Any = None

        # runtime observability: span tracer, metrics registry, recompile/
        # memory watchdogs, profiler window (docs/OBSERVABILITY.md)
        self.obs = Observability(config)
        # resilience: preemption handler, update guard, host-call hardening,
        # fault plan (docs/RESILIENCE.md). Shares the metrics registry so
        # every resilience/* counter rides the tracker stream. reward_fn is
        # wrapped ONCE here, hardening every call site (rollouts, eval).
        self.resilience = Resilience(config, metrics=self.obs.metrics)
        self.reward_fn = self.resilience.harden_reward_fn(
            self.reward_fn, seed=config.train.seed
        )
        self.tracker = self.resilience.harden_tracker(
            make_tracker(config), seed=config.train.seed
        )
        self._train_step_flops: Optional[float] = None
        self._flops_thread = None
        self.eval_pipeline: Optional[BasePipeline] = None
        self.iter_count = 0
        self.nth_evaluation = 0
        self.best_reward = -float("inf")
        self._emergency_resume = False
        self._prompt_chunks_drawn = 0
        self._triage_dumps = 0

    # ------------------------------------------------------------------
    # subclass contract
    # ------------------------------------------------------------------

    @abstractmethod
    def loss_fn(
        self, params: Any, batch: Dict[str, jax.Array], rng: jax.Array
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        ...

    def _resolved_logit_chunk(self) -> int:
        """``method.logit_chunk`` when the module can stream the vocab
        projection, else 0 — warning ONCE (and before any forward runs, so
        DPO's whole-dataset reference precompute isn't silently full-size)."""
        chunk = getattr(self.config.method, "logit_chunk", 0)
        if not chunk:
            return 0
        if hasattr(type(self.module), "project_logits"):
            return chunk
        if not getattr(self, "_warned_logit_chunk", False):
            self._warned_logit_chunk = True
            logger.warning(
                "method.logit_chunk=%d is IGNORED: %s has no project_logits — "
                "the full [B, T, V] logits will be materialized",
                chunk,
                type(self.module).__name__,
            )
        return 0

    def with_router_aux(
        self,
        loss_stats: Tuple[jax.Array, Dict[str, Any]],
        out: Any,
    ) -> Tuple[jax.Array, Dict[str, Any]]:
        """Fold the MoE router auxiliary losses (Switch load-balance +
        ST-MoE z-loss, weighted by the model config's ``router_aux_coef`` /
        ``router_z_coef``) into a trainer loss. No-op for dense backbones —
        every ``loss_fn`` routes its return through here so any trainer can
        drive a mixture-of-experts policy."""
        loss, stats = loss_stats
        aux = out.get("router_aux_loss") if isinstance(out, dict) else None
        if aux is None:
            return loss, stats
        tcfg = self.tcfg
        new_loss = (
            loss
            + getattr(tcfg, "router_aux_coef", 0.0) * aux[0]
            + getattr(tcfg, "router_z_coef", 0.0) * aux[1]
        )
        stats = dict(stats)
        stats["losses/router_load_balance"] = aux[0]
        stats["losses/router_z"] = aux[1]
        # keep the logged total in sync with what is actually optimized.
        # Contract: every method.loss must report its headline total under
        # one of these canonical keys (PPO/ILQL/GRPO/DPO flatten to
        # losses/total_loss, SFT to losses/loss) — a new method using a
        # different name would log a total that excludes the router terms
        for key in ("losses/total_loss", "losses/loss"):
            if key in stats:
                stats[key] = new_loss
        return new_loss, stats

    @abstractmethod
    def prepare_learning(self) -> None:
        ...

    def post_backward_callback(self) -> None:
        pass

    def post_epoch_callback(self) -> None:
        pass

    def adjust_logits_fn(self, extra_kwargs: Dict[str, Any]) -> Optional[Callable]:
        """On-device hook reshaping last-token logits during sampling.

        ``extra_kwargs`` are the gen kwargs not consumed by
        :class:`GenerationConfig` (e.g. ILQL's ``beta``) — resolved per
        ``generate`` call, so kwarg overrides and eval sweeps reach the hook.

        Contract: ``fn(step_out, logits) -> logits`` must be polymorphic
        over leading dims. The plain sampler passes last-position views
        (``[B, ...]`` fields, ``[B, V]`` logits); the speculative sampler
        passes the verify block (``[B, G+1, ...]`` fields, ``[B, G+1, V]``
        logits) with the same keys (model outputs + ``last_tokens``). Hooks
        that broadcast per-position fields against the trailing vocab axis
        — like ILQL's — satisfy this automatically; hooks that reshape
        assuming a fixed rank do not and must not be paired with a draft
        model.
        """
        return None

    def add_eval_pipeline(self, eval_pipeline: BasePipeline) -> None:
        self.eval_pipeline = eval_pipeline

    # ------------------------------------------------------------------
    # train step
    # ------------------------------------------------------------------

    def _build_train_step(self) -> Callable:
        optimizer = self.optimizer
        schedule = self.schedule
        accum = max(1, int(getattr(self.config.train, "grad_accum", 1)))

        # Pin the output state's shardings to the input state's (explicit
        # out_shardings below). Without the pin, output shardings are
        # reconstructed from XLA's canonicalized HloShardings, which strip
        # size-1 mesh axes from specs (P('fsdp','model') → P() on a dp-only
        # mesh): the step-1 output state then hashes differently from the
        # step-1 input and step 2 silently recompiles the entire program —
        # one full extra XLA compile and a second resident executable every
        # run. Found by the recompile watchdog (observability/watchdogs.py).
        from jax.sharding import NamedSharding

        if all(
            isinstance(getattr(leaf, "sharding", None), NamedSharding)
            for leaf in jax.tree_util.tree_leaves(self.state)
        ):
            state_shardings = jax.tree_util.tree_map(
                lambda leaf: leaf.sharding, self.state
            )
        else:  # abstract_init analysis trainers carry no real shardings
            state_shardings = None

        # Update guard (docs/RESILIENCE.md): with a policy other than "off",
        # the step checks isfinite(global_norm) ON DEVICE — any NaN/inf in
        # loss, grads, or activations propagates into the norm, which is
        # already computed for gradients/global_norm. The flag rides back in
        # the stats dict the learn loop fetches anyway: zero extra host
        # syncs. Only the "skip" policy also SELECTS the old params/opt
        # state on device — the select keeps both state versions live, which
        # defeats donation's in-place update (≈2× train-step temp memory;
        # visible in benchmarks/perf_budgets.json). "rollback"/"halt" need
        # only the flag: the host restores a committed checkpoint / raises,
        # so their train step keeps the donated, guard-free memory profile.
        guard_policy = self.resilience.guard.policy
        guard_flag = guard_policy != "off"
        guard_select = guard_policy == "skip"

        def scaled_loss(params, batch, rng, loss_scale):
            # loss_scale is 1.0 outside fault injection — an exact identity
            # multiply (IEEE x*1.0 == x bitwise) — and NaN when the plan
            # poisons this step, making loss AND grads non-finite
            loss, stats = self.loss_fn(params, batch, rng)
            return loss * loss_scale, stats

        def grads_of(params, batch, rng, loss_scale):
            return jax.value_and_grad(scaled_loss, has_aux=True)(
                params, batch, rng, loss_scale
            )

        def accumulated_grads(params, batch, step_rng, loss_scale):
            """lax.scan over ``accum`` microbatches; grads and stats averaged.

            Whitening/running statistics inside ``loss_fn`` see one
            microbatch at a time (same as the reference under DeepSpeed
            accumulation, where each micro forward is independent).
            """
            micro = jax.tree_util.tree_map(
                lambda x: x.reshape((accum, x.shape[0] // accum) + x.shape[1:]),
                batch,
            )
            rngs = jax.random.split(step_rng, accum)
            # zero-init the carry from eval_shape so the model's fwd+bwd is
            # traced exactly once (inside the scan body) — peeling the first
            # microbatch would duplicate the whole HLO graph
            first = jax.tree_util.tree_map(lambda x: x[0], micro)
            (_, stats_sh), grads_sh = jax.eval_shape(
                grads_of, params, first, rngs[0], loss_scale
            )
            zeros = lambda tree: jax.tree_util.tree_map(  # noqa: E731
                lambda s: jnp.zeros(s.shape, s.dtype), tree
            )

            def body(carry, xs):
                grads_acc, stats_acc = carry
                mb, r = xs
                (_, stats_i), grads_i = grads_of(params, mb, r, loss_scale)
                grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc, grads_i)
                stats_acc = jax.tree_util.tree_map(jnp.add, stats_acc, stats_i)
                return (grads_acc, stats_acc), None

            (grads, stats), _ = jax.lax.scan(
                body, (zeros(grads_sh), zeros(stats_sh)), (micro, rngs)
            )
            grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
            stats = jax.tree_util.tree_map(lambda s: s / accum, stats)
            # per-trainer loss key varies; callers only consume stats
            return (jnp.zeros(()), stats), grads

        def step_fn(state: TrainState, batch: Dict[str, jax.Array], loss_scale):
            rng, step_rng = jax.random.split(state.rng)
            if accum == 1:
                (loss, stats), grads = grads_of(
                    state.params, batch, step_rng, loss_scale
                )
            else:
                (loss, stats), grads = accumulated_grads(
                    state.params, batch, step_rng, loss_scale
                )
            updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            stats = dict(stats)
            stats["learning_rate"] = (
                schedule(state.step) if callable(schedule) else schedule
            )
            gnorm = optax.global_norm(grads)
            stats["gradients/global_norm"] = gnorm
            step_inc = 1
            if guard_flag:
                ok = jnp.isfinite(gnorm)
                if accum == 1:
                    ok = ok & jnp.isfinite(loss)
                stats["resilience/update_ok"] = ok.astype(jnp.float32)
            if guard_select:
                # scalar select per leaf: when the check fails, the update
                # (and the step counter driving the LR schedule) is dropped
                # on device — the poison batch never touches the weights
                params = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(ok, n, o), params, state.params
                )
                opt_state = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(ok, n, o), opt_state, state.opt_state
                )
                step_inc = ok.astype(jnp.int32)
            new_state = TrainState(
                params=params,
                opt_state=opt_state,
                step=state.step + step_inc,
                rng=rng,
            )
            return new_state, stats

        if state_shardings is not None:
            # stats stay unspecified (None): XLA picks, as before
            return jax.jit(
                step_fn, donate_argnums=(0,), out_shardings=(state_shardings, None)
            )
        return jax.jit(step_fn, donate_argnums=(0,))

    def _drop_batch_memo(self) -> None:
        """Release the memoized sharded batch (one batch of HBM) once its
        replay window is over — before rollout collection / final eval."""
        self._last_batch_host = None
        self._last_batch_sharded = None

    def _maybe_prefetch(self, loader, depth: Optional[int] = None):
        """Wrap a loader in background-thread prefetch (``depth`` batches
        ahead, default ``train.prefetch_batches``) so collation overlaps the
        device step — the reference's DataLoader-worker capability."""
        if depth is None:
            depth = getattr(self.config.train, "prefetch_batches", 0)
        if depth and depth > 0 and loader is not None:
            from trlx_tpu.pipeline import PrefetchLoader

            return PrefetchLoader(loader, depth)
        return loader

    def _maybe_prefetch_prompts(self, loader):
        """Prompt-side seam of :meth:`_maybe_prefetch`, gated on the rollout
        pipeline depth (``train.rollout_pipeline_depth``): prompt collation
        runs ahead on a background thread so ``next(prompt_iterator)`` never
        stalls the chunk dispatch loop in ``make_experience``. One worker
        preserves batch order, so rollout determinism is unaffected."""
        depth = int(getattr(self.config.train, "rollout_pipeline_depth", 0) or 0)
        return self._maybe_prefetch(loader, depth)

    def _count_prompt_chunks(self, iterator):
        """Wrap the (infinite) prompt iterator so every chunk the trainer
        consumes advances ``_prompt_chunks_drawn``. Emergency checkpoints
        record the count and resume replays exactly that many draws
        (:meth:`load`), so the prompt stream — and the loader's per-epoch
        shuffle RNG behind it — sits precisely where an uninterrupted run
        would have it. Without this, the first post-resume collection trains
        on the *initial* prompts again and the trajectory silently forks."""
        for chunk in iterator:
            self._prompt_chunks_drawn += 1
            yield chunk

    def _batch_token_count(self, batch: Any) -> int:
        """Real (unpadded) tokens this batch feeds the step — from the batch
        masks, so padding doesn't inflate ``throughput/tokens_per_sec``."""
        items = batch._asdict() if hasattr(batch, "_asdict") else batch
        if not isinstance(items, dict):
            return 0
        if "attention_mask" in items:
            return int(np.asarray(items["attention_mask"]).sum())
        masks = [
            v for k, v in items.items() if k.endswith("mask") and hasattr(v, "sum")
        ]
        if masks:
            return int(sum(np.asarray(m).sum() for m in masks))
        for v in items.values():
            if hasattr(v, "shape") and len(v.shape) >= 2:
                return int(v.shape[0] * v.shape[1])
        return 0

    def _export_observability(self) -> None:
        """Best-effort span export (``trace.json`` + ``spans.jsonl``) next to
        the tracker's stats — never allowed to fail a training run."""
        try:
            paths = self.obs.export()
            if paths:
                logger.info(f"wrote span trace: {paths['trace']}")
        except Exception as e:  # pragma: no cover - defensive
            logger.warning(f"span trace export failed: {e}")

    def train_step(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """One optimization step on a host batch; returns host scalar stats.

        The sharded device copy is memoized on the batch object: the PPO
        inner loop replays the same batch ``ppo_epochs`` times
        (``n_updates_per_batch``), and one host→device transfer serves all
        replays."""
        set_global_mesh(self.mesh)
        plan = self.resilience.plan
        if (
            plan
            and jax.process_index() == jax.process_count() - 1
            and plan.poll("sleep_one_proc", step=self.iter_count)
        ):
            # deterministic straggler: stall the LAST rank's step so the
            # cluster-telemetry watchdog has something real to flag
            # (cluster/straggler_rank; docs/OBSERVABILITY.md)
            from time import sleep as _sleep

            from trlx_tpu.resilience.faults import SLEEP_FAULT_S

            logger.warning(
                f"fault plan: sleeping {SLEEP_FAULT_S}s inside update "
                f"{self.iter_count} (injected straggler)"
            )
            _sleep(SLEEP_FAULT_S)
        if self._train_step_fn is None:
            self._train_step_fn = self._build_train_step()
        if batch is self._last_batch_host:
            arrays = self._last_batch_sharded
        else:
            items = batch._asdict() if hasattr(batch, "_asdict") else batch
            arrays = shard_batch(
                {k: v for k, v in items.items() if hasattr(v, "ndim")}, self.mesh
            )
            self._last_batch_host = batch
            self._last_batch_sharded = arrays
        self.state, stats = self._train_step_fn(self.state, arrays, self._loss_scale())
        # recompile watchdog: a warm train step retracing (shape/dtype
        # drift) is invisible otherwise — it just gets slow
        self.obs.recompile.observe("train_step", self._train_step_fn)
        return stats

    def _loss_scale(self) -> np.float32:
        """1.0, or NaN when the fault plan poisons this step's loss
        (``nan_loss@step:N`` — deterministic update-guard exercise). Traced
        as a scalar array argument, so both values share one compiled
        program and the clean-path multiply is an exact identity."""
        plan = self.resilience.plan
        if plan and plan.poll("nan_loss", step=self.iter_count):
            logger.warning(
                f"fault plan: poisoning the loss of update {self.iter_count} to NaN"
            )
            return np.float32(np.nan)
        return np.float32(1.0)

    def _ensure_train_step_flops(
        self, arrays: Optional[Dict[str, jax.Array]], wait: bool = False
    ) -> Optional[float]:
        """Per-device flops of the compiled train step (for MFU), computed
        once per trainer from the exact program via ``perf.lowered_costs``.

        The AOT lower+compile does not share the jit call path's executable
        cache, so it runs on a daemon thread — the hot loop never stalls on
        a duplicate XLA compile; ``throughput/mfu`` simply appears in the
        stats stream once the analysis lands (typically a few steps in).
        ``None`` while pending, unavailable, or disabled (``TRLX_TPU_MFU=0``)."""
        if (
            self._train_step_flops is None
            and self._flops_thread is None
            and self._train_step_fn is not None
            and arrays is not None
        ):
            import threading

            # abstract twins are built HERE (metadata only): the worker must
            # not hold the live state/batch arrays across later donations
            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=getattr(x, "sharding", None)
                ),
                (self.state, arrays, np.float32(1.0)),
            )

            def work(fn=self._train_step_fn, args=abstract):
                # -1 sentinel: tried and unavailable, don't retry
                self._train_step_flops = train_step_flops(fn, *args) or -1.0

            self._flops_thread = threading.Thread(
                target=work, name="trlx-tpu-flops", daemon=True
            )
            self._flops_thread.start()
        if (
            wait
            and self._flops_thread is not None
            and self._train_step_flops is None
        ):
            # end-of-run join: short runs still report a final MFU; a
            # still-compiling analysis on a big model gives up after the
            # timeout rather than stalling exit
            self._flops_thread.join(timeout=120.0)
        flops = self._train_step_flops
        return flops if flops is not None and flops > 0 else None

    # ------------------------------------------------------------------
    # generation
    # ------------------------------------------------------------------

    def _apply_fn(self):
        module = self.module

        def apply_fn(params, input_ids, **kw):
            return module.apply({"params": params}, input_ids, **kw)

        return apply_fn

    def _compose_logit_mask(self, adjust: Optional[Callable]) -> Optional[Callable]:
        """Chain the trainer's transition ``logit_mask`` after any algorithm
        logit reshaping: tokens whose ``mask[last_token, next_token]`` is
        False sample with −inf logits. Masks smaller than the vocab disallow
        out-of-range *next* tokens; out-of-range *last* tokens (no transition
        row exists for them) sample unconstrained rather than borrowing an
        unrelated row's constraints."""
        mask = self._logit_mask_array()
        if mask is None:
            return adjust
        from trlx_tpu.ops.sampling import apply_transition_mask

        def fn(step_out: Dict[str, Any], logits: jax.Array) -> jax.Array:
            if adjust is not None:
                logits = adjust(step_out, logits)
            return apply_transition_mask(mask, step_out["last_tokens"], logits)

        return fn

    def _logit_mask_array(self) -> Optional[jax.Array]:
        """The trainer's transition logit mask as a bool device array (one
        conversion for the step-sampler hook and the speculative path)."""
        if self.logit_mask is None:
            return None
        return jnp.asarray(np.asarray(self.logit_mask), bool)

    def _get_generate_fn(
        self, gen_config: GenerationConfig, extra_kwargs: Tuple[Tuple[str, Any], ...] = ()
    ) -> Callable:
        key = (gen_config, extra_kwargs)
        if key not in self._generate_fns:
            algo_adjust = self.adjust_logits_fn(dict(extra_kwargs))
            if self.is_seq2seq:
                adjust = self._compose_logit_mask(algo_adjust)
                module = self.module
                start_id = self.tcfg.decoder_start_token_id

                def encode_fn(params, input_ids, attention_mask, max_len):
                    return module.apply(
                        {"params": params}, input_ids, attention_mask, max_len,
                        method=type(module).encode_for_decode,
                    )

                def decode_fn(params, dec_ids, enc_hidden, enc_mask, cache, cache_index):
                    # keywords: T5Transformer.decode has decoder_mask as its
                    # 4th positional arg; positional cache would mis-bind
                    return module.apply(
                        {"params": params}, dec_ids, enc_hidden, enc_mask,
                        cache=cache, cache_index=cache_index,
                        method=type(module).decode,
                    )

                def fn(params, input_ids, attention_mask, rng):
                    return generate_seq2seq(
                        encode_fn,
                        decode_fn,
                        params,
                        input_ids,
                        attention_mask,
                        rng,
                        gen_config,
                        start_token_id=start_id,
                        adjust_logits=adjust,
                    )

            elif self.draft_module is not None:
                # speculative decoding: draft proposes, the policy verifies
                # γ tokens per forward — lossless, so the rollout semantics
                # (tokens/logprobs/values under the policy) are unchanged.
                # Every sampler feature composes: the transition logit_mask
                # (applied to draft AND target), min_new_tokens (per-row
                # positional eos blocking), and the algo adjust hook (ILQL
                # reshaping — applied to the target's verify distributions;
                # a mismatched plain draft only costs acceptance rate).
                from trlx_tpu.ops.speculative import generate_speculative

                apply_fn = self._apply_fn()
                draft_module = self.draft_module
                draft_params = self.draft_params
                tcfg, dcfg = self.tcfg, self.draft_tcfg
                gamma = self.config.model.draft_gamma
                trans_mask = self._logit_mask_array()

                def draft_apply(p, ids, **kw):
                    return draft_module.apply({"params": p}, ids, **kw)

                def fn(params, input_ids, attention_mask, rng):
                    # first arg is the target params, or the engine's
                    # (target, draft) tuple — the tuple form keeps draft
                    # params a traced operand instead of a closure, which
                    # abstract-weight lowering (trlx_tpu/perf.py) requires
                    if type(params) is tuple:
                        t_params, d_params = params
                    else:
                        t_params, d_params = params, draft_params
                    return generate_speculative(
                        apply_fn,
                        t_params,
                        draft_apply,
                        d_params,
                        lambda B, S: make_kv_cache(tcfg, B, S),
                        lambda B, S: make_kv_cache(dcfg, B, S),
                        input_ids,
                        attention_mask,
                        rng,
                        gen_config,
                        gamma=gamma,
                        return_stats=True,
                        transition_mask=trans_mask,
                        adjust_logits=algo_adjust,
                    )

            else:
                apply_fn = self._apply_fn()
                tcfg = self.tcfg
                adjust = self._compose_logit_mask(algo_adjust)

                def fn(params, input_ids, attention_mask, rng):
                    return generate(
                        apply_fn,
                        params,
                        lambda B, S: make_kv_cache(tcfg, B, S),
                        input_ids,
                        attention_mask,
                        rng,
                        gen_config,
                        adjust_logits=adjust,
                    )

            self._generate_fns[key] = jax.jit(fn)
        return self._generate_fns[key]

    def _resolve_gen_config(
        self, eval_mode: bool = False, **kwargs
    ) -> Tuple[GenerationConfig, Tuple[Tuple[str, Any], ...]]:
        """Resolve (gen_config, extra_kwargs) the way :meth:`generate` does —
        the shared seam for the plain sampler and the continuous-batching
        engine, so both see identical sampling semantics. ``extra_kwargs``
        are the non-GenerationConfig kwargs (hashable, for the program
        caches and the ``adjust_logits_fn`` hook)."""
        base = (
            self.generate_kwargs
            if eval_mode or self.generate_experience_kwargs is None
            else self.generate_experience_kwargs
        )
        gen_kwargs = dict(base)
        gen_kwargs.update(kwargs)
        gen_config = GenerationConfig.from_gen_kwargs(
            gen_kwargs,
            eos_token_id=self.tokenizer.eos_token_id,
            pad_token_id=self.tokenizer.pad_token_id,
        )
        import dataclasses as _dc

        known = {f.name for f in _dc.fields(GenerationConfig)}
        extra_kwargs = tuple(
            sorted(
                (k, tuple(v) if isinstance(v, list) else v)
                for k, v in gen_kwargs.items()
                if k not in known
            )
        )
        return gen_config, extra_kwargs

    def _get_slot_refill_fns(
        self,
        gen_config: GenerationConfig,
        extra_kwargs: Tuple[Tuple[str, Any], ...],
        batch_size: int,
        prompt_len: int,
        segment_len: int,
    ):
        """Compiled slot-refill programs (refill prefill + segment decode)
        for one shape bucket — the continuous-batching analogue of
        :meth:`_get_generate_fn`, sharing its adjust-hook composition so the
        engine samples exactly what plain ``generate`` would."""
        if self.is_seq2seq:
            raise NotImplementedError(
                "train.continuous_batching supports causal LMs only: the "
                "seq2seq decoder has no slot-refill path"
            )
        gamma = int(self.config.engine.speculative)
        if gamma and self.draft_module is None:
            # __init__ validates the config path; this guards direct callers
            raise ValueError(
                "engine.speculative requires model.draft_model_path (no "
                "draft model was built)"
            )
        if self.draft_module is not None and not gamma:
            if not getattr(self, "_warned_cb_draft", False):
                self._warned_cb_draft = True
                logger.warning(
                    "model.draft_model_path is set but engine.speculative "
                    "is 0: continuous batching runs PLAIN decode segments "
                    "(the serial path's model.draft_gamma does not apply "
                    "here — set engine.speculative to propose k tokens "
                    "per verify round)"
                )
        import dataclasses as _dc

        gen_config = _dc.replace(gen_config, per_row_rng=True)
        paged = self._resolve_paged_spec(
            batch_size, prompt_len, gen_config, gamma=gamma
        )
        decode_kernel = (
            self.config.engine.decode_kernel if paged is not None else "xla"
        )
        prefill_kernel = (
            self.config.engine.prefill_kernel if paged is not None else "xla"
        )
        key = (
            "slot_refill", gen_config, extra_kwargs, batch_size, prompt_len,
            segment_len, paged, decode_kernel, prefill_kernel, gamma,
        )
        if key not in self._generate_fns:
            from trlx_tpu.ops.slot_refill import make_slot_refill_fns

            algo_adjust = self.adjust_logits_fn(dict(extra_kwargs))
            tcfg = self.tcfg
            spec_kwargs = {}
            if gamma:
                # speculative segments take the transition mask SEPARATELY
                # (applied to draft AND target inside the shared round, the
                # serial generate_speculative convention) and the raw algo
                # hook for the target's verify distributions — composing
                # the mask into adjust would leave the draft unconstrained
                # and the acceptance rule lossy under constrained sampling
                adjust = algo_adjust
                draft_module, dcfg = self.draft_module, self.draft_tcfg

                def draft_apply(p, ids, **kw):
                    return draft_module.apply({"params": p}, ids, **kw)

                spec_kwargs = dict(
                    speculative=gamma,
                    draft_apply=draft_apply,
                    init_draft_cache_fn=lambda B, S: make_kv_cache(dcfg, B, S),
                    transition_mask=self._logit_mask_array(),
                )
            else:
                adjust = self._compose_logit_mask(algo_adjust)
            self._generate_fns[key] = make_slot_refill_fns(
                self._apply_fn(),
                lambda B, S: make_kv_cache(tcfg, B, S),
                batch_size,
                prompt_len,
                gen_config,
                adjust_logits=adjust,
                segment_len=segment_len,
                params_example=self.state.params,
                paged=paged,
                decode_kernel=decode_kernel,
                prefill_kernel=prefill_kernel,
                **spec_kwargs,
            )
        return self._generate_fns[key]

    def _engine_params(self, params: Any = None) -> Any:
        """The params object the rollout engines consume: the policy
        params, or — with ``engine.speculative`` on — the ``(target,
        draft)`` tuple the spec programs unpack. One object means
        ``swap_params`` adopts both trees atomically at a segment boundary
        (a mid-stream sync can never verify old-target against new-draft)."""
        target = self.state.params if params is None else params
        if int(self.config.engine.speculative):
            return (target, self.draft_params)
        return target

    def _resolve_paged_spec(
        self, batch_size: int, prompt_len: int, gen_config, gamma: int = 0
    ):
        """The paged-KV geometry for this trainer's ``engine:`` config
        section, or None for the dense backend. ``max_kv_blocks`` auto
        (0) sizes the pool so every slot can reach full length, plus an
        equal prefix-cache working set when the cache is on — lazy
        per-segment growth then keeps the *used* fraction at live tokens
        (docs/PERFORMANCE.md)."""
        ecfg = self.config.engine
        if ecfg.backend == "dense":
            return None
        if ecfg.backend != "paged":
            raise ValueError(
                f"unknown engine.backend '{ecfg.backend}' (dense | paged)"
            )
        from trlx_tpu.ops.paged_kv import PagedSpec, num_table_blocks

        bs = int(ecfg.kv_block_size)
        if bs < 1:
            raise ValueError(f"engine.kv_block_size {bs} must be >= 1")
        # speculative segments gather/scatter an S = P + N + gamma view
        # (solo's cache width — the G probe columns past the last commit),
        # so tables carry entries for the probe region too; only the
        # committable P + N columns ever consume allocated blocks
        table_blocks = num_table_blocks(
            prompt_len + gen_config.max_new_tokens + int(gamma), bs
        )
        max_blocks = int(ecfg.max_kv_blocks)
        if max_blocks <= 0:
            max_blocks = 1 + batch_size * table_blocks * (
                2 if self._prefix_cache_enabled() else 1
            )
        return PagedSpec(block_size=bs, max_blocks=max_blocks)

    def _prefix_cache_enabled(self) -> bool:
        """engine.prefix_cache, gated off (with a one-time warning) for MoE
        policies: expert capacity couples a row's tokens, so a suffix-only
        prefill is not bit-identical to the full prefill there."""
        if not self.config.engine.prefix_cache:
            return False
        if getattr(self.tcfg, "num_experts", 0):
            if not getattr(self, "_warned_moe_prefix", False):
                self._warned_moe_prefix = True
                logger.warning(
                    "engine.prefix_cache disabled: MoE expert capacity is "
                    "shared across a sequence's tokens, so suffix-only "
                    "prefill would not be bit-identical to the full "
                    "prefill (set engine.prefix_cache: false to silence)"
                )
            return False
        return True

    def generate(
        self,
        input_ids: np.ndarray,
        attention_mask: Optional[np.ndarray] = None,
        eval_mode: bool = False,
        params: Optional[Any] = None,
        rng: Optional[jax.Array] = None,
        **kwargs,
    ) -> GenerationOutput:
        """Sample continuations for a left-padded prompt batch.

        Rollout generation uses ``gen_experience_kwargs`` when configured
        (reference ``generate`` vs ``generate_eval``,
        ``accelerate_base_trainer.py:228-253``).

        ``params``/``rng`` default to the trainer's own state — the async
        actor path (docs/ASYNC_RL.md) passes both explicitly: actors sample
        under channel-published param copies (never ``state.params``, whose
        buffers the donated train step invalidates), and a requeued chunk
        regenerates under its dispatched RNG.
        """
        set_global_mesh(self.mesh)
        gen_config, extra_kwargs = self._resolve_gen_config(eval_mode, **kwargs)
        input_ids = np.asarray(input_ids, np.int32)
        if attention_mask is None:
            attention_mask = (input_ids != self.tokenizer.pad_token_id).astype(np.int32)
        if rng is None:
            self._rollout_rng, rng = jax.random.split(self._rollout_rng)
        # the serial dense path behind the unified Engine interface
        # (trlx_tpu/engine/core.py) — the wrapped jitted program is
        # unchanged: it stays the bit-equivalence reference for the
        # continuous-batching and paged backends. The params-override path
        # (async actor threads) gets a PER-THREAD engine wrapper: engines
        # carry mutable `params`, and an actor generating concurrently with
        # the learner's eval on one shared wrapper would clobber each
        # other's params mid-call (the compiled program underneath is still
        # shared via _get_generate_fn's cache — wrappers are thin).
        if params is not None:
            import threading as _threading

            engine = self._get_serial_engine(
                gen_config, extra_kwargs, tag=_threading.get_ident()
            )
            engine.params = params
        else:
            engine = self._get_serial_engine(gen_config, extra_kwargs)
        batch = shard_batch(
            {"input_ids": input_ids, "attention_mask": np.asarray(attention_mask, np.int32)},
            self.mesh,
        )
        # cleared up front so stats only ever reflect the *current* rollout
        # path — a draft-less or seq2seq generate must not keep reporting a
        # stale acceptance rate from an earlier speculative call
        self.last_spec_stats = {}
        self._note_dense_kv_gauge(input_ids.shape, gen_config)
        # fenced span: duration is device-true decode time, not dispatch
        # latency (nests under make_experience's "rollout" span)
        with self.obs.span("generate", eval_mode=bool(eval_mode)) as sp:
            out = engine.generate(batch["input_ids"], batch["attention_mask"], rng)
            if type(out) is tuple:  # speculative sampler: (output, stats) —
                # GenerationOutput itself is a NamedTuple, hence the exact check
                out, spec_stats = out
                # recorded for make_experience's stats (rollout observability:
                # the knob this informs is model.draft_gamma)
                # device_get already lands host scalars; no asarray needed
                self.last_spec_stats = {
                    "rollout/spec_acceptance_rate": float(
                        jax.device_get(spec_stats["acceptance_rate"])
                    ),
                    "rollout/spec_rounds": int(
                        jax.device_get(spec_stats["rounds"])
                    ),
                }
            sp.fence((out.sequences, out.response_tokens))
        self.last_generate_time = sp.duration
        self.obs.recompile.observe("generate", engine._fn)
        return out

    def _get_serial_engine(self, gen_config, extra_kwargs, tag=None):
        """The SerialEngine wrapping this (config, kwargs)'s jitted rollout
        program — cached alongside the programs themselves; params are
        refreshed per call (the policy trains between collections).
        ``tag`` isolates wrappers per caller thread (async actors)."""
        key = ("serial_engine", gen_config, extra_kwargs, tag)
        if key not in self._generate_fns:
            from trlx_tpu.engine.core import SerialEngine

            self._generate_fns[key] = SerialEngine(
                self._get_generate_fn(gen_config, extra_kwargs),
                self.state.params,
                self.tokenizer.pad_token_id,
            )
        engine = self._generate_fns[key]
        engine.params = self.state.params
        return engine

    def _note_dense_kv_gauge(self, prompt_shape, gen_config) -> None:
        """``memory/kv_cache_bytes`` for the serial dense path: the cache
        is allocated inside the jitted program, so the gauge is computed
        from the static shapes (exact). The continuous-batching engines
        report their own measured gauge (EngineStats.metrics)."""
        if self.is_seq2seq:
            return  # T5 cross/self caches have their own layout; not gauged
        from trlx_tpu.ops.paged_kv import dense_kv_bytes

        B, P = prompt_shape
        S = P + gen_config.max_new_tokens
        total = dense_kv_bytes(self.tcfg, B, S)
        if self.draft_module is not None:
            # speculative decoding: target + draft caches, both S + gamma
            # slots (ops/speculative.py sizes them P + N + G)
            S_spec = S + int(self.config.model.draft_gamma)
            total = dense_kv_bytes(self.tcfg, B, S_spec) + dense_kv_bytes(
                self.draft_tcfg, B, S_spec
            )
        self.obs.metrics.set_gauge("memory/kv_cache_bytes", float(total))

    def generate_eval(self, input_ids, attention_mask=None, **kwargs) -> GenerationOutput:
        return self.generate(input_ids, attention_mask, eval_mode=True, **kwargs)

    # ------------------------------------------------------------------
    # decoding
    # ------------------------------------------------------------------

    def decode(
        self,
        prompt_ids: np.ndarray,  # [B, P] left-padded
        response_ids: np.ndarray,  # [B, N] right-padded
        append_eos_token: bool = False,
    ) -> Tuple[List[str], List[str], List[str]]:
        """Token batches → (samples, prompts, outputs) strings, trimming
        outputs at the first stop sequence and optionally re-appending eos
        (reference ``decode``, ``accelerate_base_trainer.py:200-226``)."""
        str_samples, str_prompts, str_outputs = [], [], []
        for prompt_row, response_row in zip(np.asarray(prompt_ids), np.asarray(response_ids)):
            str_prompt = self.tokenizer.decode(prompt_row.tolist(), skip_special_tokens=True)
            str_output = self.tokenizer.decode(response_row.tolist(), skip_special_tokens=True)
            if self.stop_sequences:
                for stop in self.stop_sequences:
                    result = str_output.split(stop)[0]
                    str_output = result
            if append_eos_token:
                str_output += self.tokenizer.eos_token
            str_prompts.append(str_prompt)
            str_outputs.append(str_output)
            if self.is_seq2seq:
                # seq2seq samples join prompt and output with the sep token
                # (reference ``decode``, ``accelerate_base_trainer.py:219-221``)
                sep = getattr(self.tokenizer, "sep_token", None) or " "
                str_samples.append(str_prompt + sep + str_output)
            else:
                str_samples.append(str_prompt + str_output)
        return str_samples, str_prompts, str_outputs

    # ------------------------------------------------------------------
    # evaluation
    # ------------------------------------------------------------------

    def evaluate(self) -> Dict[str, Any]:  # noqa: C901
        """Generate on eval prompts; score with reward/metric fns.

        Supports a single list-valued gen kwarg swept across generations
        (reference ``accelerate_base_trainer.py:286-428``).
        """
        set_global_mesh(self.mesh)
        logger.info("Evaluating model")
        stats: Dict[str, Any] = {}
        table_rows: List[List[Any]] = []

        sweep_key, sweep_values = None, [None]
        for k, v in self.generate_kwargs.items():
            if isinstance(v, list):
                sweep_key, sweep_values = k, v
                break

        eval_batch_size = self.config.train.eval_batch_size or self.config.train.batch_size
        loader = self.eval_pipeline.create_loader(eval_batch_size)

        for sweep_value in sweep_values:
            gen_overrides = {sweep_key: sweep_value} if sweep_key else {}
            all_prompts: List[str] = []
            all_outputs: List[str] = []
            all_samples: List[str] = []
            # device-true: every generate() call below fences on its outputs
            # at span exit, so this loop timer no longer reads dispatch
            gen_time = time()
            for batch in loader:
                out = self.generate_eval(
                    batch["input_ids"], batch["attention_mask"], **gen_overrides
                )
                prompt_ids = np.asarray(out.sequences)[:, : batch["input_ids"].shape[1]]
                response_ids = to_host(out.response_tokens)
                samples, prompts, outputs = self.decode(prompt_ids, response_ids)
                all_samples += samples
                all_prompts += prompts
                all_outputs += outputs
            stats["time/generate"] = time() - gen_time

            suffix = f"@{sweep_key}={sweep_value}" if sweep_key else ""
            if self.reward_fn:
                rewards = np.asarray(
                    self.reward_fn(
                        samples=all_samples, prompts=all_prompts, outputs=all_outputs
                    ),
                    dtype=np.float64,
                )
                stats[f"reward/mean{suffix}"] = float(rewards.mean())
                stats[f"reward/std{suffix}"] = float(rewards.std())
            else:
                rewards = [None] * len(all_samples)
            if self.metric_fn:
                metric_time = time()
                metrics = self.metric_fn(
                    samples=all_samples, prompts=all_prompts, outputs=all_outputs
                )
                stats["time/metric"] = time() - metric_time
                for name, values in metrics.items():
                    arr = np.asarray(values, dtype=np.float64)
                    stats[f"metrics/{name}{suffix}"] = (
                        float(arr.mean()) if arr.size else 0.0
                    )

            for i in range(min(len(all_prompts), 8)):
                row = [all_prompts[i], all_outputs[i]]
                if self.reward_fn:
                    row.append(significant(float(rewards[i])))
                if sweep_key:
                    row.append(sweep_value)
                table_rows.append(row)

        if jax.process_index() == 0 and table_rows:
            lines = ["prompt | output" + (" | reward" if self.reward_fn else "")]
            for row in table_rows[:8]:
                lines.append(" | ".join(str(c)[:80].replace("\n", "⏎") for c in row))
            logger.info("Eval samples:\n" + "\n".join(lines))

        self.nth_evaluation += 1
        return stats

    def _report_sweep(self, stats: Dict[str, Any]) -> None:
        """Write the latest eval stats to ``$TRLX_TPU_SWEEP_RESULT`` for the
        sweep runner — the subprocess analogue of the reference's Ray
        ``session.report`` (``accelerate_base_trainer.py:510-511``), written
        at every evaluation so interrupted trials still report."""
        path = os.environ.get("TRLX_TPU_SWEEP_RESULT")
        if not path or jax.process_index() != 0:
            return
        payload = {
            "iter_count": self.iter_count,
            "stats": filter_non_scalars(to_host(stats)),
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)

    # ------------------------------------------------------------------
    # the learn loop
    # ------------------------------------------------------------------

    def learn(self) -> Dict[str, Any]:
        """Epochs → batches → n updates per batch, with interval checkpoints,
        interval eval, and best-reward checkpointing (reference
        ``accelerate_base_trainer.py:433-553``).

        Resilience wiring (docs/RESILIENCE.md): SIGTERM/SIGINT handlers are
        installed for the duration of the loop (emergency checkpoint at the
        next step boundary, then :class:`TrainingPreempted`); any exception
        — including a crash — flushes the tracker and exports the span
        trace before propagating, so a dying run keeps its metrics."""
        set_global_mesh(self.mesh)
        logger.info("Starting training")
        self.prepare_learning()
        self.maybe_resume()
        self._maybe_start_serving()
        try:
            with self.resilience.preemption:
                return self._learn_loop()
        except BaseException as e:
            # crash-safe shutdown: without this, an exception loses every
            # buffered tracker record and the whole Perfetto trace — and
            # the flight recorder's last-moments ring (flightrec.json)
            self._shutdown_observability(
                reason=f"{type(e).__name__}: {e}"
            )
            raise
        finally:
            # async actors (threads or a remote fleet waiting on the weight
            # channel) must not outlive the learn loop — on a clean finish
            # AND on every crash/preemption path (docs/ASYNC_RL.md)
            self._shutdown_collectors()

    def _maybe_start_serving(self) -> None:
        """Stand up the serving frontend (``serve.enabled``,
        docs/SERVING.md): a dedicated ContinuousEngine built through the
        SAME slot-refill program cache as the collection engines, owned by
        the serve pump thread for the whole ``learn()`` run, receiving
        every published params version at step boundaries."""
        cfg = self.config.serve
        if not cfg.enabled or self._serve is not None:
            return
        if not hasattr(self, "_cb_make_engine"):
            raise ValueError(
                f"serve.enabled: {type(self).__name__} has no continuous-"
                "batching engine path to serve from (PPO-family trainers "
                "only)"
            )
        gen_kwargs: Dict[str, Any] = {}
        if int(cfg.max_new_tokens) > 0:
            gen_kwargs["max_new_tokens"] = int(cfg.max_new_tokens)
        gen_config, extra_kwargs = self._resolve_gen_config(
            eval_mode=True, **gen_kwargs
        )
        engine = self._cb_make_engine(
            gen_config,
            extra_kwargs,
            int(cfg.slots),
            1,
            tag="serve",
            version=self.iter_count,
        )
        engine.reserve_slots = int(cfg.reserve_slots)
        for tenant, blocks in (cfg.tenant_quota_blocks or {}).items():
            engine.allocator.set_tenant_quota(str(tenant), int(blocks))
        if int(cfg.host_tier_blocks) > 0:
            from trlx_tpu.ops.paged_kv import block_bytes
            from trlx_tpu.serve.tiering import HostTier

            engine.attach_host_tier(
                HostTier(
                    int(cfg.host_tier_blocks),
                    block_bytes=block_bytes(engine.state.cache),
                )
            )
        from trlx_tpu.serve.server import ServeServer

        slo_s = {
            k: float(v)
            for k, v in (
                ("interactive", cfg.slo_interactive_s),
                ("eval", cfg.slo_eval_s),
                ("actor", cfg.slo_actor_s),
            )
            if float(v) > 0
        }
        self._serve = ServeServer(
            engine,
            default_tenant=cfg.default_tenant,
            default_class=cfg.default_class,
            slo_s=slo_s,
            max_queue=int(cfg.max_queue),
            stream_buffer=int(cfg.stream_buffer),
            drain_timeout_s=float(cfg.drain_timeout_s),
            retain_param_versions=int(cfg.retain_param_versions),
            default_max_new_tokens=int(cfg.max_new_tokens),
        )
        # publish BEFORE exposing the HTTP port: the pump drains params
        # ahead of ingress, so every request admitted once the listener is
        # up is stamped with a real version (never a pre-publish None)
        self._serve.publish(self._serve_params_copy(), version=self.iter_count)
        self._serve.start(host=cfg.host, port=int(cfg.port))
        logger.info(
            f"serving frontend up on {cfg.host}:{self._serve.port} "
            f"({cfg.slots} slots, classes {list(slo_s) or 'un-SLO-gated'})"
        )

    def _serve_params_copy(self) -> Any:
        """Buffer-owning copy of the engine-params tree for the serve pump
        (the weight-channel idiom, ``async_rl/channel.py``): the train step
        donates its input state, so a published alias of ``state.params``
        would be invalidated under the pump mid-decode — and under
        ``serve.retain_param_versions`` the history must stay readable
        after arbitrarily many later updates."""
        return jax.tree_util.tree_map(jnp.copy, self._engine_params())

    def _shutdown_collectors(self) -> None:
        """Stop any background experience collectors (PPO's async
        actor/learner split overrides and chains back here). Never raises.

        Closing the prompt-iterator generator chain unwinds
        ``PrefetchLoader.__iter__``'s ``finally`` — which is what joins the
        ``trlx-prefetch`` worker: a consumer that stopped mid-epoch
        otherwise leaves the worker parked on a full queue until the
        trainer is garbage-collected (caught by the leaked-thread sentinel
        in tests/conftest.py, the dynamic complement of graftlint GL403).

        The serving frontend drains FIRST (new admissions 503, in-flight
        requests get ``serve.drain_timeout_s`` to finish, both serve
        threads joined) — on the clean path AND on every crash/preemption
        path, composing with the emergency-checkpoint exit: a SIGTERM'd
        run writes its checkpoint at the step boundary, then drains serving
        on the way out (docs/SERVING.md "Graceful drain")."""
        serve = self._serve
        if serve is not None:
            self._serve = None
            try:
                serve.drain()
            except Exception:  # pragma: no cover - defensive teardown
                logger.warning("serve drain failed", exc_info=True)
        self._close_prompt_iterator()

    def _close_prompt_iterator(self) -> None:
        iterator = getattr(self, "prompt_iterator", None)
        if iterator is not None and hasattr(iterator, "close"):
            try:
                iterator.close()
            except Exception:  # pragma: no cover - defensive
                pass

    def _shutdown_observability(self, reason: Optional[str] = None) -> None:
        """Best-effort flush of profiler, span trace, and tracker — callable
        from exception paths, never raising. A non-None ``reason`` marks a
        crash path and additionally dumps the flight recorder
        (``flightrec.json``): any exception, NaN-halt, and preemption all
        funnel through here (docs/OBSERVABILITY.md "Flight recorder")."""
        try:
            self.obs.profile.stop()
        except Exception:  # pragma: no cover - defensive
            pass
        if reason is not None:
            try:
                self.obs.dump_flight_record(reason=reason)
            except Exception:  # pragma: no cover - defensive
                pass
        self._export_observability()
        try:
            self.tracker.finish()
        except Exception:  # pragma: no cover - defensive
            pass

    def _triage_extra(self, arrays: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Subclass hook: derived per-token quantities worth keeping with a
        triaged batch (e.g. the PPO trainer adds advantages/returns and
        per-token logprob deltas). Must not raise past its own best effort."""
        return {}

    def _dump_triage(self, reason: str, stats: Dict[str, Any]) -> Optional[str]:
        """Write the current (memoized) batch as ``triage/step<N>.npz`` so a
        bad update is reproducible offline — tokens, masks, and whatever the
        trainer derives (docs/OBSERVABILITY.md "Training dynamics").

        Bounded (first ``TRIAGE_MAX_ROWS`` rows, at most ``TRIAGE_MAX_DUMPS``
        files per run), atomic (tmp + ``os.replace``), process 0 only, and
        never raises — it runs on failure paths. Returns the path or None."""
        if jax.process_index() != 0:
            return None
        directory = self.obs._trace_dir
        batch = self._last_batch_host
        if hasattr(batch, "_asdict"):
            batch = batch._asdict()
        if not directory or not isinstance(batch, dict):
            return None
        if self._triage_dumps >= TRIAGE_MAX_DUMPS:
            return None
        try:
            arrays: Dict[str, np.ndarray] = {}
            for key, value in batch.items():
                if hasattr(value, "shape") and getattr(value, "ndim", 0) > 0:
                    arrays[key] = np.asarray(value[:TRIAGE_MAX_ROWS])
            if not arrays:
                return None
            try:
                extra = self._triage_extra(arrays)
            except Exception:  # pragma: no cover - defensive
                extra = {}
            for key, value in extra.items():
                arrays.setdefault(key, np.asarray(value)[:TRIAGE_MAX_ROWS])
            meta = {
                "step": self.iter_count,
                "reason": reason,
                "rows": int(next(iter(arrays.values())).shape[0]),
                "stats": {
                    k: float(v)
                    for k, v in stats.items()
                    if isinstance(v, (int, float)) and np.isfinite(v)
                },
            }
            arrays["__meta__"] = np.frombuffer(
                json.dumps(meta).encode("utf-8"), dtype=np.uint8
            )
            triage_dir = os.path.join(directory, "triage")
            os.makedirs(triage_dir, exist_ok=True)
            path = os.path.join(triage_dir, f"step{self.iter_count}.npz")
            tmp = path + ".tmp.npz"
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, path)
            self._triage_dumps += 1
            self.obs.metrics.inc("health/triage_dumps")
            self.obs.flightrec.record(
                "triage",
                {
                    "step": self.iter_count,
                    "reason": reason,
                    "path": path,
                    "keys": sorted(k for k in arrays if k != "__meta__"),
                },
            )
            logger.warning(f"triage batch dumped to {path} ({reason})")
            return path
        except Exception:  # pragma: no cover - defensive, crash-path code
            logger.warning("triage dump failed", exc_info=True)
            return None

    def _check_faults_and_preemption(self) -> None:
        """Step-boundary seam, called before every update: deliver any
        fault-plan signals for this step, coordinate the preemption flag
        across processes, then honor an agreed request with one committed
        emergency checkpoint."""
        import signal as _signal

        plan = self.resilience.plan
        if plan:
            # raise_signal runs the installed handler synchronously, so the
            # request is honored at THIS boundary — fully deterministic
            if plan.poll("sigterm", step=self.iter_count):
                _signal.raise_signal(_signal.SIGTERM)
            if plan.poll("sigint", step=self.iter_count):
                _signal.raise_signal(_signal.SIGINT)
            # the multihost fault: every process polls (lockstep counters),
            # only process 0 is actually signaled — the coordination
            # allgather below must carry the request to the peers
            if (
                plan.poll("sigterm_one_proc", step=self.iter_count)
                and jax.process_index() == 0
            ):
                _signal.raise_signal(_signal.SIGTERM)
            if plan.poll("flightrec_dump", step=self.iter_count):
                # deterministic flight-recorder exercise: same dump path as
                # the crash/NaN-halt/preemption shutdown, no crash needed
                self.obs.dump_flight_record(
                    reason=f"fault plan: flightrec_dump@step:{self.iter_count}"
                )
            if plan.poll("health_trip", step=self.iter_count):
                # arm an injected detector trip; this step's health update
                # consumes it and runs the organic flightrec+triage path
                self.obs.health.force_trip("fault_plan", step=self.iter_count)
            if self._serve is not None and plan.poll(
                "request_flood", step=self.iter_count
            ):
                # admission-control drill (docs/RESILIENCE.md): a synthetic
                # burst through the real gate must shed load with 429s
                rejected = self._serve.flood_drill()
                logger.warning(
                    f"request_flood drill at step {self.iter_count}: "
                    f"{rejected} synthetic requests shed by admission"
                )
        if self._serve is not None:
            # serve-while-training: every step boundary publishes the fresh
            # params; the pump adopts them at its next serve-idle point, so
            # every response is generated under ONE params version
            self._serve.publish(self._serve_params_copy(), version=self.iter_count)
        preemption = self.resilience.preemption
        requested = preemption.requested
        coordinate = self.resilience.config.coordinate_preemption
        if self.obs.cluster.enabled or coordinate:
            # cross-rank telemetry beat (docs/OBSERVABILITY.md "Distributed
            # telemetry"): ONE allgather carries the preemption flag AND the
            # per-rank scalars (step time, host wait, tokens/s, memory) —
            # the coordinated-preemption collective, not a new sync point.
            # With coordination disabled the beat stays local (no
            # collective) and only this rank's gauges publish. The beat is
            # the ONLY collective on this boundary and whether it posts
            # depends only on `coordinate` (rank-uniform config, graftlint
            # GL704) — never on the per-process TRLX_TPU_CLUSTER_TELEMETRY
            # env gate, which would let one mis-launched rank post a
            # mismatched collective and hang the pod (a telemetry-disabled
            # rank still rides the same allgather, skipping only the
            # analysis).
            requested_any = self.obs.cluster.beat(
                requested, step=self.iter_count, collective=coordinate
            )
            if coordinate:
                requested = requested_any
        if not requested:
            return
        if not preemption.requested:
            # this process was not signaled itself; a peer was
            preemption.request("peer preemption (coordinated)")
        self.obs.flightrec.record(
            "resilience",
            {
                "event": "preemption",
                "signal": preemption.signal_received,
                "step": self.iter_count,
            },
        )
        subfolder = f"checkpoint_{self.iter_count:0{len(str(self.total_steps))}d}"
        path = os.path.join(self.config.train.checkpoint_dir, subfolder)
        logger.warning(
            f"preemption ({preemption.signal_received}): writing emergency "
            f"checkpoint to {path}"
        )
        self.save(path, emergency=True)
        wait_for_saves()  # the commit marker must land before we exit
        raise TrainingPreempted(
            f"preempted by {preemption.signal_received}; emergency checkpoint "
            f"committed at {path} — relaunch with "
            "train.resume_from_checkpoint to continue",
            checkpoint_dir=path,
        )

    def _learn_loop(self) -> Dict[str, Any]:  # noqa: C901
        # Emergency resume: the checkpoint froze the run between two
        # updates. Fast-forward the loop to that exact boundary — skipped
        # slots run no device work, no eval, no callbacks (all of that
        # happened before the checkpoint; the rollout RNG and controller
        # state were restored with it), so the resumed run's stream of
        # device calls is identical to an uninterrupted run's.
        emergency_resume = self._emergency_resume
        self._emergency_resume = False
        skip_target = self.iter_count if emergency_resume else 0
        done = 0

        if emergency_resume:
            results: Dict[str, Any] = {}
            logger.info(
                f"emergency resume: fast-forwarding to update {skip_target}"
            )
        else:
            results = self.evaluate()
            self.tracker.log(results, step=self.iter_count)
            self._report_sweep(results)
        clock = Clock()

        tbar = logging.tqdm(
            initial=self.iter_count,
            total=self.total_steps,
            disable=jax.process_index() != 0,
            position=0,
            leave=True,
        )

        profile = self.obs.profile
        for _ in range(self.config.train.epochs):
            if done < skip_target:
                # fully-skipped epochs cost nothing (not even collation)
                try:
                    per_epoch = len(self.train_dataloader) * self.n_updates_per_batch
                except TypeError:
                    per_epoch = None
                if per_epoch and done + per_epoch <= skip_target:
                    done += per_epoch
                    # trainers that reuse one loader across epochs (SFT/
                    # ILQL) draw a fresh shuffle per epoch from a stateful
                    # RNG: burn the skipped epoch's draw so the resume
                    # epoch's order matches the uninterrupted run. Trainers
                    # that rebuild the loader every epoch (PPO's post-epoch
                    # refill) must NOT burn — their resumed loader is
                    # already the fresh one.
                    if not getattr(self, "_fresh_loader_per_epoch", False) and hasattr(
                        self.train_dataloader, "advance_epoch"
                    ):
                        self.train_dataloader.advance_epoch()
                    continue
            epoch_ran = False
            for batch in self._maybe_prefetch(self.train_dataloader):
                batch_ran = False
                for _ in range(self.n_updates_per_batch):
                    if done < skip_target:
                        done += 1
                        continue
                    batch_ran = epoch_ran = True
                    self._check_faults_and_preemption()
                    profile.on_step_start(self.iter_count)
                    with profile.step_annotation("train", self.iter_count):
                        with self.obs.span("train_step") as sp:
                            device_stats = self.train_step(batch)
                            # fence on the new state AND the stat outputs:
                            # the donated-state update can still be in
                            # flight after the stats land, and without any
                            # fence the timer reads async dispatch latency
                            sp.fence((self.state, device_stats))
                    host_stats = to_host(device_stats)
                    stats = filter_non_scalars(host_stats)
                    # collapse the on-device distribution sketches into
                    # dist/* percentile gauges BEFORE the filter's output is
                    # used — the raw histogram arrays live only in host_stats
                    stats.update(self.obs.dynamics.summarize(host_stats))
                    # a guard-rejected update is the one moment the offending
                    # batch is still in hand — triage it before any rollback
                    # (docs/RESILIENCE.md "Update guard", OBSERVABILITY.md
                    # "Training dynamics")
                    if stats.get(UPDATE_OK_KEY) == 0.0:
                        if self._dump_triage("update_guard", stats):
                            self.obs.dump_flight_record(
                                reason=f"update guard rejected step {self.iter_count}"
                            )
                    # update guard: the on-device finiteness flag landed
                    # with the stats; skip was already applied on device,
                    # rollback/halt are host decisions (docs/RESILIENCE.md)
                    if self.resilience.guard.after_step(stats) == "rollback":
                        self._rollback_to_committed()
                    step_time = sp.duration
                    stats["time/step"] = step_time
                    stats["time/train_step"] = step_time
                    batch_size = next(
                        v.shape[0] for v in batch.values() if hasattr(v, "shape")
                    ) if isinstance(batch, dict) else self.config.train.batch_size
                    stats.update(
                        self.obs.throughput.step_stats(
                            step_time,
                            tokens=self._batch_token_count(batch),
                            samples=batch_size,
                            flops_per_device=self._ensure_train_step_flops(
                                self._last_batch_sharded
                            ),
                        )
                    )
                    stats.update(self.obs.memory.collect())
                    # feed the NEXT boundary's cluster beat (distributed
                    # telemetry) with this step's scalars, and surface the
                    # tracer's drop counter before the snapshot below
                    self.obs.cluster.note_step(
                        step_time,
                        tokens_per_sec=stats.get(
                            "throughput/tokens_per_sec", 0.0
                        ),
                        device_bytes=stats.get(
                            "memory/device_bytes_in_use",
                            stats.get("memory/host_rss_bytes", 0.0),
                        ),
                    )
                    # elastic fleet membership rides the same beat vector
                    # (async_rl.transport: collective; None off-fleet)
                    collector = getattr(self, "_async", None)
                    if collector is not None and hasattr(
                        collector, "fleet_size"
                    ):
                        self.obs.cluster.note_fleet(collector.fleet_size())
                    self.obs.note_dropped_spans()
                    stats.update(self.obs.metrics.snapshot())
                    if self._serve is not None:
                        # per-tenant/per-class SLO percentiles live on the
                        # HTTP /metrics endpoint; the flat SERVE_KEYS
                        # gauges ride the training metric stream
                        stats.update(self._serve.flat_metrics())
                    # windowed health detectors over this step's metric
                    # stream; a trip transition dumps the flight record and
                    # triages the batch that produced it
                    stats.update(
                        self.obs.health.update(stats, step=self.iter_count)
                    )
                    tripped = self.obs.health.just_tripped
                    if tripped is not None:
                        if self._dump_triage(f"health:{tripped}", stats):
                            # this step's registry snapshot is already taken;
                            # surface the counter on the step that dumped
                            stats["health/triage_dumps"] = float(
                                self._triage_dumps
                            )
                        self.obs.dump_flight_record(
                            reason=f"health_trip: {tripped} @ step {self.iter_count}"
                        )
                    # the flight recorder keeps the last N steps' stats for
                    # the crash dump (docs/OBSERVABILITY.md)
                    self.obs.flightrec.record(
                        "step", {"iter": self.iter_count, "stats": stats}
                    )
                    clock.tick(batch_size)
                    stats["time/per_1k_samples"] = clock.get_stat(1000)
                    profile.on_step_end(self.iter_count)
                    self.iter_count += 1

                    if self.iter_count % self.config.train.checkpoint_interval == 0:
                        # retention ring: prune BEFORE saving so the join
                        # inside prune waits on the long-finished previous
                        # save, not the one about to dispatch
                        keep = self.resilience.config.keep_last_n
                        if keep > 0:
                            prune_checkpoints(self.config.train.checkpoint_dir, keep)
                        subfolder = f"checkpoint_{self.iter_count:0{len(str(self.total_steps))}d}"
                        self.save(os.path.join(self.config.train.checkpoint_dir, subfolder))

                    if self.iter_count % self.config.train.eval_interval == 0:
                        results = self.evaluate()
                        stats.update(results)
                        self._report_sweep(stats)
                        if self.config.train.save_best:
                            reward = stats.get(
                                "reward/mean", stats.get("metrics/reward", -float("inf"))
                            )
                            if reward > self.best_reward:
                                self.best_reward = reward
                                best_path = os.path.join(
                                    self.config.train.checkpoint_dir, "best_checkpoint"
                                )
                                logger.info(f"Saving best state so far into {best_path}")
                                self.save(best_path)

                    desc = " | ".join(
                        f"{k}: {significant(v)}"
                        for k, v in stats.items()
                        if k.startswith("losses/")
                    )
                    tbar.set_description(f"[{desc}]")
                    tbar.update()

                    if self.iter_count >= self.total_steps:
                        profile.stop()
                        # the flops analysis runs on a daemon thread; join it
                        # here so even a run too short for it to land mid-loop
                        # still reports a final measured MFU
                        flops = self._ensure_train_step_flops(
                            self._last_batch_sharded, wait=True
                        )
                        if flops and "throughput/mfu" not in stats:
                            stats["throughput/mfu"] = obs_mfu(
                                flops, step_time, self.obs.throughput.peak
                            )
                        self._drop_batch_memo()
                        results = self.evaluate()
                        stats.update(results)
                        stats.update(self.obs.throughput.summary())
                        self.tracker.log(stats, step=self.iter_count)
                        self._report_sweep(stats)
                        subfolder = f"checkpoint_{self.iter_count:0{len(str(self.total_steps))}d}"
                        self.save(os.path.join(self.config.train.checkpoint_dir, subfolder))
                        tbar.close()
                        wait_for_saves()  # async saves must land before exit
                        self._export_observability()
                        # flush/close the tracker (W&B runs must finalize;
                        # JSONL transparently reopens if logged again)
                        self.tracker.finish()
                        return results

                    self.tracker.log(stats, step=self.iter_count)

                if batch_ran:  # fully fast-forwarded batches already had
                    self.post_backward_callback()  # their callback pre-checkpoint
            if epoch_ran:
                self._drop_batch_memo()  # free the batch's HBM before rollouts
                self.post_epoch_callback()
        profile.stop()
        tbar.close()
        wait_for_saves()  # async saves must land before exit
        self._export_observability()
        self.tracker.finish()
        return results

    # ------------------------------------------------------------------
    # checkpointing
    # ------------------------------------------------------------------

    def maybe_resume(self) -> None:
        """Restore the newest interval checkpoint when
        ``train.resume_from_checkpoint`` is set — relaunching a crashed or
        preempted run picks up where it left off (reference: Ray session
        restore ``accelerate_base_trainer.py:452-460``; NeMo
        ``resume_if_exists``).

        Idempotent; ``train()`` invokes it *before* the initial PPO rollout
        collection (rollout behavior-logprobs must come from the restored
        policy, not the fresh one), and ``learn()`` again as a fallback for
        direct-trainer use."""
        if getattr(self, "_resume_done", False):
            return
        self._resume_done = True
        if not getattr(self.config.train, "resume_from_checkpoint", False):
            return
        root = self.config.train.checkpoint_dir
        if not os.path.isdir(root):
            return
        wait_for_saves()  # a same-process save may still be pending its commit
        # Only COMMITTED checkpoints are candidates: a crash mid-save leaves
        # a partial dir that Orbax would die restoring — skip it with a
        # warning and take the newest committed one instead. The scan
        # (numeric step sort, commit test) is the same helper the update
        # guard's rollback uses, so resume and rollback can never disagree
        # about which checkpoint is newest.
        from trlx_tpu.utils.checkpoint import _checkpoint_step_dirs

        candidates = []
        for _step, path in _checkpoint_step_dirs(root):
            if is_committed(path):
                candidates.append(path)
            else:
                logger.warning(
                    f"skipping uncommitted/partial checkpoint {path} "
                    "(crash mid-save?); the newest committed checkpoint wins"
                )
        if not candidates:
            return
        path = candidates[-1]
        logger.info(f"Resuming training state from {path}")
        self.load(path)

    def _extra_checkpoint_state(self) -> Dict[str, Any]:
        """Host-side scalar state to persist beyond the TrainState (trainers
        override; e.g. PPO's KL controller and reward running moments —
        without them a resumed run diverges from an uninterrupted one)."""
        return {}

    def _restore_extra_checkpoint_state(self, extra: Dict[str, Any]) -> None:
        pass

    def _save_emergency_payload(self, directory: str) -> None:
        """Trainer hook: persist host-side data an exact mid-run resume
        needs beyond the TrainState (PPO: the rollout store)."""

    def _restore_emergency_payload(self, directory: str) -> None:
        pass

    @staticmethod
    def _rng_to_list(key) -> list:
        """A PRNG key as a JSON-serializable uint32 list (old-style and
        typed keys both)."""
        try:
            data = jax.random.key_data(key)
        except (TypeError, ValueError):
            data = key
        return np.asarray(jax.device_get(data), np.uint32).tolist()

    def _rng_from_list(self, data: list, template):
        arr = np.asarray(data, np.uint32)
        try:
            if jnp.issubdtype(template.dtype, jax.dtypes.prng_key):
                return jax.random.wrap_key_data(arr)
        except (AttributeError, TypeError):
            pass
        return jnp.asarray(arr)

    def save(
        self, directory: Optional[str] = None, emergency: bool = False, **kwargs
    ) -> None:
        """Checkpoint full training state (params, opt state, step, RNG).

        ``emergency=True`` (preemption path) additionally freezes the
        host-side run position — rollout RNG, eval counter, best reward,
        and the trainer's emergency payload (PPO: the rollout store) — so a
        resumed run continues bit-identically from this step boundary."""
        directory = directory or self.config.train.checkpoint_dir
        extra = {"iter_count": self.iter_count, "best_reward": self.best_reward}
        extra.update(self._extra_checkpoint_state())
        # every checkpoint records the prompt-stream position (one int):
        # interval-checkpoint resumes need the same replay as emergency
        # ones, or the fresh iterator re-draws the epoch's first prompts
        extra["prompt_chunks_drawn"] = self._prompt_chunks_drawn
        if emergency:
            extra["emergency"] = True
            extra["rollout_rng"] = self._rng_to_list(self._rollout_rng)
            extra["nth_evaluation"] = self.nth_evaluation
            if jax.process_index() == 0:
                # host-side payload files have one author; peers read them
                # back from the shared checkpoint dir on resume
                os.makedirs(directory, exist_ok=True)
                self._save_emergency_payload(directory)
        save_state(directory, self.state, extra=extra)

    def load(
        self,
        directory: Optional[str] = None,
        restore_payload: bool = True,
        **kwargs,
    ) -> None:
        directory = directory or self.config.train.checkpoint_dir
        # the one restore seam (docs/RESILIENCE.md "Elastic restore"): a
        # matching topology takes the sharded Orbax fast path unchanged; a
        # checkpoint saved on a DIFFERENT mesh (device or process count)
        # reshards host-side onto the live mesh — resilience.elastic gates
        # it, resilience/reshard_s gauges it
        from trlx_tpu.resilience.elastic import restore_state_elastic

        self.state = restore_state_elastic(
            directory,
            self.state,
            elastic=self.resilience.config.elastic,
            metrics=self.obs.metrics,
        )
        extra = read_extra(directory)
        self.iter_count = int(extra.get("iter_count", 0))
        if "best_reward" in extra:
            self.best_reward = float(extra["best_reward"])
        self._restore_extra_checkpoint_state(extra)
        if restore_payload and extra.get("emergency"):
            # an emergency checkpoint froze the run mid-learn: restore the
            # host-side position so learn() fast-forwards to the boundary
            self._emergency_resume = True
            if "rollout_rng" in extra:
                self._rollout_rng = self._rng_from_list(
                    extra["rollout_rng"], self._rollout_rng
                )
            self.nth_evaluation = int(
                extra.get("nth_evaluation", self.nth_evaluation)
            )
            self._restore_emergency_payload(directory)
        if restore_payload:
            # replay the prompt-stream position: the uninterrupted run has
            # consumed `prompt_chunks_drawn` chunks by this boundary; draw
            # and discard until this run's (fresh) iterator catches up, so
            # the NEXT collection trains on the same prompts in the same
            # shuffle order. Host-only work (collation), no device cost.
            # Applies to interval checkpoints too (any save records the
            # position); rollback passes restore_payload=False — its
            # iterator is live mid-run and must not be advanced.
            target = int(extra.get("prompt_chunks_drawn", 0))
            iterator = getattr(self, "prompt_iterator", None)
            if iterator is not None and target > self._prompt_chunks_drawn:
                logger.info(
                    f"resume: fast-forwarding the prompt stream "
                    f"by {target - self._prompt_chunks_drawn} chunks"
                )
                while self._prompt_chunks_drawn < target:
                    next(iterator)

    def _rollback_to_committed(self) -> None:
        """Update-guard rollback: restore the newest committed checkpoint's
        device + controller state, keep the loop bookkeeping marching
        forward (the poison batch is skipped, not retried)."""
        root = self.config.train.checkpoint_dir
        path = newest_committed_checkpoint(root)
        if path is None:
            # rollback is flag-only on device (no keep-old select), so the
            # poisoned update has already landed — without a committed
            # checkpoint there is nothing sane to continue from
            from trlx_tpu.resilience import NonFiniteUpdateError

            raise NonFiniteUpdateError(
                f"non-finite update with update_guard='rollback' but no "
                f"committed checkpoint exists under {root} to restore — "
                "halting (lower train.checkpoint_interval, or use 'skip')"
            )
        cur_iter, cur_best = self.iter_count, self.best_reward
        self.load(path, restore_payload=False)
        self.iter_count, self.best_reward = cur_iter, cur_best
        self._drop_batch_memo()
        self.obs.flightrec.record(
            "resilience",
            {"event": "rollback", "checkpoint": path, "step": self.iter_count},
        )
        logger.warning(f"rolled back train state to {path}")

    def save_pretrained(self, directory: Optional[str] = None, **kwargs) -> None:
        directory = directory or f"{self.config.train.checkpoint_dir}/hf_model"
        save_pretrained(
            directory,
            self.state.params,
            self.tcfg,
            tokenizer_path=self.config.tokenizer.tokenizer_path,
        )

    def push_to_hub(self, repo_id: str, **kwargs) -> str:
        """Publish the current policy weights to the HF Hub (reference:
        ``modeling_base.py:30`` via ``PushToHubMixin``). Stages a full
        ``save_pretrained`` export locally, then uploads it in one call;
        see ``utils/checkpoint.py::push_to_hub`` for the offline/test
        ``uploader=`` seam."""
        from trlx_tpu.utils.checkpoint import push_to_hub

        kwargs.setdefault("tokenizer_path", self.config.tokenizer.tokenizer_path)
        return push_to_hub(repo_id, self.state.params, self.tcfg, **kwargs)
