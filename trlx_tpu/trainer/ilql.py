"""ILQL trainer: offline RL from reward-labeled samples.

Behavioral parity target: ``AccelerateILQLTrainer`` + module-level
``make_experience`` (``trlx/trainer/accelerate_ilql_trainer.py:30-250``):

- ``make_experience`` tokenizes dialogues, builds per-token action/state
  indices (actions at output-token positions − 1, matching the causal shift),
  normalizes returns across the dataset, and puts the scalar return on the
  final action token;
- the loss runs the backbone once, gathers hidden states at action/state
  positions, applies V/Q/target-Q heads on the *gathered* positions only
  (the reference's ``ILQLHeads.forward`` index-select,
  ``trlx/models/modeling_ilql.py:160-180``), and feeds ``ILQLConfig.loss``;
- target-Q heads Polyak-sync every ``steps_for_target_q_sync`` optimizer
  steps (``:136-138``);
- generation reshapes sampling logits on device to
  ``log π + β·(min target-Q − V)`` with top-k masking, via the
  ``adjust_logits`` hook of the jitted sampler (reference custom ``generate``,
  ``modeling_ilql.py:246-317``).
"""

from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.data.tokenizer import Tokenizer
from trlx_tpu.models.heads import sync_target_q_params
from trlx_tpu.models.ilql import ILQLConfig, batched_index_select
from trlx_tpu.pipeline.offline_pipeline import (
    ILQLRolloutStorage,
    ILQLSeq2SeqRolloutStorage,
    tokenize_dialogue,
)
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.base import TPUBaseTrainer
from trlx_tpu.utils import logging
from trlx_tpu.utils.stats import logprobs_of_labels  # noqa: F401 (parity surface)

logger = logging.get_logger(__name__)


# samples per pipelined tokenization chunk: large enough that the worker's
# per-chunk overhead is noise, small enough that index-building overlaps a
# meaningful fraction of the tokenization tail
_TOKENIZE_CHUNK = 64


def _fold_tokenized(
    samples: List[Union[str, List[str]]],
    tokenizer: Optional[Tokenizer],
    max_length: int,
    pipeline_depth: int,
    fold,
    chunk_size: int = _TOKENIZE_CHUNK,
) -> None:
    """Feed ``fold`` tokenized sample chunks in order.

    With ``pipeline_depth`` > 0 and a tokenizer, chunks tokenize on a
    :class:`~trlx_tpu.pipeline.rollout_pipeline.RolloutPipeline` worker while
    ``fold`` (the per-sample index/reward shaping) drains earlier chunks on
    the calling thread — the offline twin of the PPO generation/reward
    overlap. One worker + ordered drain ⇒ output identical to the serial
    path, element for element."""
    if tokenizer is None:
        fold(list(samples))  # already tokenized
        return
    # `> 0` (not truthiness): any non-positive depth means serial, matching
    # PPO's gate — a -1 "disable" value must not reach RolloutPipeline
    if pipeline_depth > 0 and len(samples) > chunk_size:
        from trlx_tpu.pipeline.rollout_pipeline import RolloutPipeline

        with RolloutPipeline(
            depth=pipeline_depth, finalize=fold, name="ilql_tokenize"
        ) as pipe:
            for start in range(0, len(samples), chunk_size):
                part = samples[start : start + chunk_size]
                pipe.submit(
                    lambda part=part: [
                        tokenize_dialogue(s, tokenizer, max_length) for s in part
                    ]
                )
        return
    fold([tokenize_dialogue(s, tokenizer, max_length) for s in samples])


def _causal_sample_arrays(sample) -> tuple:
    """Per-sample causal index math: (input_ids, actions_ixs, states_ixs,
    dones) — shared by the serial and pipelined paths of
    :func:`make_experience`."""
    length = 0
    input_ids = np.array([t for m in sample for t in m.tokens], dtype=np.int32)
    actions_ixs = []
    for dm in sample:
        if dm.is_output:
            # actions index into the *shifted* sequence: the action chosen
            # at state t is the token emitted at position t+1
            actions_ixs.append(
                np.arange(length - 1, length + len(dm.tokens) - 1, dtype=np.int32)
            )
        length += len(dm.tokens)
    ixs = np.concatenate(actions_ixs) if actions_ixs else np.zeros(0, np.int32)
    states_ixs = np.concatenate([ixs, np.array([length - 1], np.int32)])
    dones = np.array([1] * (len(states_ixs) - 1) + [0], dtype=np.int32)
    return input_ids, ixs, states_ixs, dones


def make_experience(
    samples: List[Union[str, List[str]]],
    rewards: List[float],
    tokenizer: Optional[Tokenizer] = None,
    max_length: int = 2048,
    verbose: bool = True,
    pipeline_depth: int = 0,
) -> ILQLRolloutStorage:
    """Tokenize samples and shape rewards into an :class:`ILQLRolloutStorage`
    (reference ``accelerate_ilql_trainer.py:30-99``). ``pipeline_depth`` > 0
    overlaps chunked tokenization (background worker) with the per-sample
    index building here — the result is identical to the serial path."""
    if verbose:
        logger.info("Collecting rollouts")

    all_input_ids = []
    all_actions_ixs = []
    all_states_ixs = []
    all_dones = []

    def fold(chunk):
        for sample in chunk:
            input_ids, ixs, states_ixs, dones = _causal_sample_arrays(sample)
            all_input_ids.append(input_ids)
            all_actions_ixs.append(ixs)
            all_states_ixs.append(states_ixs)
            all_dones.append(dones)

    _fold_tokenized(samples, tokenizer, max_length, pipeline_depth, fold)

    sample_lengths = np.array(list(map(len, all_input_ids)))
    output_lengths = np.array(list(map(len, all_actions_ixs)))
    prompt_lengths = sample_lengths - output_lengths
    if verbose:
        logger.info(
            "Experience string stats: "
            f"prompt {prompt_lengths.mean():.2f} ∈ [{prompt_lengths.min()}, {prompt_lengths.max()}], "
            f"output {output_lengths.mean():.2f} ∈ [{output_lengths.min()}, {output_lengths.max()}], "
            f"sample {sample_lengths.mean():.2f} ∈ [{sample_lengths.min()}, {sample_lengths.max()}]"
        )

    # dataset-level return normalization; scalar return lands on the final
    # action token (reference ``:83-89``)
    returns = np.asarray(rewards, dtype=np.float64)
    returns = returns - returns.mean()
    std = returns.std()
    if not np.isnan(std) and std > 0:
        returns = returns / (std + np.finfo(returns.dtype).eps)
    token_rewards = [np.zeros(len(ixs), np.float32) for ixs in all_actions_ixs]
    for rs, ret in zip(token_rewards, returns):
        if len(rs):
            rs[-1] = ret

    attention_mask = [np.ones(len(x), np.int32) for x in all_input_ids]
    return ILQLRolloutStorage(
        all_input_ids,
        attention_mask,
        token_rewards,
        all_states_ixs,
        all_actions_ixs,
        all_dones,
    )


def make_experience_seq2seq(
    samples: List[Union[str, List[str]]],
    rewards: List[float],
    tokenizer: Optional[Tokenizer] = None,
    max_length: int = 2048,
    verbose: bool = True,
    pipeline_depth: int = 0,
) -> ILQLSeq2SeqRolloutStorage:
    """Seq2seq variant: the prompt feeds the encoder, the output becomes the
    decoder sequence with actions/states indexed over decoder positions
    (reference ``make_experience_seq2seq``,
    ``accelerate_ilql_trainer.py:175-240``). ``pipeline_depth`` as in
    :func:`make_experience`."""
    if verbose:
        logger.info("Collecting rollouts")

    all_input_ids = []
    all_output_ids = []
    all_actions_ixs = []
    all_states_ixs = []
    all_dones = []

    def fold(chunk):
        for sample in chunk:
            prompt_tokens = [t for m in sample if not m.is_output for t in m.tokens]
            output_tokens = [t for m in sample if m.is_output for t in m.tokens]
            all_input_ids.append(np.asarray(prompt_tokens, np.int32))
            all_output_ids.append(np.asarray(output_tokens, np.int32))
            length = len(output_tokens)
            actions_ixs = np.arange(0, max(length - 1, 0), dtype=np.int32)
            states_ixs = np.concatenate(
                [actions_ixs, np.array([max(length - 1, 0)], np.int32)]
            )
            all_dones.append(np.array([1] * (len(states_ixs) - 1) + [0], np.int32))
            all_actions_ixs.append(actions_ixs)
            all_states_ixs.append(states_ixs)

    _fold_tokenized(samples, tokenizer, max_length, pipeline_depth, fold)

    returns = np.asarray(rewards, dtype=np.float64)
    returns = returns - returns.mean()
    std = returns.std()
    if not np.isnan(std) and std > 0:
        returns = returns / (std + np.finfo(returns.dtype).eps)
    token_rewards = [np.zeros(len(ixs), np.float32) for ixs in all_actions_ixs]
    for rs, ret in zip(token_rewards, returns):
        if len(rs):
            rs[-1] = ret

    attention_mask = [np.ones(len(x), np.int32) for x in all_input_ids]
    return ILQLSeq2SeqRolloutStorage(
        all_input_ids,
        attention_mask,
        all_output_ids,
        token_rewards,
        all_states_ixs,
        all_actions_ixs,
        all_dones,
    )


@register_trainer
class ILQLTrainer(TPUBaseTrainer):
    model_head = "ilql"

    def __init__(self, config: TRLConfig, **kwargs):
        super().__init__(config, **kwargs)
        if not isinstance(config.method, ILQLConfig):
            raise ValueError("config.method must be ILQLConfig")
        self.ilql: ILQLConfig = config.method
        self.store: Optional[ILQLRolloutStorage] = None
        self._sync_fn = jax.jit(
            partial(sync_target_q_params, alpha=self.ilql.alpha)
        )

    def make_experience(
        self, samples, rewards, max_length: int = 2048
    ) -> None:
        # the rollout pipeline knob gates the offline overlap too: chunked
        # tokenization on a background worker, index building in the drain
        depth = int(getattr(self.config.train, "rollout_pipeline_depth", 0) or 0)
        if self.is_seq2seq:
            self.store = make_experience_seq2seq(
                samples, rewards, self.tokenizer, max_length=max_length,
                pipeline_depth=depth,
            )
        else:
            self.store = make_experience(
                samples, rewards, self.tokenizer, max_length=max_length,
                pipeline_depth=depth,
            )

    # ------------------------------------------------------------------
    # loss
    # ------------------------------------------------------------------

    def loss_fn(
        self, params: Any, batch: Dict[str, jax.Array], rng: jax.Array
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        module = self.module

        if self.is_seq2seq:
            # decoder positions carry actions/states (reference seq2seq heads
            # forward, ``modeling_ilql.py:396-427``); logits project at the
            # gathered action positions only, like the causal path below
            backbone_out = module.apply(
                {"params": params},
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                decoder_input_ids=batch["decoder_input_ids"],
                logits_span=(0, 0),
                method=type(module).backbone_forward,
            )
            action_source = batch["decoder_input_ids"]
        else:
            # logits_span=(0,0): only hidden states come back — the CE term
            # needs logits at ACTION positions only, so the vocab projection
            # runs on the gathered [B, A, E] hidden below instead of the
            # full [B, T, V] tensor (the peak-memory item at large vocab)
            backbone_out = module.apply(
                {"params": params},
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                logits_span=(0, 0),
                method=type(module).backbone_forward,
            )
            action_source = batch["input_ids"]
        hidden = backbone_out["hidden_states"]

        # pin the gathered activations to the batch layout: the
        # take_along_axis output otherwise inherits a hidden-sharded spec
        # from the backbone that GSPMD can only reconcile with the heads'
        # batch-sharded expectation by an involuntary full rematerialization
        # (replicate-then-repartition) of every gathered tensor per step
        from trlx_tpu.parallel.mesh import get_global_mesh
        from trlx_tpu.parallel.sharding import batch_spec, constrain_activation

        mesh = get_global_mesh()
        hs_actions = constrain_activation(
            batched_index_select(hidden, batch["actions_ixs"]),
            mesh, *batch_spec(3),
        )
        hs_states = constrain_activation(
            batched_index_select(hidden, batch["states_ixs"]),
            mesh, *batch_spec(3),
        )
        qs, target_qs, vs = module.apply(
            {"params": params},
            hs_actions,
            hs_states,
            method=type(module).heads_on,
        )
        logits = module.apply(
            {"params": params}, hs_actions, method=type(module).project_logits
        )
        # the action token itself = the next token after the action index
        actions = jnp.take_along_axis(
            action_source[:, 1:], batch["actions_ixs"], axis=1
        )
        return self.with_router_aux(
            self.ilql.loss(
                logits=logits,
                qs=qs,
                target_qs=target_qs,
                vs=vs,
                actions=actions,
                rewards=batch["rewards"],
                dones=batch["dones"],
            ),
            backbone_out,
        )

    def prepare_learning(self) -> None:
        self.train_dataloader = self.store.create_loader(
            self.config.train.batch_size, shuffle=True, seed=self.config.train.seed
        )
        self.n_updates_per_batch = 1
        self.total_steps = min(
            self.config.train.total_steps,
            self.config.train.epochs * len(self.train_dataloader),
        )

    def post_backward_callback(self) -> None:
        if self.iter_count % self.ilql.steps_for_target_q_sync == 0:
            self.state = self.state.replace(
                params=self._sync_fn(self.state.params)
            )

    # ------------------------------------------------------------------
    # advantage-reshaped sampling
    # ------------------------------------------------------------------

    def adjust_logits_fn(self, extra_kwargs: Dict[str, Any]) -> Optional[Callable]:
        """On-device: logits ← log π + β(min target-Q − V); the sampler's own
        top-k/temperature filtering then applies to the shaped logits, which
        is order-equivalent to the reference's topk-then-temperature
        (``modeling_ilql.py:280-317`` — top-k selection is invariant under
        positive temperature scaling). ``beta`` resolves per generate call,
        so overrides and eval sweeps take effect."""
        beta = float(extra_kwargs.get("beta", 1.0))

        def adjust(step_out: Dict[str, Any], logits: jax.Array) -> jax.Array:
            target_qs = step_out["target_qs"]
            if isinstance(target_qs, (tuple, list)) and len(target_qs) > 1:
                q = jnp.minimum(target_qs[0], target_qs[1])
            elif isinstance(target_qs, (tuple, list)):
                q = target_qs[0]
            else:
                q = target_qs
            v = step_out["vs"]  # [B, 1]
            adv = q.astype(jnp.float32) - v.astype(jnp.float32)
            pi_beta = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            return pi_beta + beta * adv

        return adjust
