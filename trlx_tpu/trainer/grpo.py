"""GRPO trainer: group sampling, group-relative advantages, no value head.

Beyond the reference (which ships PPO/ILQL/SFT): the PPO trainer's TPU
rollout machinery — jitted KV-cache generation, the score-free scoring
forward overlapping the host reward call, the hydra frozen-reference branch
— is inherited unchanged; what changes is *what* is learned from a rollout:

- each prompt is repeated ``group_size`` times (group-contiguous rows);
- the scalar reward of each sequence is normalized within its group
  (:func:`~trlx_tpu.models.grpo.group_advantages_np`) — no values, no GAE;
- the KL penalty moves from reward shaping into the loss
  (:meth:`~trlx_tpu.models.grpo.GRPOConfig.loss`), so rewards stay pure.
"""

from time import perf_counter
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.data.grpo_types import GRPORLElement
from trlx_tpu.models.grpo import GRPOConfig, group_advantages_np
from trlx_tpu.pipeline import BasePipeline
from trlx_tpu.pipeline.grpo_pipeline import GRPORolloutStorage
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.ppo import PPOTrainer
from trlx_tpu.utils import infinite_loader, logging, to_host
from trlx_tpu.utils.stats import logprobs_of_labels

logger = logging.get_logger(__name__)


@register_trainer
class GRPOTrainer(PPOTrainer):
    model_head = None  # no value function — half the trainable state

    def __init__(self, config: TRLConfig, **kwargs):
        # cheap config validation before the expensive model build
        if config.model.model_arch_type == "seq2seq":
            raise NotImplementedError("GRPO is implemented for causal LMs")
        method = config.method
        if not isinstance(method, GRPOConfig):
            raise ValueError("config.method must be GRPOConfig")
        if method.chunk_size % method.group_size:
            raise ValueError(
                f"chunk_size {method.chunk_size} must be a multiple of "
                f"group_size {method.group_size}"
            )
        from trlx_tpu.models.grpo import BASELINES

        if method.baseline not in BASELINES:
            raise ValueError(
                f"unknown method.baseline '{method.baseline}'; known: {BASELINES}"
            )
        if bool(config.async_rl.enabled) and bool(
            getattr(config.train, "continuous_batching", False)
        ):
            # fail at construction, not on the Nth actor thread after
            # max_actor_restarts respawn cycles
            raise NotImplementedError(
                "async_rl + train.continuous_batching is implemented for the "
                "PPO trainer only: GRPO's group-aware harvest keeps the "
                "single-program CB loop. Drop one of the two."
            )
        if method.baseline == "rloo":
            if method.group_size < 2:
                raise ValueError("baseline=rloo needs group_size >= 2")
            if method.scale_advantage:
                logger.warning(
                    "baseline=rloo ignores scale_advantage (RLOO is unscaled "
                    "by definition) — set method.scale_advantage: false to "
                    "silence this"
                )
        super().__init__(config, **kwargs)
        self.store = GRPORolloutStorage(self.tokenizer.pad_token_id)

    def add_prompt_pipeline(self, pipeline: BasePipeline) -> None:
        # one loader row fans out into group_size rollout rows
        method: GRPOConfig = self.config.method
        loader = pipeline.create_loader(
            max(method.chunk_size // method.group_size, 1),
            shuffle=True,
            seed=self.config.train.seed,
        )
        # same prompt-prefetch seam as PPO (GRPO's make_experience is still
        # serial — prefetch only overlaps collation, not reward scoring);
        # the chunk counter lets an emergency resume replay the stream
        self.prompt_iterator = self._count_prompt_chunks(
            infinite_loader(self._maybe_prefetch_prompts(loader))
        )

    # scoring reuses PPOTrainer._get_score_fn, which adapts to the head-less
    # policy (no value output, branch params bound at the tree root)

    def post_backward_callback(self) -> None:
        # GRPO's KL coefficient (method.beta) is fixed in-loss — no adaptive
        # controller to update (PPO's kl_ctl stays at its init value, unused)
        pass

    def _extra_checkpoint_state(self) -> Dict[str, Any]:
        # PPO's extra state minus the adaptive-KL coefficient (fixed in-loss)
        extra = super()._extra_checkpoint_state()
        extra.pop("kl_ctl_value", None)
        return extra

    # the scoring-forward dispatch (async copies, recompile watchdog) is
    # PPOTrainer._dispatch_score — shared with the chunked PPO device stage
    # and the continuous-batching group flush

    def _grpo_score_batch(
        self,
        prompt_ids: np.ndarray,  # [B, P] left-padded, group-contiguous rows
        prompt_mask: np.ndarray,
        response_tokens: np.ndarray,  # [B, N]
        response_mask: np.ndarray,
        elements: list,
        agg: Dict[str, Any],
        score_out=None,  # pre-dispatched scoring outputs (serial path)
    ) -> None:
        """Score + store one group-contiguous batch — the shared tail of the
        serial chunk loop and the continuous-batching group flush, composed
        from the produce/finalize halves the async actor/learner split also
        uses (produce runs on the actor, finalize on the learner)."""
        chunk = self._grpo_chunk_produce(
            prompt_ids, prompt_mask, response_tokens, response_mask,
            score_out=score_out,
        )
        agg["score_time_sum"] += chunk["score_s"]
        self._grpo_chunk_finalize(chunk, elements, agg)

    def _grpo_chunk_produce(
        self,
        prompt_ids: np.ndarray,
        prompt_mask: np.ndarray,
        response_tokens: np.ndarray,
        response_mask: np.ndarray,
        score_out=None,
        params=None,
    ) -> Dict[str, Any]:
        """Device+host half of one group-contiguous batch: scoring forward
        (policy + hydra ref, async copies), string decode, host reward —
        everything that needs no learner state. Pure w.r.t. its inputs, so
        it can run on an actor thread/process."""
        B, P = prompt_ids.shape
        N = int(response_tokens.shape[1])
        if score_out is None:
            score_out = self._dispatch_score(
                (B, P, N),
                np.concatenate([prompt_ids, response_tokens], axis=1),
                prompt_mask,
                response_tokens,
                response_mask,
                params=params,
            )
        samples, prompts, outputs = self.decode(
            prompt_ids, response_tokens, append_eos_token=True
        )
        score_time = perf_counter()
        scores = np.asarray(
            self.reward_fn(samples=samples, prompts=prompts, outputs=outputs),
            dtype=np.float32,
        )
        score_s = perf_counter() - score_time
        host = to_host(score_out)
        return {
            "prompt_ids": prompt_ids,
            "prompt_mask": prompt_mask,
            "response_tokens": response_tokens,
            "response_mask": response_mask,
            "scores": scores,
            "host": host,
            "score_s": score_s,
        }

    def _grpo_chunk_finalize(
        self, chunk: Dict[str, Any], elements: list, agg: Dict[str, Any]
    ) -> None:
        """Learner-side ordered tail: reward clipping, running moments,
        group-relative advantages, KL logging, element construction."""
        method: GRPOConfig = self.config.method
        G = method.group_size
        prompt_ids = chunk["prompt_ids"]
        prompt_mask = chunk["prompt_mask"]
        response_tokens = chunk["response_tokens"]
        response_mask = chunk["response_mask"]
        scores = chunk["scores"]
        host = chunk["host"]
        B = prompt_ids.shape[0]

        clip = method.cliprange_reward
        if clip:
            scores = np.clip(scores, -clip, clip)
        self.running_moments.update(scores)  # logging only: the group
        # normalization below IS the reward scaling in GRPO
        agg["all_scores"].append(scores)
        advantages = group_advantages_np(
            scores, G, method.scale_advantage, baseline=method.baseline
        )

        # reference KL for logging (the loss recomputes it on device);
        # to_host already landed numpy arrays — no further conversion
        lp, rlp = host["logprobs"], host["ref_logprobs"]
        delta = (rlp - lp) * response_mask
        n_tok = max(response_mask.sum(), 1)
        mean_kl = float(((np.exp(delta) - delta - 1.0) * response_mask).sum() / n_tok)
        agg["kl_sum"] += mean_kl
        agg["kl_batches"] += 1

        behavior = chunk.get("behavior_logprobs")
        if method.iw_correction == "off":
            behavior = None
        for i in range(B):
            n_i = int(response_mask[i].sum())
            if n_i == 0:
                continue
            elements.append(
                GRPORLElement(
                    query_tensor=prompt_ids[i][prompt_mask[i] > 0],
                    response_tensor=response_tokens[i, :n_i],
                    logprobs=lp[i, :n_i],
                    ref_logprobs=rlp[i, :n_i],
                    advantage=float(advantages[i]),
                    behavior_logprobs=(
                        np.asarray(behavior[i, :n_i], np.float32)
                        if behavior is not None
                        else None
                    ),
                )
            )

    def _grpo_collect_serial(
        self, num_rollouts: int, elements: list, agg: Dict[str, Any]
    ) -> None:
        """Chunked reference path: each prompt batch fans out into
        ``group_size`` rows, generates to the slowest row, then scores."""
        method: GRPOConfig = self.config.method
        G = method.group_size
        while len(elements) < num_rollouts:
            batch = next(self.prompt_iterator)
            prompt_ids = np.repeat(np.asarray(batch["input_ids"], np.int32), G, axis=0)
            prompt_mask = np.repeat(
                np.asarray(batch["attention_mask"], np.int32), G, axis=0
            )

            gen_time = perf_counter()
            gen_out = self.generate(prompt_ids, prompt_mask)
            # dispatch the scoring forward on the generation's device arrays
            # FIRST: it needs nothing from the host, so it runs while the
            # generation outputs land and reward_fn scores them
            B, P = prompt_ids.shape
            N = int(gen_out.response_tokens.shape[1])
            score_out = self._dispatch_score(
                (B, P, N),
                gen_out.sequences,
                prompt_mask,
                gen_out.response_tokens,
                gen_out.response_mask,
            )
            host_gen = to_host(
                {
                    "response_tokens": gen_out.response_tokens,
                    "response_mask": gen_out.response_mask,
                }
            )
            response_tokens = host_gen["response_tokens"]
            response_mask = host_gen["response_mask"]
            agg["gen_time_sum"] += perf_counter() - gen_time
            # slot accounting (docs/PERFORMANCE.md): this chunk's decode ran
            # max(n_i) steps over B slots — same mask-derived gauges as
            # PPO's chunked paths, so a serial-vs-CB A/B compares them
            n_per_row = response_mask.sum(axis=1)
            agg["slot_steps"] += int(response_mask.shape[0]) * (
                int(n_per_row.max()) if n_per_row.size else 0
            )
            agg["live_slot_steps"] += int(n_per_row.sum())

            self._grpo_score_batch(
                prompt_ids, prompt_mask, response_tokens, response_mask,
                elements, agg, score_out=score_out,
            )

    def _grpo_collect_continuous(
        self, num_rollouts: int, elements: list, agg: Dict[str, Any]
    ) -> None:
        """Continuous-batching collection with *group-aware* harvest: slots
        refill from the prompt queue as individual rollouts finish; a group
        becomes ready when its last member completes, and ready groups flush
        into group-contiguous score batches in completion order — the chunk
        barrier (every group waiting for the whole chunk's slowest row) is
        gone, while the group-relative advantage math is untouched."""
        from collections import deque

        if num_rollouts <= 0:
            return
        method: GRPOConfig = self.config.method
        G = method.group_size
        gen_config, extra_kwargs = self._resolve_gen_config(eval_mode=False)
        groups_per_batch = max(method.chunk_size // G, 1)
        state: Dict[str, Any] = {
            "engine": None, "supplied": 0, "processed": 0, "next_group": 0,
        }
        partial: Dict[int, list] = {}  # group id → completed members
        ready: deque = deque()  # fully-completed groups, completion order

        def fetch_chunk() -> None:
            batch = next(self.prompt_iterator)
            ids = np.repeat(np.asarray(batch["input_ids"], np.int32), G, axis=0)
            mask = np.repeat(np.asarray(batch["attention_mask"], np.int32), G, axis=0)
            keys = self._cb_chunk_keys(ids.shape[0])
            metas = [
                (state["next_group"] + r // G, r % G) for r in range(ids.shape[0])
            ]
            state["next_group"] += ids.shape[0] // G
            if state["engine"] is None:
                state["engine"] = self._cb_make_engine(
                    gen_config, extra_kwargs, ids.shape[0], ids.shape[1]
                )
            state["engine"].enqueue_prompts(ids, mask, keys, metas=metas)
            state["supplied"] += ids.shape[0]

        def flush(n_groups: int) -> None:
            rows = [
                member
                for _ in range(n_groups)
                for member in sorted(ready.popleft(), key=lambda c: c.meta[1])
            ]
            state["processed"] += len(rows)
            self._grpo_score_batch(
                np.stack([c.prompt_ids for c in rows]).astype(np.int32),
                np.stack([c.prompt_mask for c in rows]).astype(np.int32),
                np.stack([c.tokens for c in rows]).astype(np.int32),
                np.stack([c.mask for c in rows]).astype(np.int32),
                elements,
                agg,
            )

        while True:
            while (
                len(elements) + state["supplied"] - state["processed"] < num_rollouts
            ):
                fetch_chunk()
            engine = state["engine"]
            if not engine.busy:
                if ready:
                    flush(len(ready))
                if len(elements) >= num_rollouts:
                    break
                continue
            for c in engine.step():
                members = partial.setdefault(c.meta[0], [])
                members.append(c)
                if len(members) == G:
                    ready.append(partial.pop(c.meta[0]))
            while len(ready) >= groups_per_batch:
                flush(groups_per_batch)

        agg["gen_time_sum"] += engine.stats.decode_s + engine.stats.refill_s
        agg["engine_stats"] = engine.stats

    def _store_element_cls(self) -> type:
        # emergency-checkpoint payload (PPOTrainer hooks): GRPO elements
        # serialize through the same field-generic code path
        return GRPORLElement

    # -- async actor/learner split (docs/ASYNC_RL.md) -------------------

    def _async_produce_chunk(self, spec, params, version, channel) -> Dict[str, Any]:
        """GRPO actor chunk: the spec's prompt batch fans out into
        ``group_size`` group-contiguous rows, generates serially under the
        adopted params, and produces the score batch. (Async GRPO keeps the
        serial generation path; the CB group-aware harvest stays on the
        single-program loop.)"""
        if bool(getattr(self.config.train, "continuous_batching", False)):
            raise NotImplementedError(
                "async_rl + train.continuous_batching is implemented for the "
                "PPO trainer only: GRPO's group-aware harvest keeps the "
                "single-program CB loop. Drop one of the two."
            )
        G = self.config.method.group_size
        prompt_ids = np.repeat(spec.prompt_ids, G, axis=0)
        prompt_mask = np.repeat(spec.prompt_mask, G, axis=0)
        gen_out = self.generate(prompt_ids, prompt_mask, params=params, rng=spec.rng)
        B, P = prompt_ids.shape
        N = int(gen_out.response_tokens.shape[1])
        score_out = self._dispatch_score(
            (B, P, N),
            gen_out.sequences,
            prompt_mask,
            gen_out.response_tokens,
            gen_out.response_mask,
            params=params,
        )
        host_gen = to_host(
            {
                "response_tokens": gen_out.response_tokens,
                "response_mask": gen_out.response_mask,
                "behavior_logprobs": gen_out.response_logprobs,
            }
        )
        chunk = self._grpo_chunk_produce(
            prompt_ids,
            prompt_mask,
            host_gen["response_tokens"],
            host_gen["response_mask"],
            score_out=score_out,
        )
        chunk["behavior_logprobs"] = np.asarray(
            host_gen["behavior_logprobs"], np.float32
        )
        return chunk

    def _collect_async_grpo(
        self, num_rollouts: int, elements: list, agg: Dict[str, Any]
    ) -> None:
        """Learner-side drain for GRPO: same ordered-finalize contract as
        the PPO collector path, with the GRPO finalize tail."""
        collector = self._ensure_async_collector()
        collector.begin_collection()
        while len(elements) < num_rollouts:
            chunk = collector.next_chunk()
            agg["score_time_sum"] += chunk.payload["score_s"]
            self._grpo_chunk_finalize(chunk.payload, elements, agg)
            mask = chunk.payload["response_mask"]
            n_per_row = mask.sum(axis=1)
            agg["slot_steps"] += int(mask.shape[0]) * (
                int(n_per_row.max()) if n_per_row.size else 0
            )
            agg["live_slot_steps"] += int(n_per_row.sum())
        collector.end_collection()
        agg["async_stats"] = collector.collection_stats()

    def make_experience(self, num_rollouts: int = 1024, iter_count: int = 0) -> None:
        """Collect grouped rollouts with group-relative advantages."""
        if self._consume_skip_initial_experience():
            return
        logger.info("Collecting GRPO rollouts")
        if self.prompt_iterator is None:
            raise RuntimeError("add_prompt_pipeline must be called before make_experience")

        stats: Dict[str, float] = {}
        elements: list = []
        agg: Dict[str, Any] = {
            "kl_sum": 0.0, "kl_batches": 0, "all_scores": [],
            "gen_time_sum": 0.0, "score_time_sum": 0.0,
            "slot_steps": 0, "live_slot_steps": 0,
        }
        exp_time = perf_counter()

        if bool(self.config.async_rl.enabled):
            self._collect_async_grpo(num_rollouts, elements, agg)
        elif bool(getattr(self.config.train, "continuous_batching", False)):
            self._grpo_collect_continuous(num_rollouts, elements, agg)
        else:
            self._grpo_collect_serial(num_rollouts, elements, agg)

        self.mean_kl = agg["kl_sum"] / max(agg["kl_batches"], 1)
        stats["policy/sqrt_ref_kl"] = float(np.sqrt(max(self.mean_kl, 0.0)))
        stats["time/exp_generate"] = agg["gen_time_sum"]
        stats.update(self.last_spec_stats)
        stats["time/exp_score"] = agg["score_time_sum"]
        all_scores = agg["all_scores"]
        pooled = np.concatenate(all_scores) if all_scores else np.zeros((0,), np.float32)
        stats["exp_scores/mean"] = float(pooled.mean()) if pooled.size else 0.0
        stats["exp_scores/std"] = float(pooled.std()) if pooled.size else 0.0
        if "async_stats" in agg:
            stats.update(agg["async_stats"])
        engine_stats = agg.get("engine_stats")
        if engine_stats is not None:
            engine_metrics = engine_stats.metrics()
            stats.update(engine_metrics)
            # EngineStats snapshot into the crash flight recorder (same as
            # the PPO continuous path)
            self.obs.flightrec.record("engine_stats", engine_metrics)
        elif agg["slot_steps"]:
            # mask-derived slot gauges on the serial path (the CB branch
            # reports the engine's exact counters above)
            stats["throughput/slot_utilization"] = (
                agg["live_slot_steps"] / agg["slot_steps"]
            )
            stats["rollout/padded_decode_frac"] = (
                1.0 - agg["live_slot_steps"] / agg["slot_steps"]
            )
        stats["time/exp"] = perf_counter() - exp_time
        self.make_experience_stats = stats
        self.tracker.log(stats, step=iter_count)

        self.store.push(elements[:num_rollouts] if num_rollouts else elements)
        if self.log_rollouts:
            self.store.export_history(location=self.rollout_logging_dir)

    def loss_fn(
        self, params: Any, batch: Dict[str, jax.Array], rng: jax.Array
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """Forward on query‖response then the GRPO clipped objective."""
        method: GRPOConfig = self.config.method
        queries = batch["query_tensors"]
        responses = batch["response_tensors"]
        Q, R = queries.shape[1], responses.shape[1]
        input_ids = jnp.concatenate([queries, responses], axis=1)
        attention_mask = jnp.concatenate(
            [batch["query_mask"], batch["response_mask"]], axis=1
        )
        out = self.module.apply(
            {"params": params}, input_ids, attention_mask=attention_mask,
            logits_span=(Q - 1, Q + R - 1),
        )
        logprobs = logprobs_of_labels(out["logits"], responses)
        return self.with_router_aux(
            method.loss(
                logprobs=logprobs,
                old_logprobs=batch["logprobs"],
                ref_logprobs=batch["ref_logprobs"],
                advantages=batch["advantages"],
                mask=batch["response_mask"],
                behavior_logprobs=batch.get("behavior_logprobs"),
            ),
            out,
        )
