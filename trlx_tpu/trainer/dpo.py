"""DPO trainer: preference pairs → logistic loss on implicit reward margins.

Beyond the reference feature set. Offline like ILQL/SFT — no rollouts, no
reward model; ``trlx.train(samples=[(prompt, chosen, rejected), ...],
config=...)`` with ``train.trainer: DPOTrainer``.

TPU design: the reference completion logprobs are precomputed in ONE jitted
pass over the dataset at ``make_experience`` time (per-length-bucket
compiled programs) using the pre-update parameters directly — experience
creation runs before any optimization step, so no reference snapshot is
ever materialized and the train step holds a single model doing a single
forward on the chosen‖rejected concatenated batch. DPO's usual
reference-model memory cost does not exist here at all.
"""

from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.models.dpo import DPOConfig
from trlx_tpu.pipeline.dpo_pipeline import DPOStore
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.base import TPUBaseTrainer
from trlx_tpu.utils import logging
from trlx_tpu.utils.stats import logprobs_of_labels

logger = logging.get_logger(__name__)


def _completion_logps(module, params, input_ids, attention_mask, out_mask, chunk=0):
    """Summed logprob of completion tokens per row: token t is predicted at
    position t-1; only positions with ``out_mask`` contribute. Also returns
    the raw forward outputs (router aux losses for MoE policies).

    With ``chunk`` > 0 the vocab projection streams in T-chunks through the
    model's ``project_logits`` under ``jax.checkpoint`` — the ``[B, T, V]``
    logits never materialize (DPO holds chosen AND rejected rows per pair,
    doubling the logits footprint relative to SFT at the same batch)."""
    sel = (out_mask[:, 1:] * attention_mask[:, 1:]).astype(jnp.float32)
    labels = input_ids[:, 1:]
    if chunk and hasattr(type(module), "project_logits"):
        from trlx_tpu.ops.chunked import stream_projected_reduce

        out = module.apply(
            {"params": params}, input_ids, attention_mask=attention_mask,
            logits_span=(0, 0),
        )

        def body(carry, logits, l, s):
            lp = logprobs_of_labels(logits.astype(jnp.float32), l)
            return carry + jnp.sum(lp * s, axis=1)

        sums = stream_projected_reduce(
            module,
            params,
            out["hidden_states"][:, :-1],
            [(labels, 0), (sel, 0.0)],
            chunk,
            jnp.zeros((input_ids.shape[0],), jnp.float32),
            body,
        )
        return sums, out
    out = module.apply({"params": params}, input_ids, attention_mask=attention_mask)
    lp = logprobs_of_labels(out["logits"][:, :-1], labels)
    # accumulate in fp32: a bf16 sum of hundreds of logprobs has an ulp of
    # O(1) nats — the same order as real DPO margins
    return jnp.sum(lp.astype(jnp.float32) * sel, axis=1), out


@register_trainer
class DPOTrainer(TPUBaseTrainer):
    model_head = None

    def __init__(self, config: TRLConfig, **kwargs):
        if not isinstance(config.method, DPOConfig):
            raise ValueError("config.method must be DPOConfig")
        if config.model.model_arch_type == "seq2seq":
            raise NotImplementedError("DPO is implemented for causal LMs")
        super().__init__(config, **kwargs)
        self.store: DPOStore = None
        # No reference snapshot is ever materialized: the one-time reference
        # pass in make_experience runs BEFORE any optimization step (train()
        # collects experience first, and resume happens inside learn()), so
        # the current parameters ARE the reference — zero extra param HBM.
        self.ref_params = None

    def _get_ref_logp_fn(self):
        """Memoized jitted reference-logprob program: a fresh
        ``jax.jit(lambda ...)`` per ``make_experience`` call would compile a
        new executable every invocation (the jit cache keys on function
        identity — graftlint GL204); one named program serves every call."""
        if getattr(self, "_ref_logp_fn", None) is None:
            module = self.module
            chunk = self._resolved_logit_chunk()

            def ref_logps(p, ids, attn, out):
                return _completion_logps(module, p, ids, attn, out, chunk)[0]

            self._ref_logp_fn = jax.jit(ref_logps)
        return self._ref_logp_fn

    def make_experience(self, samples: Sequence[Sequence[str]], seq_length: int) -> None:
        """Tokenize preference triples and precompute the frozen-reference
        completion logprobs for every pair."""
        self.store = DPOStore(samples, self.tokenizer, seq_length)
        if self.config.method.reference_free:
            for e in self.store.history:
                e["ref_chosen_logp"] = 0.0
                e["ref_rejected_logp"] = 0.0
            return

        logger.info("Precomputing frozen-reference logprobs for %d pairs", len(self.store))
        from trlx_tpu.parallel import shard_batch

        ref_fn = self._get_ref_logp_fn()
        bs = min(self.config.train.batch_size, len(self.store))
        loader = self.store.create_loader(bs, shuffle=False, drop_last=False)
        idx = 0
        for batch in loader:
            # mesh placement like every other forward path: batch arrays
            # data-sharded, matching the sharded parameters (required on
            # multi-host, where process-local arrays cannot mix with
            # globally-sharded params in one jit)
            arrays = shard_batch(
                {k: batch[k] for k in ("input_ids", "attention_mask", "out_mask")},
                self.mesh,
            )
            logps = np.asarray(
                jax.device_get(
                    ref_fn(
                        # pre-update params ARE the frozen reference here
                        self.state.params,
                        arrays["input_ids"],
                        arrays["attention_mask"],
                        arrays["out_mask"],
                    )
                ),
                np.float32,
            )
            n = logps.shape[0] // 2
            for j in range(n):  # interleaved (c0, r0, c1, r1, ...)
                self.store.history[idx + j]["ref_chosen_logp"] = float(logps[2 * j])
                self.store.history[idx + j]["ref_rejected_logp"] = float(logps[2 * j + 1])
            idx += n
        assert idx == len(self.store)

    def loss_fn(
        self, params: Any, batch: Dict[str, jax.Array], rng: jax.Array
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        logps, out = _completion_logps(
            self.module, params, batch["input_ids"], batch["attention_mask"],
            batch["out_mask"], self._resolved_logit_chunk(),
        )
        refs = batch["ref_logps"]
        # interleaved pair layout: chosen at even rows, rejected at odd
        return self.with_router_aux(
            self.config.method.loss(
                policy_chosen_logps=logps[0::2],
                policy_rejected_logps=logps[1::2],
                ref_chosen_logps=refs[0::2],
                ref_rejected_logps=refs[1::2],
            ),
            out,
        )

    def prepare_learning(self) -> None:
        if len(self.store) < self.config.train.batch_size:
            raise ValueError(
                f"preference dataset has {len(self.store)} pairs but "
                f"train.batch_size={self.config.train.batch_size}; the loader "
                "drops incomplete batches, so training would silently run zero "
                "updates — lower train.batch_size or provide more pairs"
            )
        self.train_dataloader = self.store.create_loader(
            self.config.train.batch_size, shuffle=True, seed=self.config.train.seed
        )
        self.n_updates_per_batch = 1
        self.total_steps = min(
            self.config.train.total_steps,
            self.config.train.epochs * len(self.train_dataloader),
        )
