"""PPO trainer: rollout collection with KL penalty vs a frozen reference,
reward scaling, GAE + clipped-objective optimization.

Behavioral parity target: ``AcceleratePPOTrainer``
(``trlx/trainer/accelerate_ppo_trainer.py:33-489``):

- ``make_experience`` — jitted KV-cache generation, host reward scoring,
  running-moments reward scaling/clipping, a scoring forward for logprobs +
  values, a frozen-reference forward (hydra branch when
  ``num_layers_unfrozen > 0``, else a full frozen copy), per-token KL-penalty
  rewards with the task score on the final token;
- ``loss`` — GAE advantages/returns then the clipped PPO objective
  (``trlx/models/modeling_ppo.py:134-233``);
- KL controller updated post-backward, store refilled post-epoch.

TPU redesign notes: the reference's rank choreography (pad/gather to rank 0,
reward on rank 0, scatter back, ``:292-327``) collapses to device_get →
host reward fn → shard_batch, since arrays are globally sharded. All rollout
math (KL penalty, masked stats) runs on device in one jitted program per
shape bucket.
"""

import os
from contextlib import ExitStack
from time import perf_counter
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.data.ppo_types import PPORLElement
from trlx_tpu.models.builder import hydra_ref_params
from trlx_tpu.models.ppo import PPOConfig, kl_penalty_rewards_np
from trlx_tpu.observability.dynamics import (
    SKETCH_RANGES,
    entropy_of_logits,
    loss_sketches,
    sketch_np,
)
from trlx_tpu.models.transformer import CausalTransformer
from trlx_tpu.ops.pallas_utils import has_pallas_tpu
from trlx_tpu.ops.sampling import GenerationOutput
from trlx_tpu.parallel import shard_batch
from trlx_tpu.pipeline import BasePipeline
from trlx_tpu.pipeline.ppo_pipeline import PPORolloutStorage
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.base import TPUBaseTrainer
from trlx_tpu.utils import infinite_loader, logging, to_host
from trlx_tpu.utils.stats import RunningMoments, logprobs_of_labels

logger = logging.get_logger(__name__)


@register_trainer
class PPOTrainer(TPUBaseTrainer):
    model_head = "value"
    # post_epoch_callback rebuilds the dataloader from the refilled store:
    # the emergency-resume fast-forward must not burn shuffle draws on it
    _fresh_loader_per_epoch = True

    def __init__(self, config: TRLConfig, **kwargs):
        super().__init__(config, **kwargs)
        method: PPOConfig = config.method
        if not isinstance(method, PPOConfig):
            raise ValueError("config.method must be PPOConfig")
        if self.reward_fn is None:
            raise ValueError("PPO requires a reward_fn")

        self.store = PPORolloutStorage(self.tokenizer.pad_token_id)
        self.kl_ctl = method.kl_controller()

        # Frozen reference for the KL penalty. With a partially-unfrozen model
        # the reference branch shares the frozen trunk and only copies the top
        # layers (hydra; reference ``modeling_ppo.py:331-427``); otherwise a
        # full frozen backbone copy (``accelerate_ppo_trainer.py:71-74``).
        # Copies are real (jnp.copy): the train step donates its input state,
        # so the snapshot must own its buffers.
        nlu = config.model.num_layers_unfrozen
        self.num_layers_unfrozen = nlu
        if self.is_seq2seq:
            from trlx_tpu.models.builder import seq2seq_hydra_ref_params
            from trlx_tpu.models.seq2seq import T5Transformer

            if nlu > 0:
                extract = lambda p: seq2seq_hydra_ref_params(p, self.tcfg, nlu)  # noqa: E731
            else:
                extract = lambda p: p["backbone"]  # noqa: E731
            self._ref_module = T5Transformer(self.tcfg)
        else:
            if nlu > 0:
                extract = lambda p: hydra_ref_params(p, self.tcfg, nlu)  # noqa: E731
            else:
                # head wrappers scope the transformer under "backbone";
                # head-less policies (GRPO) are the bare transformer tree
                extract = lambda p: p["backbone"] if "backbone" in p else p  # noqa: E731
            self._ref_module = CausalTransformer(self.tcfg)
        self.ref_params = self._ref_snapshot(extract)

        self.running_moments = RunningMoments()
        self.ref_mean: Optional[float] = method.ref_mean
        self.ref_std: Optional[float] = method.ref_std

        self.prompt_iterator = None
        self.mean_kl = 0.0
        self._score_fns: Dict[Tuple[int, int, int], Any] = {}
        self.make_experience_stats: Dict[str, float] = {}

        # disaggregated async collection (trlx_tpu/async_rl/,
        # docs/ASYNC_RL.md): the collector is built lazily at the first
        # async make_experience; _async_version is the learner's update
        # clock (the weight-channel version)
        self._async = None
        self._async_version = 0

        if config.train.rollout_logging_dir is not None:
            self.log_rollouts = True
            self.setup_rollout_logging(config)
        else:
            self.log_rollouts = False

    def _ref_snapshot(self, extract):
        """Frozen-reference snapshot of (a branch of) the current params.

        Real runs take buffer-owning copies (the train step donates its
        input state, so the snapshot must not alias it); under
        ``abstract_init`` only shapes are produced — the branch extractor's
        slicing traces fine under ``eval_shape`` and an abstract trainer
        never executes."""
        if self.abstract_init:
            return jax.eval_shape(extract, self.state.params)
        return jax.tree_util.tree_map(jnp.copy, extract(self.state.params))

    # ------------------------------------------------------------------
    # rollout collection
    # ------------------------------------------------------------------

    def add_prompt_pipeline(self, pipeline: BasePipeline) -> None:
        loader = pipeline.create_loader(
            self.config.method.chunk_size, shuffle=True, seed=self.config.train.seed
        )
        # prompt collation prefetches on a background thread when the rollout
        # pipeline is on, so chunk dispatch never stalls on next(...); the
        # chunk counter lets an emergency resume replay the stream position
        self.prompt_iterator = self._count_prompt_chunks(
            infinite_loader(self._maybe_prefetch_prompts(loader))
        )

    def _extra_checkpoint_state(self) -> Dict[str, Any]:
        return {
            "kl_ctl_value": float(self.kl_ctl.value),
            # the post-backward KL update reads mean_kl from the last
            # collection; a resumed run must apply the same update
            "mean_kl": float(self.mean_kl),
            "running_moments": {
                "mean": self.running_moments.mean,
                "std": self.running_moments.std,
                "var": self.running_moments.var,
                "count": self.running_moments.count,
            },
        }

    def _restore_extra_checkpoint_state(self, extra: Dict[str, Any]) -> None:
        if "kl_ctl_value" in extra:
            self.kl_ctl.value = float(extra["kl_ctl_value"])
        if "mean_kl" in extra:
            self.mean_kl = float(extra["mean_kl"])
        rm = extra.get("running_moments")
        if rm:
            self.running_moments.mean = rm["mean"]
            self.running_moments.std = rm["std"]
            self.running_moments.var = rm["var"]
            self.running_moments.count = rm["count"]

    # -- emergency-checkpoint payload (docs/RESILIENCE.md) --------------
    #
    # A preemption freezes the run BETWEEN two updates, usually mid-epoch:
    # the store still holds rollouts the remaining updates must train on.
    # The payload serializes them (field-generically — GRPO's element type
    # rides the same code) so the resumed run replays the exact batches an
    # uninterrupted run would, instead of re-collecting with the restored
    # policy and diverging.

    _STORE_PAYLOAD = "rollout_store.npz"

    def _store_element_cls(self) -> type:
        return PPORLElement

    def _save_emergency_payload(self, directory: str) -> None:
        import dataclasses as _dc

        arrays: Dict[str, np.ndarray] = {"count": np.asarray(len(self.store.history))}
        for i, elem in enumerate(self.store.history):
            for f in _dc.fields(elem):
                raw = getattr(elem, f.name)
                if raw is None:  # optional fields (behavior_logprobs) skip
                    continue
                value = np.asarray(raw)
                if value.dtype.kind == "V":
                    # custom float dtypes (bfloat16) round-trip through npz
                    # as raw void bytes; widen to f32 — exact, and collation
                    # casts these fields to f32 for the train batch anyway
                    value = value.astype(np.float32)
                arrays[f"{i}.{f.name}"] = value
        np.savez(os.path.join(directory, self._STORE_PAYLOAD), **arrays)

    def _restore_emergency_payload(self, directory: str) -> None:
        import dataclasses as _dc

        path = os.path.join(directory, self._STORE_PAYLOAD)
        if not os.path.exists(path):
            return
        cls = self._store_element_cls()
        names = [f.name for f in _dc.fields(cls)]
        with np.load(path) as data:
            elements = []
            for i in range(int(data["count"])):
                fields = {}
                for name in names:
                    key = f"{i}.{name}"
                    if key not in data:  # optional field saved as absent
                        continue
                    value = data[key]
                    fields[name] = value.item() if value.ndim == 0 else value
                elements.append(cls(**fields))
        self.store.clear_history()
        self.store.push(elements)
        # the initial trlx.train() collection must be skipped exactly once:
        # the uninterrupted run would be training on THESE rollouts here
        self._skip_initial_experience = True

    def setup_rollout_logging(self, config: TRLConfig) -> None:
        import os

        dir_name = config.train.rollout_logging_dir
        os.makedirs(dir_name, exist_ok=True)
        self.rollout_logging_dir = dir_name

    def _get_score_fn(self, batch_shape: Tuple[int, int, int]):
        """Jitted scoring program for a (B, P, N) shape bucket: one policy
        forward (logits + values + trunk activations) and one frozen-reference
        forward (hydra branch replay or full copy), returning per-token
        logprobs / ref logprobs / values.

        Deliberately score-free: it is dispatched the moment generation
        finishes and its outputs copy to host asynchronously, so the device
        scoring forward + transfer genuinely overlap the host-side string
        decode and ``reward_fn`` (and, with ``rollout_pipeline_depth`` > 0,
        the next chunk's generation); the KL-penalty reward assembly then
        runs on host (:func:`trlx_tpu.models.ppo.kl_penalty_rewards_np`)."""
        if batch_shape in self._score_fns:
            return self._score_fns[batch_shape]

        module = self.module
        ref_module = self._ref_module
        nlu = self.num_layers_unfrozen
        B, P, N = batch_shape

        if self.is_seq2seq:
            start_id = self.tcfg.decoder_start_token_id

            def score_fn(params, ref_params, sequences, prompt_mask, response_tokens,
                         response_mask):
                # encoder side: the prompt; decoder side: teacher-forced
                # responses shifted right behind the start token (reference
                # seq2seq scoring, ``accelerate_ppo_trainer.py:369-398``)
                prompt_ids = sequences[:, :P]
                dec_in = jnp.concatenate(
                    [jnp.full((B, 1), start_id, jnp.int32), response_tokens[:, :-1]],
                    axis=1,
                )
                dec_mask = jnp.concatenate(
                    [jnp.ones((B, 1), jnp.int32), response_mask[:, :-1]], axis=1
                )
                out = module.apply(
                    {"params": params},
                    prompt_ids,
                    attention_mask=prompt_mask,
                    decoder_input_ids=dec_in,
                    decoder_attention_mask=dec_mask,
                    branch_layer=nlu if nlu > 0 else None,
                )
                # decoder position i predicts response token i directly
                logprobs = logprobs_of_labels(out["logits"], response_tokens)
                values = out["value"]

                if nlu > 0:
                    ref_out = module.apply(
                        {"params": {"backbone": ref_params}},
                        out["branch_input"],
                        nlu,
                        out["encoder_hidden"],
                        prompt_mask,
                        dec_mask,
                        method=type(module).forward_branch,
                    )
                else:
                    ref_out = ref_module.apply(
                        {"params": ref_params},
                        prompt_ids,
                        attention_mask=prompt_mask,
                        decoder_input_ids=dec_in,
                        decoder_attention_mask=dec_mask,
                    )
                ref_logprobs = logprobs_of_labels(ref_out["logits"], response_tokens)
                return {
                    "logprobs": logprobs,
                    "values": values,
                    "ref_logprobs": ref_logprobs,
                }

            fn = jax.jit(score_fn)
            self._score_fns[batch_shape] = fn
            return fn

        # head wrappers scope the transformer under "backbone"; head-less
        # policies (GRPO) are the bare transformer, so the hydra branch
        # params bind at the tree root and there is no value output
        has_value = self.model_head == "value"
        wrap_ref = (lambda p: {"backbone": p}) if self.model_head else (lambda p: p)

        def score_fn(params, ref_params, sequences, prompt_mask, response_tokens,
                     response_mask):
            full_mask = jnp.concatenate([prompt_mask, response_mask], axis=1)
            # logits at t predict token t+1: response token i lives at column
            # P+i, so its logprob/value come from position P-1+i; the vocab
            # projection is restricted to exactly that span (logits_span)
            span = (P - 1, P + N - 1)
            out = module.apply(
                {"params": params},
                sequences,
                attention_mask=full_mask,
                branch_layer=nlu if nlu > 0 else None,
                logits_span=span,
            )
            logprobs = logprobs_of_labels(out["logits"], response_tokens)

            if nlu > 0:
                ref_out = module.apply(
                    {"params": wrap_ref(ref_params)},
                    out["branch_input"],
                    nlu,
                    full_mask,
                    None,
                    span,
                    method=type(module).forward_branch,
                )
            else:
                ref_out = ref_module.apply(
                    {"params": ref_params}, sequences, attention_mask=full_mask,
                    logits_span=span,
                )
            ref_logprobs = logprobs_of_labels(ref_out["logits"], response_tokens)
            result = {"logprobs": logprobs, "ref_logprobs": ref_logprobs}
            if has_value:
                result["values"] = out["value"][:, P - 1 : P + N - 1]
            return result

        fn = jax.jit(score_fn)
        self._score_fns[batch_shape] = fn
        return fn

    # The per-chunk rollout work splits into three stages with distinct
    # concurrency homes (docs/PERFORMANCE.md):
    #
    #   device   — main thread: prompt fetch, jitted generation, scoring-
    #              forward dispatch + async device→host copies;
    #   host     — worker thread when train.rollout_pipeline_depth > 0:
    #              string decode, reward_fn, landing the device arrays.
    #              Pure w.r.t. its inputs (no trainer state mutation);
    #   finalize — main thread, strictly in submission order: running-
    #              moments update (the one sequential dependency — reward
    #              scaling must fold chunks in order), KL-penalty assembly,
    #              PPORLElement construction.
    #
    # Within one make_experience call the params never change, so running
    # chunk k+1's generation while chunk k's host work drains is *exactly*
    # equivalent to the serial schedule: the store is bit-identical under a
    # fixed seed (tests/test_rollout_pipeline.py pins this).

    def _dispatch_score(
        self,
        shape: Tuple[int, int, int],  # (B, P, N)
        sequences,  # [B, P+N] device rows (chunked paths) or host rows (CB)
        prompt_mask,
        response_tokens,
        response_mask,
        params=None,  # async actors score under their adopted param copy
    ):
        """Dispatch the scoring forward and start its async device→host
        copies — the single home of the dispatch tail (recompile watchdog,
        async copies) shared by the chunked device stage, the continuous-
        batching group flush, and GRPO. ``shard_batch`` is a no-copy
        ``device_put`` for already-placed device arrays, so feeding the
        generation's outputs straight through costs nothing."""
        score_fn = self._get_score_fn(shape)
        batch = shard_batch(
            {
                "sequences": sequences,
                "prompt_mask": prompt_mask,
                "response_tokens": response_tokens,
                "response_mask": response_mask,
            },
            self.mesh,
        )
        score_out = score_fn(
            self.state.params if params is None else params,
            self.ref_params,
            batch["sequences"],
            batch["prompt_mask"],
            batch["response_tokens"],
            batch["response_mask"],
        )
        self.obs.recompile.observe("score", score_fn)
        # start the device→host copies of the scoring outputs without
        # blocking: by the time the host stage asks for these arrays they
        # have usually landed
        for leaf in jax.tree_util.tree_leaves(score_out):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        return score_out

    def _rollout_chunk_device(self, stats: Dict[str, float]) -> Dict[str, Any]:
        """Main-thread device side of one chunk: prompt fetch, generation,
        and the scoring-forward dispatch with async device→host copies."""
        batch = next(self.prompt_iterator)
        prompt_ids = np.asarray(batch["input_ids"], np.int32)
        prompt_mask = np.asarray(batch["attention_mask"], np.int32)
        return self._chunk_device(prompt_ids, prompt_mask, stats)

    def _chunk_device(
        self,
        prompt_ids: np.ndarray,
        prompt_mask: np.ndarray,
        stats: Dict[str, float],
        params=None,
        rng=None,
    ) -> Dict[str, Any]:
        """Device side of one prompt chunk, prompt batch supplied by the
        caller — shared verbatim between the serial reference path (trainer
        state params/RNG) and the async actor path (channel-published
        params, dispatched per-chunk RNG)."""
        gen_time = perf_counter()
        # generate() opens its own fenced "generate" span, nested under the
        # caller's "rollout" span in the Chrome/Perfetto export
        gen_out = self.generate(prompt_ids, prompt_mask, params=params, rng=rng)
        stats["time/exp_generate"] = perf_counter() - gen_time
        stats["time/generate"] = self.last_generate_time
        stats.update(self.last_spec_stats)

        # dispatch the scoring forward immediately on the generation's
        # device arrays — it needs nothing from the host, so it runs while
        # the host stage decodes strings and calls reward_fn
        B, P = prompt_ids.shape
        N = int(gen_out.response_tokens.shape[1])
        score_out = self._dispatch_score(
            (B, P, N),
            gen_out.sequences,
            prompt_mask,
            gen_out.response_tokens,
            gen_out.response_mask,
            params=params,
        )
        return {
            "prompt_ids": prompt_ids,
            "prompt_mask": prompt_mask,
            "gen_out": gen_out,
            "score_out": score_out,
        }

    def _rollout_chunk_host(self, dev: Dict[str, Any]) -> Dict[str, Any]:
        """Host side of one chunk (pipeline worker when depth > 0): fetch the
        generation outputs, decode strings, run ``reward_fn``, land the
        scoring outputs. The "score" span covers execution → host landing of
        the scoring forward: it deliberately stays open across the
        interleaved decode/reward work, so the recorded time includes the
        overlap window rather than serializing it."""
        host_t0 = perf_counter()
        # named `stats` so scripts/check_metric_names.py lints these keys too
        stats: Dict[str, float] = {}
        with ExitStack() as score_ctx:
            # ExitStack (not a plain `with`) mirrors the historical shape:
            # the span must close even if decode/reward raises mid-overlap
            score_sp = score_ctx.enter_context(self.obs.span("score"))
            # to_host already lands numpy arrays — no further conversion
            host_gen = to_host(
                {
                    "response_tokens": dev["gen_out"].response_tokens,
                    "response_mask": dev["gen_out"].response_mask,
                }
            )
            response_tokens = host_gen["response_tokens"]
            response_mask = host_gen["response_mask"]

            samples, prompts, outputs = self.decode(
                dev["prompt_ids"], response_tokens, append_eos_token=True
            )
            with self.obs.span("reward") as reward_sp:
                scores = np.asarray(
                    self.reward_fn(samples=samples, prompts=prompts, outputs=outputs),
                    dtype=np.float32,
                )
            stats["time/reward"] = reward_sp.duration
            stats["time/exp_score"] = reward_sp.duration
            host = to_host(dev["score_out"])  # usually landed already (async copy)
        stats["time/score"] = score_sp.duration
        return {
            "prompt_ids": dev["prompt_ids"],
            "prompt_mask": dev["prompt_mask"],
            "response_tokens": response_tokens,
            "response_mask": response_mask,
            "scores": scores,
            "host": host,
            "stats": stats,
            "host_s": perf_counter() - host_t0,
        }

    def _rollout_chunk_finalize(
        self,
        chunk: Dict[str, Any],
        elements: list,
        stats: Dict[str, float],
        acc: Dict[str, float],
    ) -> None:
        """Ordered tail of one chunk — the sequential dependencies. Runs on
        the main thread in submission order in BOTH modes, so reward scaling
        (running moments) and the store contents are bit-identical between
        depth 0 and depth ≥ 1."""
        stats.update(chunk["stats"])
        acc["host_s"] += chunk["host_s"]
        scores = chunk["scores"]
        response_mask = chunk["response_mask"]
        response_tokens = chunk["response_tokens"]
        host = chunk["host"]

        # reward scaling/clipping (reference :350-366). Non-finite scores
        # (a flaky reward endpoint, an overflowed RM) are zeroed BEFORE the
        # running moments fold them in — RunningMoments state is cumulative,
        # so one NaN would poison every subsequently scaled reward.
        scores = np.asarray(scores, np.float32)
        nonfinite = ~np.isfinite(scores)
        if nonfinite.any():
            stats["health/nonfinite_scores"] = stats.get(
                "health/nonfinite_scores", 0.0
            ) + float(nonfinite.sum())
            scores = np.where(nonfinite, 0.0, scores)
        scores_mean, scores_std = self.running_moments.update(scores)
        stats["exp_scores/mean"] = float(scores_mean)
        stats["exp_scores/std"] = float(scores_std)
        stats["exp_scores/running_mean"] = float(self.running_moments.mean)
        stats["exp_scores/running_std"] = float(self.running_moments.std)
        if self.config.method.scale_reward == "running":
            scores /= max(self.running_moments.std, 1e-8)
        elif self.config.method.scale_reward == "ref":
            scores /= max(self.ref_std or 1.0, 1e-8)
        clip = self.config.method.cliprange_reward
        if clip:
            scores = np.clip(scores, -clip, clip)

        # KL-penalty reward assembly on host (numpy twin of the device
        # math; [B, N] arrays — microseconds)
        rewards, (mean_kl, mean_kl_per_seq) = kl_penalty_rewards_np(
            host["logprobs"], host["ref_logprobs"], response_mask,
            scores, self.kl_ctl.value,
        )
        # a non-finite chunk KL (one overflowed logprob) must reach neither
        # the adaptive controller's accumulator nor the tracker stream —
        # max(nan, 0.0) is nan, so the old sqrt guard passed NaN through
        if np.isfinite(mean_kl):
            acc["kl_sum"] += mean_kl
            acc["kl_batches"] += 1
            stats["policy/sqrt_kl"] = float(np.sqrt(max(mean_kl, 0.0)))
        else:
            stats["health/nonfinite_kl_chunks"] = stats.get(
                "health/nonfinite_kl_chunks", 0.0
            ) + 1.0
            stats["policy/sqrt_kl"] = 0.0
        acc["gen_tokens"] += int(response_mask.sum())
        acc["chunks"] += 1

        # rollout-side dynamics sketches (observability/dynamics.py): the
        # per-token KL vs the frozen reference only exists host-side here
        # (the train step sees new-vs-old only), and all four collection
        # paths (serial / pipelined / continuous / async) funnel through
        # this finalize — one uniform feed point for the health canary
        fmask = np.asarray(response_mask, np.float32)
        ref_lr = (
            np.asarray(host["logprobs"]) - np.asarray(host["ref_logprobs"])
        ) * fmask
        ref_k3 = (np.exp(ref_lr) - 1.0) - ref_lr
        lo, hi = SKETCH_RANGES["ref_kl"]
        acc["ref_kl_hist"] = acc.get("ref_kl_hist", 0.0) + sketch_np(
            ref_k3, fmask, lo=lo, hi=hi
        )
        # generation-length + repeated-adjacent-token canary (host twin of
        # the engine-harvest counters; engine's exact numbers win via
        # setdefault in make_experience on the continuous path)
        toks = np.asarray(response_tokens)
        pair_mask = fmask[:, 1:] * fmask[:, :-1]
        acc["rep_pairs"] = acc.get("rep_pairs", 0.0) + float(
            ((toks[:, 1:] == toks[:, :-1]) * pair_mask).sum()
        )
        acc["rep_total"] = acc.get("rep_total", 0.0) + float(pair_mask.sum())
        acc.setdefault("gen_lens", []).extend(
            fmask.sum(axis=1).astype(np.int64).tolist()
        )

        # slot accounting (docs/PERFORMANCE.md): a chunk's decode ran
        # max(n_i) steps over B slots (per-sample eos early-exit ends the
        # while_loop at the longest row) — rows past their own eos burned
        # padded slot-steps. The continuous-batching path replaces these
        # numbers with the engine's exact counters.
        n_per_row = response_mask.sum(axis=1)
        acc["slot_steps"] += int(response_mask.shape[0]) * (
            int(n_per_row.max()) if n_per_row.size else 0
        )
        acc["live_slot_steps"] += int(n_per_row.sum())

        prompt_ids, prompt_mask = chunk["prompt_ids"], chunk["prompt_mask"]
        # async chunks ship the sampler's exact behavior logprobs; they ride
        # into elements only when the IW correction will consume them — the
        # default-off path keeps the store's field set (and bytes) identical
        # to the serial reference
        behavior = chunk.get("behavior_logprobs")
        if self.config.method.iw_correction == "off":
            behavior = None
        for i in range(prompt_ids.shape[0]):
            n_i = int(response_mask[i].sum())
            if n_i == 0:
                continue
            query = prompt_ids[i][prompt_mask[i] > 0]
            elements.append(
                PPORLElement(
                    query_tensor=query,
                    # host[...] landed via to_host: already numpy, slices
                    # need no re-asarray
                    response_tensor=response_tokens[i, :n_i],
                    logprobs=host["logprobs"][i, :n_i],
                    values=host["values"][i, :n_i],
                    rewards=rewards[i, :n_i],
                    behavior_logprobs=(
                        np.asarray(behavior[i, :n_i], np.float32)
                        if behavior is not None
                        else None
                    ),
                )
            )

    def _collect_serial(
        self, num_rollouts: int, elements: list, stats: Dict[str, float],
        acc: Dict[str, float],
    ) -> None:
        """Depth-0 reference implementation: each chunk runs device → host →
        finalize strictly in sequence. Kept verbatim as the equivalence
        baseline the pipelined path is tested against."""
        while len(elements) < num_rollouts:
            # the span feeds the trace; the time/rollout *stat* is computed
            # uniformly for both modes in make_experience (wall ÷ chunks)
            with self.obs.span("rollout"):
                dev = self._rollout_chunk_device(stats)
                chunk = self._rollout_chunk_host(dev)
            self._rollout_chunk_finalize(chunk, elements, stats, acc)
        stats["throughput/rollout_overlap_frac"] = 0.0

    def _collect_pipelined(
        self, num_rollouts: int, depth: int, elements: list,
        stats: Dict[str, float], acc: Dict[str, float],
    ) -> None:
        """Software-pipelined collection: the main thread keeps the device
        busy (chunk k+1's generation dispatches as soon as chunk k's lands)
        while up to ``depth`` chunks of host work drain on the pipeline
        worker. Finalization happens on this thread in submission order —
        see the stage map above for why the result is bit-identical."""
        from collections import deque

        from trlx_tpu.pipeline.rollout_pipeline import RolloutPipeline

        # upper-bound row count of each in-flight chunk, submission order
        rows_in_flight: deque = deque()

        def finalize(chunk: Dict[str, Any]) -> None:
            rows_in_flight.popleft()
            self._rollout_chunk_finalize(chunk, elements, stats, acc)

        t0 = perf_counter()
        with RolloutPipeline(
            depth=depth, finalize=finalize, name="rollout", tracer=self.obs.tracer
        ) as pipe:
            while True:
                # submit while even full chunks cannot cover the target; when
                # the in-flight upper bound says "maybe enough", drain and
                # re-check with exact counts (rows with empty responses are
                # dropped at finalize). The set of chunks processed is
                # therefore exactly the serial loop's.
                if len(elements) + sum(rows_in_flight) >= num_rollouts:
                    pipe.drain()
                    if len(elements) >= num_rollouts:
                        break
                    continue
                # the "rollout" span covers the device side only here; the
                # host side shows up as "rollout/overlap" on the worker tid
                with self.obs.span("rollout", pipelined=True) as rollout_sp:
                    dev = self._rollout_chunk_device(stats)
                stats["time/rollout_device"] = rollout_sp.duration
                rows_in_flight.append(int(dev["prompt_ids"].shape[0]))

                def work(dev=dev):
                    # fenced: the span closes only once the scoring outputs
                    # are device-complete, so its duration is host-true
                    with self.obs.span("rollout/overlap") as sp:
                        sp.fence(dev["score_out"])
                        return self._rollout_chunk_host(dev)

                pipe.submit(work)
            pipe_stats = pipe.stats
        stats["throughput/rollout_overlap_frac"] = pipe_stats.overlap_frac(
            perf_counter() - t0
        )

    # ------------------------------------------------------------------
    # continuous batching (train.continuous_batching)
    # ------------------------------------------------------------------

    def _cb_group_device(self, group: list, params=None) -> Dict[str, Any]:
        """Device side of one harvested group: assemble the score batch from
        individually completed sequences and dispatch the scoring forward
        with async device→host copies — the same ``dev`` contract as
        :meth:`_rollout_chunk_device`, so the host/finalize stages are
        shared verbatim with the chunked paths."""
        prompt_ids = np.stack([c.prompt_ids for c in group]).astype(np.int32)
        prompt_mask = np.stack([c.prompt_mask for c in group]).astype(np.int32)
        response_tokens = np.stack([c.tokens for c in group]).astype(np.int32)
        response_mask = np.stack([c.mask for c in group]).astype(np.int32)
        gen_out = GenerationOutput(
            sequences=np.concatenate([prompt_ids, response_tokens], axis=1),
            response_tokens=response_tokens,
            response_mask=response_mask,
            response_logprobs=np.stack([c.logprobs for c in group]),
            response_values=np.stack([c.values for c in group]),
            prompt_mask=prompt_mask,
        )
        B, P = prompt_ids.shape
        N = int(response_tokens.shape[1])
        score_out = self._dispatch_score(
            (B, P, N),
            np.asarray(gen_out.sequences),
            prompt_mask,
            response_tokens,
            response_mask,
            params=params,
        )
        return {
            "prompt_ids": prompt_ids,
            "prompt_mask": prompt_mask,
            "gen_out": gen_out,
            "score_out": score_out,
        }

    def _cb_make_engine(
        self, gen_config, extra_kwargs, rows: int, chunk_width: int,
        tag: Any = None, params: Any = None, version: Any = None,
    ):
        """Build the rollout engine for this trainer — the single home of
        the engine-width invariant (PPO and GRPO must agree): the trainer-
        level prompt budget ``seq_length − max_new_tokens``, bumped to the
        first chunk's collation width if a loader pads wider. Prompt loaders
        pad to the longest row per batch, and the engine's one compiled
        shape must fit every chunk; narrower chunks left-pad
        (attention-masked, so harvested sequences stay bit-identical to
        plain generate at THIS width).

        The KV backend (dense per-slot vs paged block pool) and the prefix
        cache come from the ``engine:`` config section
        (docs/PERFORMANCE.md); outputs are bit-identical across backends,
        so the choice is purely a memory/throughput knob. Engines are
        cached per shape bucket and reused across collections —
        ``begin_collection`` resets the per-collection stats, and flushes
        the prefix cache exactly when the params tree changed (cached KV
        is only valid under the params that computed it)."""
        from trlx_tpu.engine.core import ContinuousEngine

        seg = max(
            1, int(getattr(self.config.train, "continuous_batching_segment", 8) or 8)
        )
        engine_p = max(
            int(self.config.train.seq_length) - gen_config.max_new_tokens,
            chunk_width,
        )
        key = ("cb_engine", gen_config, extra_kwargs, rows, engine_p, seg, tag)
        engine = self._generate_fns.get(key)
        if engine is None:
            fns = self._get_slot_refill_fns(
                gen_config, extra_kwargs, rows, engine_p, seg
            )
            engine = ContinuousEngine(
                fns,
                self._engine_params(params),
                self.tokenizer.pad_token_id,
                span=self.obs.span,
                # per-request lifecycle spans (engine/queue_wait → prefill →
                # decode on per-slot tracks; docs/OBSERVABILITY.md)
                tracer=self.obs.tracer,
                prefix_cache=self._prefix_cache_enabled(),
                prefix_capacity_blocks=int(self.config.engine.prefix_cache_blocks),
                # chunked-prefill scheduling: long prompts admit instantly
                # and prefill one span per step between decode segments
                prefill_chunk=int(self.config.engine.prefill_chunk),
            )
            self._generate_fns[key] = engine
        engine.begin_collection(self._engine_params(params), version=version)
        return engine

    def _cb_chunk_keys(self, rows: int) -> np.ndarray:
        """Per-row RNG chain starts for one prompt chunk: one rng split per
        chunk, then ``fold_in(row)`` — the exact chain plain generate
        derives in per_row_rng mode, so every prompt's sample stream is
        reproducible by the serial sampler."""
        from trlx_tpu.ops.sampling import per_row_keys

        self._rollout_rng, call_rng = jax.random.split(self._rollout_rng)
        return np.asarray(per_row_keys(call_rng, rows))

    def _collect_continuous(
        self, num_rollouts: int, depth: int, elements: list,
        stats: Dict[str, float], acc: Dict[str, float],
    ) -> None:
        """Continuous-batching collection: slot-refill segment decode keeps
        the device batch full while finished sequences stream — harvested
        individually at segment boundaries, grouped into score batches in
        completion order — through the scoring forward and (when
        ``rollout_pipeline_depth`` > 0) the PR-2 host pipeline. Per-sequence
        sampling is bit-identical to plain ``generate`` under per-row RNG;
        the chunk barrier of the serial path is gone, so the store matches
        the serial-with-per-row-RNG store up to sequence order
        (tests/test_continuous_batching.py)."""
        from contextlib import ExitStack

        from trlx_tpu.pipeline.rollout_pipeline import RolloutPipeline

        if num_rollouts <= 0:
            stats["throughput/rollout_overlap_frac"] = 0.0
            return
        gen_config, extra_kwargs = self._resolve_gen_config(eval_mode=False)
        state = {"engine": None, "supplied": 0, "finalized_rows": 0}
        harvest_buf: list = []

        def fetch_chunk() -> None:
            batch = next(self.prompt_iterator)
            ids = np.asarray(batch["input_ids"], np.int32)
            mask = np.asarray(batch["attention_mask"], np.int32)
            keys = self._cb_chunk_keys(ids.shape[0])
            if state["engine"] is None:
                state["engine"] = self._cb_make_engine(
                    gen_config, extra_kwargs, ids.shape[0], ids.shape[1]
                )
            state["engine"].enqueue_prompts(ids, mask, keys)
            state["supplied"] += ids.shape[0]

        def finalize(chunk: Dict[str, Any]) -> None:
            state["finalized_rows"] += int(chunk["prompt_ids"].shape[0])
            self._rollout_chunk_finalize(chunk, elements, stats, acc)

        t0 = perf_counter()
        with ExitStack() as ctx:
            pipe = None
            if depth > 0:
                pipe = ctx.enter_context(
                    RolloutPipeline(
                        depth=depth, finalize=finalize, name="rollout",
                        tracer=self.obs.tracer,
                    )
                )

            def submit_group(group: list) -> None:
                dev = self._cb_group_device(group)
                if pipe is None:
                    finalize(self._rollout_chunk_host(dev))
                    return

                def work(dev=dev):
                    with self.obs.span("rollout/overlap") as sp:
                        sp.fence(dev["score_out"])
                        return self._rollout_chunk_host(dev)

                pipe.submit(work)

            while True:
                # supply so the queue can (expected-case) cover the target;
                # every supplied row yields an element unless its response
                # is empty, in which case the drain below tops up
                while (
                    len(elements) + state["supplied"] - state["finalized_rows"]
                    < num_rollouts
                ):
                    fetch_chunk()
                engine = state["engine"]
                B = engine.B
                if not engine.busy:
                    while harvest_buf:  # flush the (possibly partial) tail
                        group, harvest_buf = harvest_buf[:B], harvest_buf[B:]
                        submit_group(group)
                    if pipe is not None:
                        pipe.drain()
                    if len(elements) >= num_rollouts:
                        break
                    continue
                harvest_buf.extend(engine.step())
                while len(harvest_buf) >= B:
                    group, harvest_buf = harvest_buf[:B], harvest_buf[B:]
                    submit_group(group)
            if pipe is not None:
                stats["throughput/rollout_overlap_frac"] = pipe.stats.overlap_frac(
                    perf_counter() - t0
                )
            else:
                stats["throughput/rollout_overlap_frac"] = 0.0

        engine = state["engine"]
        if engine is not None:
            # exact on-device counters replace the mask-derived estimates
            engine_metrics = engine.stats.metrics()
            stats.update(engine_metrics)
            stats["time/exp_generate"] = engine.stats.decode_s + engine.stats.refill_s
            stats["time/generate"] = engine.stats.decode_s
            # EngineStats snapshot into the crash flight recorder: a run
            # dying mid-collection keeps its last engine picture
            self.obs.flightrec.record("engine_stats", engine_metrics)

    # ------------------------------------------------------------------
    # disaggregated async collection (async_rl.enabled; docs/ASYNC_RL.md)
    # ------------------------------------------------------------------
    #
    # The actor/learner split: N actors (threads here, or run_actor
    # processes) produce experience chunks continuously — gated by the
    # weight channel's staleness bound — while the learner drains chunks in
    # index order and trains. The learner publishes params after every
    # update (in-flight weight sync), so collection k+1 is generated under
    # params at most max_staleness updates behind its consumption.

    def _async_chunks_per_collection(self) -> int:
        from trlx_tpu.async_rl.actor import chunks_per_collection

        return chunks_per_collection(self.config)

    def _async_queue_capacity(self) -> int:
        cap = int(self.config.async_rl.queue_capacity)
        return cap if cap > 0 else 2 * self._async_chunks_per_collection()

    def _async_updates_per_phase(self) -> int:
        """Optimizer updates between two collections: one learn-loop epoch
        (the gate target the learner announces at drain end)."""
        method = self.config.method
        batches = max(1, int(method.num_rollouts) // int(self.config.train.batch_size))
        return int(method.ppo_epochs) * batches

    def _ensure_async_collector(self):
        if self._async is not None:
            return self._async
        import os as _os

        from trlx_tpu.async_rl.channel import FileWeightChannel, WeightChannel
        from trlx_tpu.async_rl.queue import ExperienceQueue, FileExperienceQueue
        from trlx_tpu.async_rl.runtime import AsyncCollector

        acfg = self.config.async_rl
        capacity = self._async_queue_capacity()
        coordinator = None
        member_factory = None
        if acfg.transport not in ("file", "collective"):
            raise ValueError(
                f"unknown async_rl.transport '{acfg.transport}' "
                "(file | collective)"
            )
        if acfg.transport == "collective":
            # the fleet fabric (async_rl/transport.py): param-dissemination
            # tree + in-fabric chunk commits + elastic membership. The file
            # transports below remain the degraded/fallback mode.
            if acfg.queue_policy == "drop_oldest":
                raise ValueError(
                    "async_rl.transport: collective back-pressures through "
                    "the fleet production window; queue_policy: drop_oldest "
                    "is a file-transport knob"
                )
            from trlx_tpu.async_rl.transport import (
                CollectiveExperienceQueue,
                CollectiveWeightChannel,
                FleetCoordinator,
                make_member_factory,
                write_endpoint,
            )

            coordinator = FleetCoordinator(
                fanout=acfg.fanout,
                bind_host=acfg.bind_host,
                capacity=capacity,
                plan=self.resilience.plan,
                metrics=self.obs.metrics,
                sync_every=acfg.sync_every,
                actor_timeout_s=acfg.actor_timeout_s,
            )
            queue = CollectiveExperienceQueue(coordinator)
            channel = CollectiveWeightChannel(coordinator)
            if acfg.mode == "process":
                if not acfg.root_dir:
                    raise ValueError(
                        "async_rl.mode: process requires async_rl.root_dir "
                        "(endpoint discovery for the run_actor processes)"
                    )
                write_endpoint(
                    acfg.root_dir, coordinator.address, coordinator.authkey
                )
                spawn = False  # actors are external run_actor processes
            elif acfg.mode == "thread":
                # each actor thread joins the fleet as its own member over
                # loopback — the same wire protocol as a pod's processes
                member_factory = make_member_factory(
                    coordinator, lambda: self.state.params
                )
                spawn = True
            else:
                raise ValueError(
                    f"unknown async_rl.mode '{acfg.mode}' (thread | process)"
                )
        elif acfg.mode == "process":
            if not acfg.root_dir:
                raise ValueError(
                    "async_rl.mode: process requires async_rl.root_dir (a "
                    "directory shared with the run_actor processes)"
                )
            queue = FileExperienceQueue(
                _os.path.join(acfg.root_dir, "spool"),
                capacity=capacity,
                poll_interval_s=acfg.poll_interval_s,
                metrics=self.obs.metrics,
            )
            channel = FileWeightChannel(
                _os.path.join(acfg.root_dir, "weights"),
                plan=self.resilience.plan,
                metrics=self.obs.metrics,
                sync_every=acfg.sync_every,
                poll_interval_s=acfg.poll_interval_s,
                fetch_timeout_s=acfg.fetch_timeout_s,
            )
            spawn = False  # actors are external run_actor processes
        elif acfg.mode == "thread":
            queue = ExperienceQueue(
                capacity,
                policy=acfg.queue_policy,
                metrics=self.obs.metrics,
                # late-bound through self._async: evicted chunks regenerate
                on_drop=(
                    self._async_on_drop
                    if acfg.queue_policy == "drop_oldest" else None
                ),
            )
            channel = WeightChannel(
                plan=self.resilience.plan,
                metrics=self.obs.metrics,
                sync_every=acfg.sync_every,
            )
            spawn = True
        else:
            raise ValueError(
                f"unknown async_rl.mode '{acfg.mode}' (thread | process)"
            )
        self._async = AsyncCollector(
            trainer=self,
            queue=queue,
            channel=channel,
            num_actors=acfg.num_actors,
            max_staleness=acfg.max_staleness,
            updates_per_phase=self._async_updates_per_phase(),
            chunks_per_collection=self._async_chunks_per_collection(),
            spawn_actors=spawn,
            chunk_timeout_s=acfg.actor_timeout_s,
            max_actor_restarts=acfg.max_actor_restarts,
            metrics=self.obs.metrics,
            tracer=self.obs.tracer,
            span=self.obs.span,
            member_factory=member_factory,
            transport=coordinator,
        )
        self._async.version = self._async_version
        return self._async

    def _async_on_drop(self, chunk) -> None:
        """drop_oldest eviction callback: hand the evicted chunk back to the
        collector for regeneration under fresher params."""
        if self._async is not None:
            self._async.requeue_dropped(chunk)

    def _async_produce_chunk(self, spec, params, version, channel) -> Dict[str, Any]:
        """One actor chunk, device + host halves, under the actor's adopted
        ``params`` (a channel copy — NEVER ``state.params``, whose buffers
        the donated train step invalidates). Serial generation by default;
        with ``train.continuous_batching`` the chunk decodes on the
        slot-refill engine with PipelineRL-style in-flight weight swaps at
        segment boundaries. The payload always carries the sampler's exact
        behavior logprobs — under in-flight swaps they are the only honest
        record of the (mixed-version) behavior policy."""
        stats: Dict[str, float] = {}
        if bool(getattr(self.config.train, "continuous_batching", False)):
            dev = self._async_produce_cb(spec, params, version, channel, stats)
        else:
            dev = self._chunk_device(
                spec.prompt_ids, spec.prompt_mask, stats, params=params,
                rng=spec.rng,
            )
        chunk = self._rollout_chunk_host(dev)
        chunk["stats"].update(stats)
        chunk["behavior_logprobs"] = np.asarray(
            dev["gen_out"].response_logprobs, np.float32
        )
        return chunk

    def _async_produce_cb(
        self, spec, params, version, channel, stats: Dict[str, float]
    ) -> Dict[str, Any]:
        """Continuous-batching actor chunk: slot-refill segment decode over
        the chunk's prompts with per-row RNG, adopting newly published
        params at every segment boundary (``ContinuousEngine.swap_params``'s
        memoized version counter makes the per-segment check one int
        compare; a real change flushes the prefix cache so stale shared KV
        is never reused). Live rows keep decoding across a swap — their
        recorded logprobs remain the exact behavior distribution."""
        import threading as _threading

        from trlx_tpu.ops.sampling import per_row_keys

        gen_config, extra_kwargs = self._resolve_gen_config(eval_mode=False)
        ids, mask = spec.prompt_ids, spec.prompt_mask
        engine = self._cb_make_engine(
            gen_config, extra_kwargs, ids.shape[0], ids.shape[1],
            tag=("async", _threading.get_ident()),
            params=params, version=version,
        )
        keys = np.asarray(per_row_keys(spec.rng, ids.shape[0]))
        engine.enqueue_prompts(ids, mask, keys)
        completed = []
        while engine.busy:
            completed.extend(engine.step())
            if channel is not None and engine.busy:
                fresh, fresh_version = channel.fetch(template=self.state.params)
                # spec engines swap the (target, draft) tuple atomically
                engine.swap_params(self._engine_params(fresh), fresh_version)
        completed.sort(key=lambda c: c.index)
        stats["time/exp_generate"] = engine.stats.decode_s + engine.stats.refill_s
        stats["time/generate"] = engine.stats.decode_s
        gen_params = engine.params
        if int(self.config.engine.speculative):
            gen_params = gen_params[0]  # scoring runs under the target
        return self._cb_group_device(completed, params=gen_params)

    def _collect_async(
        self, num_rollouts: int, elements: list, stats: Dict[str, float],
        acc: Dict[str, float],
    ) -> None:
        """Learner-side drain: consume actor chunks in strict index order
        (running moments fold exactly as the serial path's) and finalize on
        this thread. ``begin_collection`` force-publishes the params this
        collection is consumed under; ``end_collection`` announces the
        upcoming phase's end version — the staleness gate for the chunks
        feeding the NEXT collection."""
        collector = self._ensure_async_collector()
        collector.begin_collection()
        while len(elements) < num_rollouts:
            chunk = collector.next_chunk()
            self._rollout_chunk_finalize(chunk.payload, elements, stats, acc)
        collector.end_collection()
        stats.update(collector.collection_stats())

    def train_step(self, batch):
        stats = super().train_step(batch)
        if self._async is not None:
            # the learner's update clock IS the weight-channel version:
            # publish after every optimizer update (in-flight sync; thinned
            # by async_rl.sync_every inside the channel)
            self._async_version += 1
            self._async.on_update(self.state.params, self._async_version)
        return stats

    def _shutdown_collectors(self) -> None:
        # actors first (they draw from the prompt iterator), then the
        # base closes the iterator chain and joins the prefetch worker
        if self._async is not None:
            try:
                self._async.close()
            except Exception:  # pragma: no cover - defensive
                pass
        super()._shutdown_collectors()

    def _consume_skip_initial_experience(self) -> bool:
        """True exactly once after an emergency-payload restore: the store
        already holds the rollouts this collection would replace."""
        if getattr(self, "_skip_initial_experience", False):
            self._skip_initial_experience = False
            logger.info(
                "emergency resume: rollout store restored from the checkpoint; "
                "skipping the initial collection"
            )
            return True
        return False

    def make_experience(self, num_rollouts: int = 1024, iter_count: int = 0) -> None:
        """Collect ``num_rollouts`` experiences into the store (reference
        ``accelerate_ppo_trainer.py:251-489``), overlapping device generation
        with host reward scoring when ``train.rollout_pipeline_depth`` > 0."""
        if self._consume_skip_initial_experience():
            return
        logger.info("Collecting rollouts")
        if self.prompt_iterator is None:
            raise RuntimeError("add_prompt_pipeline must be called before make_experience")

        depth = int(getattr(self.config.train, "rollout_pipeline_depth", 0) or 0)
        continuous = bool(getattr(self.config.train, "continuous_batching", False))
        stats: Dict[str, float] = {}
        elements: list = []
        acc: Dict[str, float] = {
            "kl_sum": 0.0, "kl_batches": 0, "host_s": 0.0,
            "gen_tokens": 0, "chunks": 0,
            "slot_steps": 0, "live_slot_steps": 0,
        }
        exp_time = perf_counter()

        if bool(self.config.async_rl.enabled):
            # the actor/learner split (docs/ASYNC_RL.md): actors generate —
            # continuously, across collections — and this thread only drains
            # and finalizes. rollout_pipeline_depth is moot here (host work
            # already runs on actor threads/processes); continuous_batching
            # selects the actors' engine path.
            self._collect_async(num_rollouts, elements, stats, acc)
        elif continuous:
            self._collect_continuous(num_rollouts, depth, elements, stats, acc)
        elif depth > 0:
            self._collect_pipelined(num_rollouts, depth, elements, stats, acc)
        else:
            self._collect_serial(num_rollouts, elements, stats, acc)

        self.mean_kl = acc["kl_sum"] / max(acc["kl_batches"], 1)
        stats["kl_ctl_value"] = self.kl_ctl.value
        stats["time/rollout_host"] = acc["host_s"]
        total = perf_counter() - exp_time
        stats["time/exp"] = total
        # whole-collection aggregates with identical definitions in BOTH
        # modes (wall per chunk; generated tokens ÷ collection wall time) —
        # the benchmark suite's A/B report then measures real speedup, never
        # a per-mode metric redefinition
        stats["time/rollout"] = total / max(acc["chunks"], 1)
        if total > 0 and acc["gen_tokens"]:
            stats["throughput/rollout_tokens_per_sec"] = acc["gen_tokens"] / total
        # slot accounting, uniform across modes (continuous batching already
        # set these from the engine's exact counters; the chunked paths
        # derive them from response masks — see docs/PERFORMANCE.md)
        if acc["slot_steps"]:
            stats.setdefault(
                "throughput/slot_utilization",
                acc["live_slot_steps"] / acc["slot_steps"],
            )
            stats.setdefault(
                "rollout/padded_decode_frac",
                1.0 - acc["live_slot_steps"] / acc["slot_steps"],
            )
        # rollout-side dynamics summaries + health canary (accumulated per
        # chunk in _rollout_chunk_finalize; setdefault keeps the engine's
        # exact counters when continuous batching already merged them)
        ref_hist = acc.get("ref_kl_hist")
        if ref_hist is not None:
            stats.update(
                self.obs.dynamics.summarize({"dist/ref_kl_hist": ref_hist})
            )
        gen_lens = acc.get("gen_lens")
        if gen_lens:
            stats.setdefault(
                "rollout/gen_len_p50", float(np.percentile(gen_lens, 50))
            )
            stats.setdefault(
                "rollout/gen_len_p95", float(np.percentile(gen_lens, 95))
            )
        if acc.get("rep_total"):
            stats.setdefault(
                "rollout/repetition_frac", acc["rep_pairs"] / acc["rep_total"]
            )
        self.obs.health.observe_rollout(stats)
        self.make_experience_stats = stats
        self.tracker.log(stats, step=iter_count)

        self.store.push(elements[:num_rollouts] if num_rollouts else elements)
        if self.log_rollouts:
            self.store.export_history(location=self.rollout_logging_dir)

    # ------------------------------------------------------------------
    # optimization
    # ------------------------------------------------------------------

    def loss_fn(
        self, params: Any, batch: Dict[str, jax.Array], rng: jax.Array
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """GAE + clipped PPO objective on a rollout minibatch (reference
        ``accelerate_ppo_trainer.py:136-207``)."""
        method: PPOConfig = self.config.method
        queries = batch["query_tensors"]
        responses = batch["response_tensors"]
        query_mask = batch["query_mask"]
        response_mask = batch["response_mask"].astype(jnp.float32)
        Q = queries.shape[1]
        R = responses.shape[1]

        old_logprobs = batch["logprobs"]
        old_values = batch["values"]
        rewards = batch["rewards"]

        # method.loss_kernel: pallas routes through the fused learner kernel
        # (ops/fused_loss.py): GAE + whitening + clipped loss in ONE program,
        # so get_advantages_and_returns moves inside the kernel and the
        # trainer hands it raw rewards instead of precomputed targets. The
        # XLA path below stays the bit-parity reference.
        use_fused = getattr(method, "loss_kernel", "xla") == "pallas"
        if not use_fused:
            advantages, returns = method.get_advantages_and_returns(
                old_values, rewards, response_mask
            )

        def method_loss(logprobs, values_pred):
            if use_fused:
                loss, stats = method.loss_fused(
                    logprobs=logprobs,
                    values=values_pred,
                    old_logprobs=old_logprobs,
                    old_values=old_values,
                    rewards=rewards,
                    mask=response_mask,
                    behavior_logprobs=batch.get("behavior_logprobs"),
                )
                # observability: 1.0 only when the Mosaic (pallas TPU)
                # backend is importable — a Mosaic-less build's staged
                # fallback reports 0, so an artifact can't claim a kernel
                # it never ran
                stats["train/loss_kernel_pallas"] = jnp.asarray(
                    float(has_pallas_tpu()), jnp.float32
                )
                return loss, stats
            return method.loss(
                logprobs=logprobs,
                values=values_pred,
                old_logprobs=old_logprobs,
                old_values=old_values,
                advantages=advantages,
                returns=returns,
                mask=response_mask,
                behavior_logprobs=batch.get("behavior_logprobs"),
            )

        if self.is_seq2seq:
            B = queries.shape[0]
            start_id = self.tcfg.decoder_start_token_id
            dec_in = jnp.concatenate(
                [jnp.full((B, 1), start_id, jnp.int32), responses[:, :-1]], axis=1
            )
            dec_mask = jnp.concatenate(
                [jnp.ones((B, 1), jnp.int32), batch["response_mask"][:, :-1]], axis=1
            )
            out = self.module.apply(
                {"params": params},
                queries,
                attention_mask=query_mask,
                decoder_input_ids=dec_in,
                decoder_attention_mask=dec_mask,
            )
            logprobs = logprobs_of_labels(out["logits"], responses)
            values_pred = out["value"]
            loss, stats = method_loss(logprobs, values_pred)
            if method.dist_sketches:
                # entropy needs the full logits the method's loss never
                # sees — sketch it here while [B, R, V] is still live
                stats.update(
                    loss_sketches(
                        {"entropy": (entropy_of_logits(out["logits"]), response_mask)}
                    )
                )
            return self.with_router_aux((loss, stats), out)

        input_ids = jnp.concatenate([queries, responses], axis=1)
        attention_mask = jnp.concatenate(
            [query_mask, batch["response_mask"]], axis=1
        )
        out = self.module.apply(
            {"params": params}, input_ids, attention_mask=attention_mask,
            logits_span=(Q - 1, Q + R - 1),
        )
        logprobs = logprobs_of_labels(out["logits"], responses)
        values_pred = out["value"][:, Q - 1 : Q + R - 1]

        loss, stats = method_loss(logprobs, values_pred)
        if method.dist_sketches:
            # entropy needs the full logits the method's loss never sees —
            # sketch it here while the [B, R, V] span is still live
            stats.update(
                loss_sketches(
                    {"entropy": (entropy_of_logits(out["logits"]), response_mask)}
                )
            )
        return self.with_router_aux((loss, stats), out)

    def prepare_learning(self) -> None:
        self.train_dataloader = self.store.create_loader(
            self.config.train.batch_size, shuffle=True, seed=self.config.train.seed
        )
        self.n_updates_per_batch = self.config.method.ppo_epochs
        self.total_steps = min(
            self.config.train.total_steps,
            self.config.train.epochs
            * self.n_updates_per_batch
            * len(self.train_dataloader),
        )

    def _triage_extra(self, arrays: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Derived per-token quantities for a triaged batch: GAE advantages/
        returns, plus the new-policy per-token logprob deltas from one
        un-jitted forward under the current params (best-effort — a sick
        enough state can fail the forward, and the tokens/masks already
        dumped are the irreplaceable part)."""
        extra: Dict[str, np.ndarray] = {}
        values = arrays.get("values")
        rewards = arrays.get("rewards")
        mask = arrays.get("response_mask")
        try:
            if values is not None and rewards is not None and mask is not None:
                adv, ret = self.config.method.get_advantages_and_returns(
                    jnp.asarray(values),
                    jnp.asarray(rewards),
                    jnp.asarray(mask, jnp.float32),
                )
                extra["advantages"] = np.asarray(adv)
                extra["returns"] = np.asarray(ret)
        except Exception:  # pragma: no cover - defensive, crash-path code
            pass
        needed = (
            "query_tensors", "response_tensors", "query_mask",
            "response_mask", "logprobs",
        )
        try:
            if not self.is_seq2seq and all(k in arrays for k in needed):
                queries = jnp.asarray(arrays["query_tensors"])
                responses = jnp.asarray(arrays["response_tensors"])
                Q, R = queries.shape[1], responses.shape[1]
                out = self.module.apply(
                    {"params": self.state.params},
                    jnp.concatenate([queries, responses], axis=1),
                    attention_mask=jnp.concatenate(
                        [
                            jnp.asarray(arrays["query_mask"]),
                            jnp.asarray(arrays["response_mask"]),
                        ],
                        axis=1,
                    ),
                    logits_span=(Q - 1, Q + R - 1),
                )
                new_logprobs = logprobs_of_labels(out["logits"], responses)
                extra["logprob_deltas"] = np.asarray(new_logprobs) - np.asarray(
                    arrays["logprobs"]
                )
        except Exception:  # pragma: no cover - defensive, crash-path code
            pass
        return extra

    def post_backward_callback(self) -> None:
        # adaptive KL coefficient folds into the next compiled rollout as a
        # scalar argument (reference ``accelerate_ppo_trainer.py:233-234``)
        self.kl_ctl.update(self.mean_kl, n_steps=self.config.train.batch_size)
        skips = getattr(self.kl_ctl, "skipped", 0)
        if skips:
            # non-finite chunk KLs the controller refused to fold in
            # (models/ppo.py AdaptiveKLController.update)
            self.obs.metrics.set_gauge("health/kl_ctl_skips", float(skips))

    def post_epoch_callback(self) -> None:
        # fresh rollouts with the updated policy (reference ``:222-231``)
        self.store.clear_history()
        self.make_experience(self.config.method.num_rollouts, self.iter_count)
        self.train_dataloader = self.store.create_loader(
            self.config.train.batch_size, shuffle=True, seed=self.config.train.seed
        )
