"""Trainer registry + abstract trainer contract.

Reference: ``trlx/trainer/__init__.py:9-103``. The registry keys are this
framework's trainer names (``PPOTrainer``/``ILQLTrainer``/``SFTTrainer``); the
reference's ``Accelerate*``/``NeMo*`` names are accepted as aliases so
existing trlx configs load unchanged.
"""

import sys
from abc import abstractmethod
from typing import Any, Callable, Dict, Iterable, List, Optional

from trlx_tpu.data.configs import TRLConfig

_TRAINERS: Dict[str, type] = {}

# reference trainer names → this framework's equivalents
_TRAINER_ALIASES = {
    "accelerateppotrainer": "ppotrainer",
    "accelerateilqltrainer": "ilqltrainer",
    "acceleratesfttrainer": "sfttrainer",
    "nemoilqltrainer": "ilqltrainer",
    "nemosfttrainer": "sfttrainer",
    "nemoppotrainer": "ppotrainer",
}


def register_trainer(name: Any = None) -> Callable:
    """Decorator registering a trainer class by name."""

    def register_cls(cls, registered_name: str):
        _TRAINERS[registered_name.lower()] = cls
        setattr(sys.modules[__name__], registered_name, cls)
        return cls

    if isinstance(name, type):
        return register_cls(name, name.__name__)

    def wrap(cls):
        return register_cls(cls, name if isinstance(name, str) else cls.__name__)

    return wrap


def get_trainer(name: str) -> type:
    resolved = _TRAINER_ALIASES.get(name.lower(), name.lower())
    if resolved in _TRAINERS:
        return _TRAINERS[resolved]
    raise ValueError(f"Unknown trainer '{name}'. Registered: {sorted(_TRAINERS)}")


class BaseRLTrainer:
    """Abstract trainer contract (reference ``BaseRLTrainer``,
    ``trlx/trainer/__init__.py:34-103``)."""

    def __init__(
        self,
        config: TRLConfig,
        reward_fn: Optional[Callable] = None,
        metric_fn: Optional[Callable] = None,
        stop_sequences: Optional[List[str]] = None,
        logit_mask=None,
        **kwargs,
    ):
        self.config = config
        self.reward_fn = reward_fn
        self.metric_fn = metric_fn
        self.stop_sequences = stop_sequences or []
        # [V, V] bool: logit_mask[last_token, next_token] = allowed — applied
        # to sampling logits during generation (reference contract:
        # ``trlx/trainer/__init__.py:41-50``, consumed by ILQL generate
        # ``modeling_ilql.py:297-298``; here it applies to every trainer's
        # decode loop). Pass via ``train.trainer_kwargs`` or the constructor.
        self.logit_mask = logit_mask

    @abstractmethod
    def learn(self):
        """Train the model and yield final stats."""
        ...

    @abstractmethod
    def save(self, directory: Optional[str] = None, **kwargs):
        """Checkpoint full training state (params, opt state, step)."""
        ...

    @abstractmethod
    def load(self, directory: Optional[str] = None, **kwargs):
        """Restore training state from a checkpoint."""
        ...

    def save_pretrained(self, directory: Optional[str] = None, **kwargs):
        """Export model weights in an interoperable (HF-style) layout."""
        raise NotImplementedError
