"""Supervised fine-tuning trainer.

Behavioral parity target: ``AccelerateSFTTrainer``
(``trlx/trainer/accelerate_sft_trainer.py:16-75``) — cross-entropy on plain
samples or on prompt/output dialogs with non-output tokens loss-masked via
``IGNORE_INDEX`` labels built by the pipeline
(``trlx/pipeline/offline_pipeline.py:72-99``).
"""

from typing import Any, Dict, List, Tuple, Union

import jax

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.models.sft import SFTConfig
from trlx_tpu.pipeline.offline_pipeline import DialogStore, tokenize_dialogue
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.base import TPUBaseTrainer
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


@register_trainer
class SFTTrainer(TPUBaseTrainer):
    model_head = None

    def __init__(self, config: TRLConfig, **kwargs):
        super().__init__(config, **kwargs)
        if not isinstance(config.method, SFTConfig):
            raise ValueError("config.method must be SFTConfig")
        self.store: DialogStore = None

    def make_experience(
        self, samples: List[Union[str, List[str]]], seq_length: int
    ) -> None:
        """Tokenize samples (strings or interleaved prompt/output lists) into
        a loss-masked :class:`DialogStore`."""
        dialogs = [tokenize_dialogue(s, self.tokenizer, seq_length) for s in samples]
        self.store = DialogStore(dialogs, self.tokenizer)

    def loss_fn(
        self, params: Any, batch: Dict[str, jax.Array], rng: jax.Array
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        chunk = self._resolved_logit_chunk()
        if chunk:
            # stream the vocab projection: logits_span=(0,0) returns hidden
            # states with an empty logits tensor, chunked_loss does the rest
            out = self.module.apply(
                {"params": params},
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                logits_span=(0, 0),
            )
            return self.with_router_aux(
                self.config.method.chunked_loss(
                    self.module, params, out["hidden_states"], batch["labels"], chunk
                ),
                out,
            )
        out = self.module.apply(
            {"params": params},
            batch["input_ids"],
            attention_mask=batch["attention_mask"],
        )
        return self.with_router_aux(
            self.config.method.loss(out["logits"], batch["labels"]), out
        )

    def prepare_learning(self) -> None:
        self._resolved_logit_chunk()  # surface the ignored-knob warning early
        self.train_dataloader = self.store.create_loader(
            self.config.train.batch_size, shuffle=True, seed=self.config.train.seed
        )
        self.n_updates_per_batch = 1
        self.total_steps = min(
            self.config.train.total_steps,
            self.config.train.epochs * len(self.train_dataloader),
        )
