"""Crash flight recorder: a bounded ring of recent spans, metric updates,
resilience events, and engine snapshots, dumped as ``flightrec.json`` when a
run dies.

Post-mortems of distributed RL runs usually start from almost nothing: the
tracker stream ends mid-step, the Perfetto trace (if it was exported at all)
is capped, and the interesting part — the last few seconds before the NaN
halt / preemption / crash — is exactly what a forward-only log loses first.
The flight recorder is the black box for that window:

- a **bounded deque** (``capacity`` records, oldest evicted first) that
  keeps rotating even after the span tracer's own buffer hits its cap —
  the recorder taps :meth:`Tracer.add_listener`, which fires for dropped
  events too;
- **metric updates** arrive through :meth:`MetricsRegistry.add_listener`,
  so every ``resilience/*`` counter bump and ``cluster/*`` gauge write is
  in the ring with a wall-clock timestamp;
- **structured events** (``record(kind, payload)``) from the trainer loop:
  per-step stats, preemption/rollback decisions, fault-plan firings, and
  :class:`~trlx_tpu.engine.core.EngineStats` snapshots;
- :meth:`dump` writes the ring as one JSON document — atomically
  (tmp + rename), never raising — from the existing crash-safe shutdown
  path (``trainer/base.py::_shutdown_observability``) on any exception,
  NaN-halt, or preemption, and deterministically via the
  ``flightrec_dump@step:N`` fault-plan trigger (docs/RESILIENCE.md).

Thread-safe: span listeners fire from pipeline worker threads while the
learn loop records step stats.
"""

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

DEFAULT_CAPACITY = 512
FLIGHTREC_FORMAT = 1


def _jsonable(value: Any) -> Any:
    """Best-effort JSON coercion: numpy scalars → python, arrays → a shape
    summary, unknown objects → ``repr``. The recorder must never refuse a
    payload — a crash dump with a lossy field beats no dump."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    item = getattr(value, "item", None)
    shape = getattr(value, "shape", None)
    if item is not None and shape is not None:
        if shape == ():
            try:
                return _jsonable(item())
            except Exception:
                pass
        return f"<array shape={tuple(shape)} dtype={getattr(value, 'dtype', '?')}>"
    return repr(value)


class FlightRecorder:
    """Bounded forensic ring buffer (see module docstring)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY, enabled: bool = True):
        self.enabled = enabled
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        # span listeners append from pipeline worker threads while the learn
        # loop records step events: every mutation takes the lock (enforced
        # by graftlint's lock-discipline pass, docs/STATIC_ANALYSIS.md)
        self._ring: deque = deque(maxlen=self.capacity)  # guarded-by: _lock
        self.recorded = 0  # total ever recorded, ring evicts  # guarded-by: _lock
        self.dumps = 0  # guarded-by: _lock
        self._t0 = time.time()

    # -- recording -------------------------------------------------------

    def record(self, kind: str, payload: Optional[Dict[str, Any]] = None) -> None:
        """Append one record; ``payload`` is coerced to JSON-safe values."""
        if not self.enabled:
            return
        rec = {"t": time.time(), "kind": kind}
        if payload:
            rec["data"] = _jsonable(payload)
        with self._lock:
            self._ring.append(rec)
            self.recorded += 1

    def span_listener(self, event: Dict[str, Any]) -> None:
        """``Tracer.add_listener`` tap: one ring record per closed span /
        instant (metadata events skipped — track labels are trace-only)."""
        if event.get("ph") == "M":
            return
        payload = {
            "name": event.get("name"),
            "ts_s": event.get("ts", 0.0) / 1e6,
            "dur_s": event.get("dur", 0.0) / 1e6,
            "pid": event.get("pid"),
            "tid": event.get("tid"),
        }
        args = event.get("args")
        if args:
            payload["args"] = args
        self.record("span", payload)

    def metric_listener(self, op: str, name: str, value: float) -> None:
        """``MetricsRegistry.add_listener`` tap: counter/gauge writes."""
        self.record("metric", {"op": op, "name": name, "value": value})

    # -- reading / dumping ----------------------------------------------

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def dump(
        self,
        path: str,
        reason: str = "unspecified",
        extra: Optional[Dict[str, Any]] = None,
    ) -> Optional[str]:
        """Write the ring as ``flightrec.json`` (atomic tmp + rename).

        Returns the written path, or None on failure — a crash dump must
        never mask the original exception with its own."""
        try:
            with self._lock:
                records = list(self._ring)
                recorded_total = self.recorded
                self.dumps += 1
                n_dumps = self.dumps
            doc = {
                "format": FLIGHTREC_FORMAT,
                "reason": reason,
                "dumped_at": time.time(),
                "started_at": self._t0,
                "capacity": self.capacity,
                "recorded_total": recorded_total,
                "dump_number": n_dumps,
                "records": records,
            }
            if extra:
                doc.update(_jsonable(extra))
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, path)
            return path
        except Exception as e:  # pragma: no cover - defensive
            logger.warning(f"flight recorder dump failed: {e}")
            return None
