"""Lightweight span tracer for the runtime (host wall-time, device-fenced).

The trainer's timers historically clocked JAX's *async dispatch* — the host
returns from a jitted call long before the device finishes. A :class:`Span`
therefore carries an optional **fence**: a pytree of device arrays that is
``jax.block_until_ready``-ed at span exit, so the recorded duration is
device-true execution time, not dispatch latency.

Spans nest (a thread-local stack), are rank-aware (every event records
``jax.process_index()`` as its Chrome-trace ``pid``), and export two ways:

- ``export_jsonl(path)`` — one JSON object per span, grep/pandas friendly;
- ``export_chrome_trace(path)`` — Chrome/Perfetto ``trace.json`` (complete
  ``"ph": "X"`` events; containment on one ``tid`` renders as nesting).

Usage::

    from trlx_tpu.observability import span

    with span("rollout"):
        with span("generate") as sp:
            out = generate(...)
            sp.fence(out.sequences)   # block on device work at exit
"""

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

FenceLike = Union[None, Any, Callable[[], Any]]


def _process_index() -> int:
    # lazy: importing/initializing jax at module import would race the
    # platform-selection env vars set by conftest/initialize_runtime
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


def _block(tree: Any) -> None:
    import jax

    jax.block_until_ready(tree)


class Span:
    """One timed region. ``duration`` is valid after the span closes."""

    __slots__ = ("name", "depth", "args", "t0", "t1", "_fence")

    def __init__(self, name: str, depth: int, args: Optional[Dict[str, Any]] = None):
        self.name = name
        self.depth = depth
        self.args = args or {}
        self.t0 = time.perf_counter()
        self.t1: Optional[float] = None
        self._fence: FenceLike = None

    def fence(self, tree: FenceLike) -> "Span":
        """Set the device pytree to ``block_until_ready`` at span exit."""
        self._fence = tree
        return self

    @property
    def duration(self) -> float:
        """Seconds, device-fenced if a fence was set. 0.0 while open."""
        return (self.t1 - self.t0) if self.t1 is not None else 0.0

    def close(self) -> float:
        if self._fence is not None:
            _block(self._fence() if callable(self._fence) else self._fence)
        self.t1 = time.perf_counter()
        return self.duration


class Tracer:
    """Collects closed spans as Chrome-trace-shaped events.

    Thread-safe for recording; the span *stack* is thread-local so spans
    opened on different threads nest independently. The event buffer is
    bounded (``max_events``): past the cap, events are dropped and counted
    rather than growing without limit over a long run.
    """

    def __init__(self, enabled: bool = True, max_events: int = 200_000):
        self.enabled = enabled
        self.max_events = max_events
        self._lock = threading.Lock()
        # spans close on pipeline worker threads too: every mutation of the
        # shared buffers below takes the lock (enforced statically by
        # graftlint's lock-discipline pass, docs/STATIC_ANALYSIS.md)
        self.dropped = 0  # guarded-by: _lock
        self._events: List[Dict[str, Any]] = []  # guarded-by: _lock
        self._local = threading.local()
        self._epoch = time.perf_counter()
        self._last_duration: Dict[str, float] = {}  # guarded-by: _lock
        # event listeners (the crash flight recorder): called for EVERY
        # event, including ones the bounded buffer drops — the recorder's
        # own ring keeps rotating after the tracer cap is hit, which is
        # exactly when a long run crashes
        self._listeners: List[Callable[[Dict[str, Any]], None]] = []  # guarded-by: _lock

    # -- recording ------------------------------------------------------

    def _stack(self) -> List[Span]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    def _tid(self) -> int:
        return getattr(
            self._local, "tid", None
        ) or threading.get_ident() % 2**31

    def alias_current_thread(self, alias: str) -> None:
        """Record this thread's events under a stable pseudo-tid derived
        from ``alias`` instead of the OS thread id. Short-lived workers that
        recur under one role — e.g. the rollout pipeline spawns one worker
        per ``make_experience`` call — then share a single named track in
        the Chrome/Perfetto export instead of scattering one near-empty row
        per incarnation. Emits the ``thread_name`` metadata event once per
        alias so the track is labeled in the viewer."""
        self._local.tid = self._track_tid(alias)

    @contextmanager
    def span(  # acquires: span
        self, name: str, fence: FenceLike = None, **args: Any
    ) -> Iterator[Span]:
        """Open a nested span; closes (and fences) on exit even on error.

        Declared to graftlint's ownership pass (GL80x): the idiomatic
        ``with tracer.span(...):`` is release-covered by ``__exit__``; a
        bare call that stashes (or discards) the context manager without
        entering it leaks the open span and is a finding."""
        stack = self._stack()
        sp = Span(name, depth=len(stack), args=args)
        if fence is not None:
            sp.fence(fence)
        stack.append(sp)
        try:
            yield sp
        finally:
            # remove *this* span (not blindly the top): an exception that
            # unwinds past a manually-entered inner span must not corrupt
            # the depth bookkeeping of outer spans
            if sp in stack:
                stack.remove(sp)
            dur = sp.close()
            with self._lock:  # worker + main thread both close spans
                self._last_duration[name] = dur
            if self.enabled:
                self._record(sp)

    def instant(self, name: str, **args: Any) -> None:
        """A zero-duration marker event (Chrome-trace ``"ph": "i"``)."""
        if not self.enabled:
            return
        event = {
            "name": name,
            "ph": "i",
            "ts": (time.perf_counter() - self._epoch) * 1e6,
            "pid": _process_index(),
            "tid": self._tid(),
            "s": "t",
        }
        if args:
            event["args"] = args
        self._append(event)

    def _record(self, sp: Span) -> None:
        event = {
            "name": sp.name,
            "ph": "X",
            "ts": (sp.t0 - self._epoch) * 1e6,
            "dur": (sp.t1 - sp.t0) * 1e6,
            "pid": _process_index(),
            "tid": self._tid(),
        }
        if sp.args:
            event["args"] = dict(sp.args)
        self._append(event)

    def _append(self, event: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
            else:
                self._events.append(event)
            listeners = list(self._listeners)
        # listeners run OUTSIDE the lock (a listener touching the tracer
        # must not deadlock) and are never allowed to break recording
        for fn in listeners:
            try:
                fn(event)
            except Exception:  # pragma: no cover - defensive
                pass

    def add_listener(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        """Subscribe to every recorded (or cap-dropped) event — the crash
        flight recorder's tap (``observability/flightrec.py``)."""
        with self._lock:
            self._listeners.append(fn)

    def _track_tid(self, alias: str) -> int:
        """Stable pseudo-tid for a named track, emitting the labeling
        ``thread_name`` metadata event once per alias (shared by
        :meth:`alias_current_thread` and :meth:`add_complete_event`)."""
        import zlib

        tid = zlib.crc32(alias.encode()) % 2**31 or 1
        if not self.enabled:
            return tid
        with self._lock:
            seen = getattr(self, "_aliased", None)
            if seen is None:
                seen = self._aliased = set()
            if alias in seen:
                return tid
            seen.add(alias)
        self._append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": _process_index(),
                "tid": tid,
                "args": {"name": alias},
            }
        )
        return tid

    def add_complete_event(
        self, name: str, t0: float, t1: float, track: Optional[str] = None,
        **args: Any,
    ) -> None:
        """Record a complete (``"ph": "X"``) event with *explicit*
        ``time.perf_counter`` endpoints — for retrospective spans whose
        boundaries were only known after the fact (the Engine's per-request
        lifecycle: queue wait → prefill → decode, emitted at harvest).
        ``track`` names a stable pseudo-thread row in the viewer."""
        if not self.enabled:
            return
        event: Dict[str, Any] = {
            "name": name,
            "ph": "X",
            "ts": (t0 - self._epoch) * 1e6,
            "dur": max(t1 - t0, 0.0) * 1e6,
            "pid": _process_index(),
            "tid": self._track_tid(track) if track else self._tid(),
        }
        if args:
            event["args"] = dict(args)
        self._append(event)

    # -- reading / export ----------------------------------------------

    def last_duration(self, name: str, default: float = 0.0) -> float:
        """Duration of the most recently closed span named ``name``."""
        return self._last_duration.get(name, default)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events = []
            self.dropped = 0

    def to_chrome_trace(self) -> Dict[str, Any]:
        meta = {"dropped_events": self.dropped} if self.dropped else {}
        return {"traceEvents": self.events(), "displayTimeUnit": "ms", **meta}

    def export_chrome_trace(self, path: str) -> str:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path

    def export_jsonl(self, path: str) -> str:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            for e in self.events():
                if e.get("ph") == "M":  # metadata (thread names): trace-only
                    continue
                record = {
                    "name": e["name"],
                    "start_s": e["ts"] / 1e6,
                    "dur_s": e.get("dur", 0.0) / 1e6,
                    "pid": e["pid"],
                    "tid": e["tid"],
                }
                if "args" in e:
                    record["args"] = e["args"]
                f.write(json.dumps(record) + "\n")
        return path


# ---------------------------------------------------------------------------
# module-level default tracer (library users without a trainer)
# ---------------------------------------------------------------------------

_DEFAULT_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _DEFAULT_TRACER


@contextmanager
def span(name: str, fence: FenceLike = None, **args: Any) -> Iterator[Span]:
    """``with span("rollout"): ...`` on the module-level default tracer."""
    with _DEFAULT_TRACER.span(name, fence=fence, **args) as sp:
        yield sp
