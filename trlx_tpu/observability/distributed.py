"""Distributed observability: cross-rank telemetry, straggler/desync
detection, and merged multi-rank Perfetto traces.

Every other telemetry surface in this package is single-process: each rank
times its own spans, publishes its own metrics, and writes its own trace.
The multihost paths (coordinated preemption, elastic restore — see
``resilience/elastic.py``) are therefore blind exactly where distributed RL
systems fail: one slow or desynced worker stalls the whole pod (Podracer,
arXiv 2104.06272; RLAX's disaggregated TPU design, arXiv 2512.06392, both
treat per-actor visibility as a prerequisite for the actor/learner split).
Three pieces close the gap:

**Cross-rank metric beat** — :class:`ClusterTelemetry` packs a small vector
of per-rank scalars (preemption flag, step counter, step time, host wait,
tokens/s, device memory, a clock timestamp) and allgathers it ONCE per step
boundary over the gloo host collectives — the *same* collective that
coordinates preemption (``coordinate_preemption``), so distributed
telemetry adds **no new sync points**: the preemption flag simply rides in
slot 0 of the telemetry vector. ``cluster/*`` min/mean/max/skew gauges are
computed from the gathered matrix (identical on every rank; only process
0's tracker publishes them downstream).

**Straggler & desync detection** — a rank whose step time persistently
exceeds the median of its *peers* (``straggler_factor`` ×, for
``straggler_patience`` consecutive beats) is flagged in
``cluster/straggler_rank`` (−1 when healthy) with a log warning and a
flight-recorder event. Per-rank step counters ride the same vector; they
can only diverge if a rank skipped or replayed a boundary, so divergence
raises :class:`ClusterDesyncError` immediately — a hard diagnostic beats
the silent collective-mismatch hang it would otherwise become.

**Merged timelines** — each beat also estimates per-rank clock offsets from
the shared barrier timestamps (all ranks stamp ``perf_counter`` relative to
their tracer epoch immediately before posting the same allgather, so
``offset_k = ts_0 − ts_k``, median over beats). At export, non-zero ranks
write ``trace_rank<k>.json`` into the shared trace dir and process 0 merges
every rank's events — shifted onto rank 0's clock — into ONE Perfetto
``trace.json`` (per-rank ``pid`` rows, labeled ``rank k``), so a cross-rank
stall is one screenful instead of N unalignable files.

Knobs: ``TRLX_TPU_CLUSTER_TELEMETRY=0`` disables the telemetry *analysis*
(gauges, straggler/desync detection, clock offsets) — but NOT the
coordination collective: when ``resilience.coordinate_preemption`` is on,
a disabled rank still posts the same packed-vector allgather as its
enabled peers and only the analysis half is skipped. The collective
schedule may depend only on rank-uniform config, never a per-process env
var — otherwise one mis-launched rank posts a mismatched collective and
hangs the pod (graftlint GL704's rank-uniformity contract,
docs/STATIC_ANALYSIS.md). ``TRLX_TPU_TRACE_MERGE_WAIT_S`` bounds how long
process 0 waits for peer trace files (default 15s; missing ranks are
recorded in the merged trace's metadata rather than hanging the export).
See docs/OBSERVABILITY.md "Distributed telemetry".
"""

import json
import os
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

# the packed per-rank beat vector, one float32 per field (float32 survives
# the x64-disabled jax default; step counters are exact to 2**24)
PACK_FIELDS = (
    "preempt",  # 1.0 when this rank requested preemption
    "step",  # completed-update counter (desync check)
    "step_time_s",  # last fenced train-step seconds
    "host_wait_s",  # beat-to-beat wall time minus step time
    "tokens_per_sec",  # last step's throughput
    "device_bytes",  # device bytes in use (host RSS on CPU)
    "clock_s",  # clock fine part: (perf_counter − epoch) mod _CLOCK_COARSE_S
    "clock_hi_s",  # clock coarse part: the subtracted _CLOCK_COARSE_S multiple
    "fleet_size",  # live async actor-fleet members (−1: no fleet on this rank)
)

# The clock stamp is split coarse+fine so float32 packing stays sub-ms for
# arbitrarily long runs: a single f32 seconds-since-epoch loses ~12 ms of
# resolution by day 3 (ulp at 2e5 s), which would mis-shift the merged
# trace by more than the engine stalls it attributes. The coarse part is an
# exact-f32 multiple of 1024 s; the fine part stays < 1024 s (ulp ≤ 61 µs).
_CLOCK_COARSE_S = 1024.0

DEFAULT_STRAGGLER_FACTOR = 1.5
DEFAULT_STRAGGLER_MIN_S = 0.05
DEFAULT_STRAGGLER_PATIENCE = 2
_OFFSET_WINDOW = 64


class ClusterDesyncError(RuntimeError):
    """Per-rank step counters diverged at a shared step boundary — a rank
    skipped or replayed an update. Continuing would turn into a silent
    collective mismatch/hang; failing here names the ranks instead."""


def _default_allgather(vec: np.ndarray) -> np.ndarray:
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(vec))


class ClusterTelemetry:
    """Per-trainer cross-rank telemetry beat (see module docstring).

    ``allgather`` is injectable for tests (a callable ``[K] -> [P, K]``);
    the default is ``multihost_utils.process_allgather`` — the gloo host
    collective the coordinated-preemption flag already rides.
    """

    def __init__(
        self,
        tracer: Any,
        metrics: Any,
        flightrec: Any = None,
        straggler_factor: float = DEFAULT_STRAGGLER_FACTOR,
        straggler_min_s: float = DEFAULT_STRAGGLER_MIN_S,
        straggler_patience: int = DEFAULT_STRAGGLER_PATIENCE,
        allgather: Optional[Callable[[np.ndarray], np.ndarray]] = None,
        enabled: Optional[bool] = None,
    ):
        if enabled is None:
            enabled = os.environ.get("TRLX_TPU_CLUSTER_TELEMETRY", "1") != "0"
        self.enabled = enabled
        self.tracer = tracer
        self.metrics = metrics
        self.flightrec = flightrec
        self.straggler_factor = float(straggler_factor)
        self.straggler_min_s = float(straggler_min_s)
        self.straggler_patience = int(straggler_patience)
        self._allgather = allgather
        self.beats = 0
        self.straggler_rank = -1
        self._exceed_counts: Dict[int, int] = {}
        self._offsets: Dict[int, deque] = {}
        self._last_step: Dict[str, float] = {
            "step_time_s": 0.0,
            "tokens_per_sec": 0.0,
            "device_bytes": 0.0,
        }
        self._fleet_size = -1.0
        self._last_beat_t: Optional[float] = None

    # -- feeding ---------------------------------------------------------

    def note_step(
        self,
        step_time_s: float,
        tokens_per_sec: float = 0.0,
        device_bytes: float = 0.0,
    ) -> None:
        """Record the just-completed step's scalars; the NEXT beat (the
        boundary before the following update) exchanges them."""
        self._last_step = {
            "step_time_s": float(step_time_s),
            "tokens_per_sec": float(tokens_per_sec),
            "device_bytes": float(device_bytes),
        }

    def note_fleet(self, size: Optional[int]) -> None:
        """Record this rank's live async actor-fleet size (``None`` = no
        collective fleet here). The membership gauge rides the NEXT beat's
        packed vector — the same allgather as everything else, so elastic
        fleet visibility adds zero new sync points."""
        self._fleet_size = -1.0 if size is None else float(size)

    # -- the beat --------------------------------------------------------

    def beat(self, requested: bool, step: int, collective: bool = True) -> bool:
        """One step-boundary exchange. Returns True when ANY rank has
        requested preemption (the coordinated-preemption decision — slot 0
        of the packed vector; ``trainer/base.py`` consumes it so the old
        flag-only allgather is subsumed, not duplicated).

        ``collective=False`` (coordination disabled by config) keeps the
        beat local: gauges still publish from this rank's own scalars and
        no collective is posted — telemetry never adds a sync point the
        run didn't already have.

        The collective schedule depends ONLY on ``collective`` (the
        rank-uniform ``resilience.coordinate_preemption`` config) — never
        on ``self.enabled``: the enabled flag comes from a per-process env
        var (``TRLX_TPU_CLUSTER_TELEMETRY``), and an env var that selects
        *which* collective a rank posts would let one mis-launched rank
        hang (or desync) the whole pod. A disabled rank therefore still
        posts the same packed-vector allgather when coordination is on; it
        just skips the analysis/publishing half (graftlint GL704's
        rank-uniformity contract, docs/STATIC_ANALYSIS.md).
        """
        if not self.enabled and not collective:
            return bool(requested)
        import jax

        now = time.perf_counter()
        step_time = self._last_step["step_time_s"]
        host_wait = 0.0
        if self._last_beat_t is not None:
            host_wait = max(0.0, (now - self._last_beat_t) - step_time)
        self._last_beat_t = now
        clock = now - getattr(self.tracer, "_epoch", 0.0)
        clock_hi = float(np.floor(clock / _CLOCK_COARSE_S) * _CLOCK_COARSE_S)
        vec = np.asarray(
            [
                float(bool(requested)),
                float(step),
                step_time,
                host_wait,
                self._last_step["tokens_per_sec"],
                self._last_step["device_bytes"],
                clock - clock_hi,
                clock_hi,
                self._fleet_size,
            ],
            np.float32,
        )
        gather = self._allgather
        if gather is None and collective and jax.process_count() > 1:
            gather = _default_allgather
        if gather is not None:
            matrix = np.asarray(gather(vec), np.float32).reshape(
                -1, len(PACK_FIELDS)
            )
        else:
            matrix = vec[None]
        if not self.enabled:
            # coordination-only beat: this rank posted the SAME collective
            # as its enabled peers (payload shapes must match rank-for-rank)
            # but skips the analysis/publishing half entirely
            return bool(matrix[:, 0].any())
        self.beats += 1
        self._check_desync(matrix)
        # clock offsets: every rank stamped its clock immediately before the
        # same barrier — offset_k maps rank k's tracer timeline onto rank
        # 0's (median over beats absorbs per-beat arrival skew). Coarse and
        # fine parts recombine in float64.
        clocks = matrix[:, 6].astype(np.float64) + matrix[:, 7].astype(
            np.float64
        )
        for k in range(matrix.shape[0]):
            self._offsets.setdefault(k, deque(maxlen=_OFFSET_WINDOW)).append(
                float(clocks[0] - clocks[k])
            )
        self._publish(matrix)
        return bool(matrix[:, 0].any())

    # -- analysis --------------------------------------------------------

    def _check_desync(self, matrix: np.ndarray) -> None:
        steps = matrix[:, 1].astype(np.int64)
        if len(set(steps.tolist())) <= 1:
            return
        detail = ", ".join(f"rank {k}: step {int(s)}" for k, s in enumerate(steps))
        if self.flightrec is not None:
            self.flightrec.record(
                "desync", {"steps": steps.tolist(), "beat": self.beats}
            )
        raise ClusterDesyncError(
            f"per-rank step counters diverged at a shared step boundary "
            f"({detail}) — a rank skipped or replayed an update; continuing "
            f"would become a silent collective mismatch. Check for "
            f"per-rank conditionals around train_step / checkpoint restore "
            f"(docs/OBSERVABILITY.md 'Distributed telemetry')."
        )

    def _detect_straggler(self, step_times: np.ndarray) -> int:
        """Flag the lowest rank whose step time exceeded the median of its
        PEERS (excluding itself — with 2 ranks the straggler would halve
        its own threshold otherwise) for ``straggler_patience`` consecutive
        beats. −1 when healthy."""
        n = step_times.shape[0]
        if n < 2:
            return -1
        for k in range(n):
            others = np.delete(step_times, k)
            med = float(np.median(others))
            threshold = max(
                med * self.straggler_factor, med + self.straggler_min_s
            )
            if float(step_times[k]) > threshold:
                self._exceed_counts[k] = self._exceed_counts.get(k, 0) + 1
            else:
                self._exceed_counts[k] = 0
        flagged = [
            k
            for k, c in sorted(self._exceed_counts.items())
            if c >= self.straggler_patience
        ]
        return flagged[0] if flagged else -1

    def _publish(self, matrix: np.ndarray) -> None:
        metrics = self.metrics
        st = matrix[:, 2]
        hw = matrix[:, 3]
        tps = matrix[:, 4]
        mem = matrix[:, 5]
        straggler = self._detect_straggler(st)
        if straggler >= 0 and straggler != self.straggler_rank:
            logger.warning(
                "cluster telemetry: rank %d is a persistent straggler "
                "(step %.3fs vs peer median %.3fs over %d+ boundaries) — "
                "the whole pod steps at its pace",
                straggler,
                float(st[straggler]),
                float(np.median(np.delete(st, straggler))),
                self.straggler_patience,
            )
            if self.flightrec is not None:
                self.flightrec.record(
                    "straggler",
                    {"rank": straggler, "step_times_s": st.tolist()},
                )
        self.straggler_rank = straggler
        if metrics is None:
            return
        # literal keys: statically visible to graftlint's GL501 scan
        # (CLUSTER_KEYS in analysis/conventions.py is the canonical list)
        metrics.set_gauge("cluster/size", float(matrix.shape[0]))
        metrics.set_gauge("cluster/step_time_min_s", float(st.min()))
        metrics.set_gauge("cluster/step_time_mean_s", float(st.mean()))
        metrics.set_gauge("cluster/step_time_max_s", float(st.max()))
        metrics.set_gauge("cluster/step_skew_s", float(st.max() - st.min()))
        metrics.set_gauge("cluster/host_wait_mean_s", float(hw.mean()))
        metrics.set_gauge("cluster/host_wait_max_s", float(hw.max()))
        metrics.set_gauge("cluster/tokens_per_sec_min", float(tps.min()))
        metrics.set_gauge("cluster/tokens_per_sec_sum", float(tps.sum()))
        metrics.set_gauge("cluster/device_bytes_in_use_max", float(mem.max()))
        metrics.set_gauge("cluster/straggler_rank", float(straggler))
        # elastic actor-fleet membership (docs/ASYNC_RL.md "Transports"):
        # the learner rank carries the live member count, peers carry −1 —
        # publish only when some rank actually hosts a fleet (a −1 gauge
        # on every fleet-less run would just pollute dashboards)
        fleet = matrix[:, 8]
        if fleet.max() >= 0:
            metrics.set_gauge("cluster/fleet_size", float(fleet.max()))

    def clock_offsets(self) -> Dict[int, float]:
        """rank → seconds to ADD to that rank's tracer-relative timestamps
        to land them on rank 0's timeline (median over the beat window)."""
        return {
            k: float(np.median(np.asarray(buf)))
            for k, buf in self._offsets.items()
            if len(buf)
        }


# ---------------------------------------------------------------------------
# merged multi-rank Perfetto traces
# ---------------------------------------------------------------------------


def rank_trace_name(rank: int) -> str:
    return f"trace_rank{rank}.json"


def write_rank_trace(tracer: Any, directory: str, rank: int) -> str:
    """Non-zero ranks: write this rank's Chrome-trace doc atomically into
    the shared trace dir for process 0's merge (tmp + rename, so a
    concurrent merge never reads a half-written file)."""
    os.makedirs(os.path.abspath(directory), exist_ok=True)
    path = os.path.join(directory, rank_trace_name(rank))
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(tracer.to_chrome_trace(), f)
    os.replace(tmp, path)
    return path


def _read_rank_trace(
    path: str, min_mtime: float = 0.0
) -> Optional[List[Dict[str, Any]]]:
    try:
        if os.path.getmtime(path) < min_mtime:
            return None  # stale file from a previous run incarnation
        with open(path) as f:
            return json.load(f).get("traceEvents", [])
    except (OSError, ValueError):
        return None


def merge_cluster_trace(
    tracer: Any,
    directory: str,
    process_count: int,
    offsets: Optional[Dict[int, float]] = None,
    timeout_s: Optional[float] = None,
    min_mtime: float = 0.0,
) -> str:
    """Process 0: merge every rank's span stream into ONE Perfetto
    ``trace.json`` on rank 0's clock.

    Peer files are written by each rank's own export (same shutdown path),
    so process 0 polls for them up to ``timeout_s`` — bounded, never a
    collective: a rank that died without exporting costs a warning and a
    ``missing_ranks`` note in the merged metadata, not a hung shutdown.
    ``min_mtime`` guards against a relaunched run (same logging dir — the
    documented resume workflow) silently merging the PREVIOUS
    incarnation's peer files: anything written before this run started is
    treated as not-yet-written and polled past.
    """
    if timeout_s is None:
        timeout_s = float(os.environ.get("TRLX_TPU_TRACE_MERGE_WAIT_S", 15.0))
    offsets = offsets or {}
    events: List[Dict[str, Any]] = [
        {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
         "args": {"name": "rank 0"}},
    ]
    events.extend(tracer.events())
    missing: List[int] = []
    deadline = time.monotonic() + timeout_s
    for rank in range(1, process_count):
        path = os.path.join(directory, rank_trace_name(rank))
        peer = _read_rank_trace(path, min_mtime)
        while peer is None and time.monotonic() < deadline:
            time.sleep(0.2)
            peer = _read_rank_trace(path, min_mtime)
        if peer is None:
            missing.append(rank)
            logger.warning(
                f"trace merge: no fresh trace from rank {rank} within "
                f"{timeout_s:.0f}s ({path}) — merging without it"
            )
            continue
        shift_us = offsets.get(rank, 0.0) * 1e6
        events.append(
            {"name": "process_name", "ph": "M", "pid": rank, "tid": 0,
             "args": {"name": f"rank {rank}"}}
        )
        for e in peer:
            e = dict(e)
            if "ts" in e:
                e["ts"] = e["ts"] + shift_us
            events.append(e)
    doc: Dict[str, Any] = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "clock_offsets_s": {str(k): v for k, v in offsets.items()},
    }
    if tracer.dropped:
        doc["dropped_events"] = tracer.dropped
    if missing:
        doc["missing_ranks"] = missing
    os.makedirs(os.path.abspath(directory), exist_ok=True)
    out = os.path.join(directory, "trace.json")
    tmp = f"{out}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out)
    return out
