"""Watchdogs: silent-recompile detection and device-memory gauging.

Two failure modes are invisible until a pod run dies:

- **steady-state recompiles** — a shape/dtype drift (unpadded batch, a new
  gen-kwarg combination) makes a supposedly-warm jitted program retrace
  every step, turning a 100ms step into a multi-second one with no error;
- **HBM growth** — a leaked buffer or an unexpectedly replicated tree grows
  device memory until an OOM kills the run hours in.

:class:`RecompileWatchdog` tracks each registered jitted callable's compile
cache (``_cache_size()`` where the jit wrapper exposes it, an argument
shape-signature set otherwise) and logs a warning — plus a
``recompile/<program>`` counter — whenever a program that already compiled
once compiles *again*. :class:`DeviceMemoryGauge` reads
``device.memory_stats()`` where the backend provides it (TPU/GPU), falling
back to host RSS on CPU, and warns when usage crosses a fraction of the
device limit.
"""

from typing import Any, Callable, Dict, Optional

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


def _cache_size(fn: Callable) -> Optional[int]:
    """Compile-cache entry count of a ``jax.jit`` wrapper, if exposed."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:
        return None


def _signature(args: Any) -> tuple:
    import jax

    return tuple(
        (getattr(leaf, "shape", None), str(getattr(leaf, "dtype", type(leaf))))
        for leaf in jax.tree_util.tree_leaves(args)
    )


class RecompileWatchdog:
    """Warns when a warm jitted program compiles again.

    The *first* compile of a program is expected and silent; every
    subsequent cache growth for the same program name is counted
    (``recompile/<name>``) and logged — one warning per event, with a
    rate-limit so a pathological per-step retrace doesn't flood the log.
    """

    def __init__(self, metrics=None, max_warnings: int = 10):
        self.metrics = metrics
        self.max_warnings = max_warnings
        # all bookkeeping is per (name, id(fn)): several distinct jitted
        # programs may share one logical name (e.g. the eval-config and
        # experience-config "generate" fns), and a second program's *first*
        # compile must not be reported as a retrace of the first
        self._cache_sizes: Dict[tuple, int] = {}  # key -> last seen size
        self._signatures: Dict[tuple, set] = {}  # key -> seen arg signatures
        self._compiles: Dict[tuple, int] = {}  # key -> total compiles seen
        self._warnings = 0

    def observe(self, name: str, fn: Callable, args: Any = None) -> int:
        """Record one call of ``fn`` under program ``name``; returns the
        number of *excess* (post-warmup) compiles seen for this fn so far."""
        key = (name, id(fn))
        size = _cache_size(fn)
        if size is not None:
            prev = self._cache_sizes.get(key)
            self._cache_sizes[key] = size
            new = size - prev if prev is not None else size
        elif args is not None:  # fallback: shape-signature tracking
            seen = self._signatures.setdefault(key, set())
            sig = _signature(args)
            new = 0 if sig in seen else 1
            seen.add(sig)
        else:
            return 0
        total = self._compiles.get(key, 0) + new
        if new <= 0:
            return max(total - 1, 0)
        self._compiles[key] = total
        if total > 1:
            newly_excess = min(new, total - 1)
            if self.metrics is not None:
                self.metrics.inc(f"recompile/{name}", newly_excess)
            if self._warnings < self.max_warnings:
                self._warnings += 1
                logger.warning(
                    "recompile watchdog: program '%s' retraced (compile #%d) — "
                    "a warm program recompiling usually means a shape/dtype "
                    "drift in its inputs; every retrace stalls the step for a "
                    "full XLA compile",
                    name,
                    total,
                )
        return max(total - 1, 0)

    def excess_compiles(self, name: str) -> int:
        """Compiles beyond each program's expected first one, summed over
        every fn observed under ``name``."""
        return sum(
            max(total - 1, 0)
            for (prog, _fn_id), total in self._compiles.items()
            if prog == name
        )


class DeviceMemoryGauge:
    """Per-step device-memory stats with graceful CPU fallback.

    ``collect()`` returns gauge metrics (also mirrored into a registry when
    one is attached): ``memory/device_bytes_in_use`` / ``_peak_bytes`` /
    ``_limit_bytes`` (max over local devices) when the backend reports
    ``memory_stats()``, plus ``memory/host_rss_bytes`` always. Crossing
    ``warn_frac`` of the device limit logs one warning per run.
    """

    def __init__(self, metrics=None, warn_frac: float = 0.92):
        self.metrics = metrics
        self.warn_frac = warn_frac
        self._warned = False

    @staticmethod
    def _host_rss_bytes() -> Optional[float]:
        try:
            import resource
            import sys

            rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            # ru_maxrss is KiB on Linux, bytes on macOS
            return float(rss) * (1.0 if sys.platform == "darwin" else 1024.0)
        except Exception:
            return None

    def collect(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        in_use = peak = limit = None
        try:
            import jax

            for dev in jax.local_devices():
                ms = dev.memory_stats() if hasattr(dev, "memory_stats") else None
                if not ms:
                    continue
                use = ms.get("bytes_in_use")
                if use is not None:
                    in_use = max(in_use or 0.0, float(use))
                pk = ms.get("peak_bytes_in_use")
                if pk is not None:
                    peak = max(peak or 0.0, float(pk))
                lim = ms.get("bytes_limit") or ms.get("bytes_reservable_limit")
                if lim:
                    limit = max(limit or 0.0, float(lim))
        except Exception:
            pass
        if in_use is not None:
            out["memory/device_bytes_in_use"] = in_use
        if peak is not None:
            out["memory/device_peak_bytes"] = peak
        if limit is not None:
            out["memory/device_limit_bytes"] = limit
        rss = self._host_rss_bytes()
        if rss is not None:
            out["memory/host_rss_bytes"] = rss
        if (
            not self._warned
            and in_use is not None
            and limit
            and in_use / limit > self.warn_frac
        ):
            self._warned = True
            logger.warning(
                "memory watchdog: device memory at %.1f%% of limit "
                "(%.2f / %.2f GiB) — the next allocation spike may OOM",
                100.0 * in_use / limit,
                in_use / 2**30,
                limit / 2**30,
            )
        if self.metrics is not None:
            for k, v in out.items():
                self.metrics.set_gauge(k, v)
        return out
