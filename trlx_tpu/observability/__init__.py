"""Runtime observability: span tracing, metrics, MFU, and watchdogs.

The reference framework validates performance only empirically on live GPUs;
this repo's hardware-free *compiled* cost net (``trlx_tpu/perf.py``) guards
programs, but nothing observed the *running* system. This subsystem closes
that gap:

- :mod:`tracing` — nestable, rank-aware spans with device fencing
  (``block_until_ready`` at span exit) and JSONL + Chrome/Perfetto export;
- :mod:`metrics` — counters/gauges/histograms feeding the existing
  ``Tracker`` stream, plus tokens/sec / samples/sec / **MFU** derived by
  joining fenced step times against XLA ``cost_analysis`` flops of the
  exact compiled programs (``perf.lowered_costs``);
- :mod:`watchdogs` — steady-state recompile detection and device-memory
  gauges with CPU fallback;
- :mod:`profiling` — ``TRLX_TPU_PROFILE=steps:3-5,dir:...`` programmatic
  ``jax.profiler`` windows and per-step ``StepTraceAnnotation``.

:class:`Observability` bundles one instance of each per trainer. See
``docs/OBSERVABILITY.md`` for the span API and metric naming convention.
"""

import os
from typing import Any, Dict, Optional

from trlx_tpu.observability.metrics import (
    DEFAULT_PEAK_FLOPS,
    MetricsRegistry,
    ThroughputMeter,
    device_peak_flops,
    mfu,
    train_step_flops,
)
from trlx_tpu.observability.profiling import ProfileWindow, parse_profile_spec
from trlx_tpu.observability.tracing import Span, Tracer, get_tracer, span
from trlx_tpu.observability.watchdogs import DeviceMemoryGauge, RecompileWatchdog

__all__ = [
    "DEFAULT_PEAK_FLOPS",
    "DeviceMemoryGauge",
    "MetricsRegistry",
    "Observability",
    "ProfileWindow",
    "RecompileWatchdog",
    "Span",
    "ThroughputMeter",
    "Tracer",
    "device_peak_flops",
    "get_tracer",
    "mfu",
    "parse_profile_spec",
    "span",
    "train_step_flops",
]


class Observability:
    """Per-trainer bundle: tracer + metrics + watchdogs + profile window.

    Each trainer owns its own instance (no cross-trainer event bleed in a
    process that builds several). ``export()`` writes the span stream next
    to the tracker's stats (``trace.json`` + ``spans.jsonl``), process 0
    only — the same single-writer gating as the trackers.
    """

    def __init__(self, config: Any = None, trace_dir: Optional[str] = None):
        self.tracer = Tracer()
        self.metrics = MetricsRegistry()
        self.recompile = RecompileWatchdog(self.metrics)
        # no registry mirror: the learn loop merges collect() into its stats
        # directly; mirroring too would double-write every memory/* key and
        # pin stale gauges into future snapshots
        self.memory = DeviceMemoryGauge()
        self.profile = ProfileWindow.from_env(config)
        self.throughput = ThroughputMeter()
        self._trace_dir = trace_dir or os.environ.get("TRLX_TPU_TRACE_DIR")
        if self._trace_dir is None and config is not None:
            train = getattr(config, "train", None)
            logging_dir = getattr(train, "logging_dir", None)
            checkpoint_dir = getattr(train, "checkpoint_dir", None)
            if logging_dir:
                self._trace_dir = logging_dir
            elif checkpoint_dir:
                self._trace_dir = os.path.join(checkpoint_dir, "logs")

    def span(self, name: str, fence: Any = None, **args: Any):
        return self.tracer.span(name, fence=fence, **args)

    def export(self, directory: Optional[str] = None) -> Dict[str, str]:
        """Write ``trace.json`` (Chrome/Perfetto) and ``spans.jsonl``.

        Returns the written paths ({} when there is no directory, no
        events, or this is a non-zero process)."""
        directory = directory or self._trace_dir
        if not directory or not self.tracer.events():
            return {}
        import jax

        if jax.process_index() != 0:
            return {}
        return {
            "trace": self.tracer.export_chrome_trace(
                os.path.join(directory, "trace.json")
            ),
            "spans": self.tracer.export_jsonl(
                os.path.join(directory, "spans.jsonl")
            ),
        }
