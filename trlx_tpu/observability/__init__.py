"""Runtime observability: span tracing, metrics, MFU, and watchdogs.

The reference framework validates performance only empirically on live GPUs;
this repo's hardware-free *compiled* cost net (``trlx_tpu/perf.py``) guards
programs, but nothing observed the *running* system. This subsystem closes
that gap:

- :mod:`tracing` — nestable, rank-aware spans with device fencing
  (``block_until_ready`` at span exit) and JSONL + Chrome/Perfetto export;
- :mod:`metrics` — counters/gauges/histograms feeding the existing
  ``Tracker`` stream, plus tokens/sec / samples/sec / **MFU** derived by
  joining fenced step times against XLA ``cost_analysis`` flops of the
  exact compiled programs (``perf.lowered_costs``);
- :mod:`watchdogs` — steady-state recompile detection and device-memory
  gauges with CPU fallback;
- :mod:`profiling` — ``TRLX_TPU_PROFILE=steps:3-5,dir:...`` programmatic
  ``jax.profiler`` windows and per-step ``StepTraceAnnotation``;
- :mod:`distributed` — cross-rank telemetry (``cluster/*`` gauges riding
  the coordinated-preemption allgather), straggler/desync detection, and
  merged multi-rank Perfetto traces on one aligned clock;
- :mod:`flightrec` — a crash flight recorder: bounded ring of recent
  spans, metric updates, and resilience events, dumped as
  ``flightrec.json`` on any exception/NaN-halt/preemption;
- :mod:`dynamics` — on-device fixed-bin distribution sketches of training
  dynamics (log-ratio, KL, advantages, value error, entropy) riding the
  existing stats fetch, summarized into ``dist/*`` percentile gauges;
- :mod:`health` — windowed RL health detectors (KL runaway, entropy
  collapse, clipfrac saturation, value EV collapse, reward flatline,
  generation canary) publishing ``health/*`` gauges and triggering
  bad-batch triage dumps.

:class:`Observability` bundles one instance of each per trainer. See
``docs/OBSERVABILITY.md`` for the span API and metric naming convention.
"""

import os
from typing import Any, Dict, Optional

from trlx_tpu.observability.distributed import (
    ClusterDesyncError,
    ClusterTelemetry,
)
from trlx_tpu.observability.dynamics import DynamicsSummarizer
from trlx_tpu.observability.flightrec import FlightRecorder
from trlx_tpu.observability.health import HealthMonitor
from trlx_tpu.observability.metrics import (
    DEFAULT_PEAK_FLOPS,
    MetricsRegistry,
    ThroughputMeter,
    device_peak_flops,
    mfu,
    train_step_flops,
)
from trlx_tpu.observability.profiling import ProfileWindow, parse_profile_spec
from trlx_tpu.observability.tracing import Span, Tracer, get_tracer, span
from trlx_tpu.observability.watchdogs import DeviceMemoryGauge, RecompileWatchdog
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

__all__ = [
    "ClusterDesyncError",
    "ClusterTelemetry",
    "DEFAULT_PEAK_FLOPS",
    "DeviceMemoryGauge",
    "DynamicsSummarizer",
    "FlightRecorder",
    "HealthMonitor",
    "MetricsRegistry",
    "Observability",
    "ProfileWindow",
    "RecompileWatchdog",
    "Span",
    "ThroughputMeter",
    "Tracer",
    "device_peak_flops",
    "get_tracer",
    "mfu",
    "parse_profile_spec",
    "span",
    "train_step_flops",
]


class Observability:
    """Per-trainer bundle: tracer + metrics + watchdogs + profile window.

    Each trainer owns its own instance (no cross-trainer event bleed in a
    process that builds several). ``export()`` writes the span stream next
    to the tracker's stats (``trace.json`` + ``spans.jsonl``), process 0
    only — the same single-writer gating as the trackers.
    """

    def __init__(self, config: Any = None, trace_dir: Optional[str] = None):
        self.tracer = Tracer()
        self.metrics = MetricsRegistry()
        self.recompile = RecompileWatchdog(self.metrics)
        # no registry mirror: the learn loop merges collect() into its stats
        # directly; mirroring too would double-write every memory/* key and
        # pin stale gauges into future snapshots
        self.memory = DeviceMemoryGauge()
        self.profile = ProfileWindow.from_env(config)
        self.throughput = ThroughputMeter()
        # crash flight recorder (flightrec.py): taps every span and every
        # metric write so the LAST window before a crash survives the crash
        self.flightrec = FlightRecorder(
            capacity=int(os.environ.get("TRLX_TPU_FLIGHTREC_CAP", "512"))
        )
        self.tracer.add_listener(self.flightrec.span_listener)
        self.metrics.add_listener(self.flightrec.metric_listener)
        # cross-rank telemetry (distributed.py): the trainer's step-boundary
        # seam drives beat(); single-process it degenerates to local gauges
        self.cluster = ClusterTelemetry(
            self.tracer, self.metrics, flightrec=self.flightrec
        )
        # training-dynamics sketches + windowed health detectors
        # (dynamics.py / health.py); method knobs read duck-typed so a bare
        # Observability() in tests still builds
        method = getattr(config, "method", None)
        self.dynamics = DynamicsSummarizer(
            cliprange=getattr(method, "cliprange", None)
        )
        self.health = HealthMonitor(
            metrics=self.metrics,
            flightrec=self.flightrec,
            kl_target=getattr(method, "target", None),
        )
        self._warned_dropped = False
        # wall-clock construction time: the merge's staleness floor — peer
        # trace files older than this run are a previous incarnation's
        # (same logging dir across a preempt/relaunch) and must not be
        # merged as if they were this run's spans
        import time as _time

        self._t_start_wall = _time.time()
        self._trace_dir = trace_dir or os.environ.get("TRLX_TPU_TRACE_DIR")
        if self._trace_dir is None and config is not None:
            train = getattr(config, "train", None)
            logging_dir = getattr(train, "logging_dir", None)
            checkpoint_dir = getattr(train, "checkpoint_dir", None)
            if logging_dir:
                self._trace_dir = logging_dir
            elif checkpoint_dir:
                self._trace_dir = os.path.join(checkpoint_dir, "logs")

    def span(self, name: str, fence: Any = None, **args: Any):
        return self.tracer.span(name, fence=fence, **args)

    def note_dropped_spans(self) -> None:
        """Surface the tracer's silent drop counter as the
        ``obs/spans_dropped`` gauge (warn once when nonzero — a capped
        trace looks complete in the viewer but is lying about the tail)."""
        dropped = self.tracer.dropped
        self.metrics.set_gauge("obs/spans_dropped", float(dropped))
        if dropped and not self._warned_dropped:
            self._warned_dropped = True
            logger.warning(
                "span tracer dropped %d event(s) past its %d-event cap — "
                "the exported trace is missing its tail (raise "
                "Tracer(max_events=...) or export more often); the flight "
                "recorder ring keeps rotating regardless",
                dropped,
                self.tracer.max_events,
            )

    def export(self, directory: Optional[str] = None) -> Dict[str, str]:
        """Write ``trace.json`` (Chrome/Perfetto) and ``spans.jsonl``.

        Multihost: non-zero ranks write ``trace_rank<k>.json`` into the
        shared trace dir (and return {}); process 0 merges every rank's
        events — shifted onto rank 0's clock via the beat-estimated offsets
        — into ONE ``trace.json``. Single-process behavior is unchanged.
        Returns the written paths ({} when there is no directory, no
        events, or this is a non-zero process)."""
        directory = directory or self._trace_dir
        if not directory or not self.tracer.events():
            return {}
        import jax

        from trlx_tpu.observability.distributed import (
            merge_cluster_trace,
            write_rank_trace,
        )

        count = jax.process_count()
        if jax.process_index() != 0:
            if count > 1:
                write_rank_trace(self.tracer, directory, jax.process_index())
            return {}
        if count > 1:
            trace_path = merge_cluster_trace(
                self.tracer,
                directory,
                process_count=count,
                offsets=self.cluster.clock_offsets(),
                # small slack absorbs wall-vs-filesystem clock skew without
                # re-admitting a genuinely previous incarnation's files
                min_mtime=self._t_start_wall - 5.0,
            )
        else:
            trace_path = self.tracer.export_chrome_trace(
                os.path.join(directory, "trace.json")
            )
        return {
            "trace": trace_path,
            "spans": self.tracer.export_jsonl(
                os.path.join(directory, "spans.jsonl")
            ),
        }

    def dump_flight_record(
        self, reason: str, directory: Optional[str] = None
    ) -> Optional[str]:
        """Dump the flight-recorder ring as ``flightrec.json`` (per-rank
        suffixed files off process 0) next to the trace exports. Returns
        the path, or None without a directory — never raises (it runs on
        crash paths)."""
        directory = directory or self._trace_dir
        if not directory:
            return None
        try:
            import jax

            rank = jax.process_index()
        except Exception:  # pragma: no cover - defensive
            rank = 0
        name = "flightrec.json" if rank == 0 else f"flightrec_rank{rank}.json"
        path = self.flightrec.dump(os.path.join(directory, name), reason=reason)
        if path:
            n_records = float(len(self.flightrec.snapshot()))
            self.metrics.inc("flightrec/dumps")
            self.metrics.set_gauge("flightrec/records", n_records)
            logger.warning(f"flight recorder dumped to {path} ({reason})")
        return path
