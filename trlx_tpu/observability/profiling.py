"""Programmatic ``jax.profiler`` trace windows + step annotations.

A pod run can't afford an always-on profiler, but "attach a profiler for
steps 3-5" must not require a code change. The window comes from either:

- ``TRLX_TPU_PROFILE=steps:3-5,dir:/tmp/trace`` — an env var, so any
  launcher can arm a window without touching configs; or
- ``config.train.profile_dir`` — the pre-existing config knob, which keeps
  its historical window (steps 1-4).

While a window is open, the learn loop also wraps each unit of device work
in ``jax.profiler.StepTraceAnnotation`` so the trace viewer groups ops by
train/generate step.
"""

import os
from contextlib import nullcontext
from typing import Any, Optional, Tuple

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

PROFILE_ENV = "TRLX_TPU_PROFILE"


def parse_profile_spec(spec: str) -> Tuple[int, int, str]:
    """``"steps:3-5,dir:/tmp/x"`` → ``(3, 5, "/tmp/x")``.

    ``steps:N`` (single step) means ``N-N``; ``dir`` defaults to
    ``/tmp/trlx_tpu_profile``. Raises ``ValueError`` on a malformed spec.
    """
    start, stop, directory = None, None, "/tmp/trlx_tpu_profile"
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, value = part.partition(":")
        if key == "steps":
            lo, _, hi = value.partition("-")
            start = int(lo)
            stop = int(hi) if hi else start
        elif key == "dir":
            directory = value
        else:
            raise ValueError(f"unknown {PROFILE_ENV} field '{key}' in '{spec}'")
    if start is None:
        raise ValueError(f"{PROFILE_ENV} needs a steps:<a>-<b> field, got '{spec}'")
    if stop < start:
        raise ValueError(f"{PROFILE_ENV} steps window is inverted: '{spec}'")
    return start, stop, directory


class ProfileWindow:
    """Starts/stops one ``jax.profiler`` trace around a step interval.

    ``on_step_start(step)`` / ``on_step_end(step)`` bracket each unit of
    work with the trainer's *pre-increment* step index; the window traces
    steps ``start..stop`` inclusive. ``stop()`` is an idempotent final
    close for early-exit paths. A disabled window (no spec) is all no-ops.
    """

    def __init__(self, start: Optional[int], stop: Optional[int], directory: Optional[str]):
        self.start = start
        self.stop_step = stop
        self.directory = directory
        self.active = False
        self._done = False

    @classmethod
    def disabled(cls) -> "ProfileWindow":
        return cls(None, None, None)

    @classmethod
    def from_env(cls, config: Any = None) -> "ProfileWindow":
        spec = os.environ.get(PROFILE_ENV)
        if spec:
            try:
                start, stop, directory = parse_profile_spec(spec)
                return cls(start, stop, directory)
            except ValueError as e:
                logger.warning("ignoring malformed %s: %s", PROFILE_ENV, e)
        profile_dir = getattr(getattr(config, "train", None), "profile_dir", None)
        if profile_dir:
            # historical config behavior: trace the window after the first
            # warmup step (pre-increment steps 1..4)
            return cls(1, 4, profile_dir)
        return cls.disabled()

    @property
    def enabled(self) -> bool:
        return self.start is not None

    def on_step_start(self, step: int) -> None:
        if not self.enabled or self.active or self._done:
            return
        if self.start <= step <= self.stop_step:
            import jax

            logger.info(
                "profiler: starting trace at step %d (window %d-%d) -> %s",
                step, self.start, self.stop_step, self.directory,
            )
            jax.profiler.start_trace(self.directory)
            self.active = True

    def on_step_end(self, step: int) -> None:
        if self.active and step >= self.stop_step:
            self.stop()
            self._done = True

    def stop(self) -> None:
        if not self.active:
            return
        import jax

        jax.profiler.stop_trace()
        self.active = False
        logger.info("profiler: trace written to %s", self.directory)

    def step_annotation(self, name: str, step: int):
        """``StepTraceAnnotation`` context while the window is open (a
        no-op context otherwise, so the hot loop never pays for it)."""
        if not self.active:
            return nullcontext()
        import jax

        return jax.profiler.StepTraceAnnotation(name, step_num=step)
