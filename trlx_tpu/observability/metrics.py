"""Metrics registry (counters / gauges / histograms) + throughput & MFU math.

Every metric key follows the repo-wide ``namespace/name`` convention
(enforced by ``scripts/check_metric_names.py``). The registry is a plain
in-process sink: the trainer merges ``snapshot()`` into its per-step stats
dict, so everything flows through the existing ``Tracker`` stream (JSONL /
TensorBoard / W&B) with no new backend.

MFU here is *measured*, not estimated: the FLOP numerator comes from XLA's
``cost_analysis()`` of the **exact compiled program** the trainer runs (the
same machinery as ``trlx_tpu/perf.py`` — see ``perf.lowered_costs``), joined
against the device-fenced step time from the span tracer. ``cost_analysis``
reports *per-device* flops, so MFU divides by the per-device peak directly.

On hardware whose peak is unknown (CPU, exotic kinds), a nominal
``DEFAULT_PEAK_FLOPS`` (1 TFLOP/s) keeps ``throughput/mfu`` defined as a
run-over-run *relative* utilization index; set ``TRLX_TPU_PEAK_FLOPS`` (per
device) to make it absolute.
"""

import os
import threading
from typing import Any, Dict, List, Optional

# bf16 peak per chip — single source of truth (bench.py imports this table)
TPU_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}

# nominal per-device peak when the hardware is unknown (CPU test meshes):
# keeps throughput/mfu defined as a relative index rather than absent
DEFAULT_PEAK_FLOPS = 1e12


def device_peak_flops(device=None) -> float:
    """Per-device peak FLOP/s: ``TRLX_TPU_PEAK_FLOPS`` env override, else the
    known TPU table by ``device_kind``, else :data:`DEFAULT_PEAK_FLOPS`."""
    env = os.environ.get("TRLX_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    if device is None:
        try:
            import jax

            device = jax.local_devices()[0]
        except Exception:
            return DEFAULT_PEAK_FLOPS
    kind = getattr(device, "device_kind", "").lower()
    for key, val in TPU_PEAK_FLOPS.items():
        if key in kind:
            return val
    return DEFAULT_PEAK_FLOPS


def mfu(flops_per_device: float, step_time_s: float, peak_flops_per_device: float) -> float:
    """Model FLOP utilization of one device for one measured step.

    ``flops_per_device`` must be XLA ``cost_analysis`` flops (already
    per-device under SPMD), ``step_time_s`` a device-fenced wall time.
    """
    if step_time_s <= 0 or peak_flops_per_device <= 0:
        return 0.0
    return flops_per_device / step_time_s / peak_flops_per_device


class MetricsRegistry:
    """Thread-safe counters / gauges / histograms with a flat snapshot.

    - counter: monotonically accumulates (``recompile/train_step``);
    - gauge: last-write-wins (``memory/device_bytes_in_use``);
    - histogram: per-window observations, summarized at snapshot as
      ``name_mean`` / ``name_max`` / ``name_count`` and reset.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # resilience counters inc() from pipeline worker threads while the
        # learn loop snapshots: all mutations take the lock (enforced by
        # graftlint's lock-discipline pass, docs/STATIC_ANALYSIS.md)
        self._counters: Dict[str, float] = {}  # guarded-by: _lock
        self._gauges: Dict[str, float] = {}  # guarded-by: _lock
        self._hists: Dict[str, List[float]] = {}  # guarded-by: _lock
        # update listeners (the crash flight recorder): called on every
        # inc/set_gauge so resilience counters and cluster gauges land in
        # the forensic ring as they happen
        self._listeners: List[Any] = []  # guarded-by: _lock

    def add_listener(self, fn) -> None:
        """Subscribe to every counter/gauge write as ``fn(op, name, value)``
        — the crash flight recorder's tap."""
        with self._lock:
            self._listeners.append(fn)

    def _notify(self, op: str, name: str, value: float) -> None:
        # listeners run outside the lock and are never allowed to break
        # metric recording
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(op, name, value)
            except Exception:  # pragma: no cover - defensive
                pass

    def inc(self, name: str, value: float = 1.0) -> float:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value
            total = self._counters[name]
        self._notify("inc", name, total)
        return total

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)
        self._notify("gauge", name, float(value))

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._hists.setdefault(name, []).append(float(value))

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def snapshot(self, reset_histograms: bool = True) -> Dict[str, float]:
        """Flat ``namespace/name`` → value dict for the tracker stream."""
        with self._lock:
            out: Dict[str, float] = dict(self._counters)
            out.update(self._gauges)
            for name, values in self._hists.items():
                if not values:
                    continue
                out[f"{name}_mean"] = sum(values) / len(values)
                out[f"{name}_max"] = max(values)
                out[f"{name}_count"] = float(len(values))
            if reset_histograms:
                self._hists = {}
            return out


class ThroughputMeter:
    """Derives per-step throughput stats from fenced step times.

    ``step_stats`` returns the canonical keys the tracker stream carries:
    ``throughput/tokens_per_sec``, ``throughput/samples_per_sec``, and —
    when a program FLOP count is known — ``throughput/mfu`` plus
    ``throughput/flops_per_sec_per_device``. Running totals fold in so a
    final ``summary()`` reports whole-run averages.
    """

    def __init__(self, peak_flops_per_device: Optional[float] = None):
        self._peak = peak_flops_per_device
        self.total_time = 0.0
        self.total_tokens = 0
        self.total_samples = 0

    @property
    def peak(self) -> float:
        if self._peak is None:
            self._peak = device_peak_flops()
        return self._peak

    def step_stats(
        self,
        step_time_s: float,
        tokens: int = 0,
        samples: int = 0,
        flops_per_device: Optional[float] = None,
    ) -> Dict[str, float]:
        stats: Dict[str, float] = {}
        if step_time_s <= 0:
            return stats
        self.total_time += step_time_s
        self.total_tokens += tokens
        self.total_samples += samples
        if tokens:
            stats["throughput/tokens_per_sec"] = tokens / step_time_s
        if samples:
            stats["throughput/samples_per_sec"] = samples / step_time_s
        if flops_per_device is not None and flops_per_device > 0:
            stats["throughput/flops_per_sec_per_device"] = (
                flops_per_device / step_time_s
            )
            stats["throughput/mfu"] = mfu(flops_per_device, step_time_s, self.peak)
        return stats

    def summary(self) -> Dict[str, float]:
        if self.total_time <= 0:
            return {}
        out = {}
        if self.total_tokens:
            out["throughput/tokens_per_sec_avg"] = self.total_tokens / self.total_time
        if self.total_samples:
            out["throughput/samples_per_sec_avg"] = (
                self.total_samples / self.total_time
            )
        return out


def train_step_flops(jitted_fn, *args: Any) -> Optional[float]:
    """Per-device FLOPs of the exact compiled train step, via the same XLA
    ``cost_analysis`` path as ``trlx_tpu/perf.py``.

    Lowers ``jitted_fn`` with abstract (shape/dtype/sharding) twins of the
    live arguments (state, batch, and any trailing scalars) — no arrays are
    touched, and with the persistent compile cache on, the AOT compile
    dedupes against the call-path executable. Returns ``None`` (never
    raises) when the backend has no cost model or lowering fails; disable
    entirely with ``TRLX_TPU_MFU=0``.
    """
    if os.environ.get("TRLX_TPU_MFU", "1") == "0":
        return None
    try:
        import jax

        from trlx_tpu.perf import lowered_costs

        def abstract(tree):
            return jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=getattr(x, "sharding", None)
                ),
                tree,
            )

        costs = lowered_costs(jitted_fn.lower(*(abstract(a) for a in args)))
        flops = costs.get("flops", -1.0)
        return flops if flops > 0 else None
    except Exception:
        return None
