"""On-device distribution sketches of training dynamics.

The loss functions report masked *means* only (``policy/approx_kl``,
``policy/clipfrac``, mean ``ratio`` — ``trlx_tpu/models/ppo.py``), which is
exactly the wrong granularity for the failure modes that actually kill RLHF
runs: KL runaway lives in the ratio distribution's tails, entropy collapse in
its left edge, value-function divergence in the error distribution's spread —
all invisible in a mean until the run is already wrecked (the silent-failure
mode RLAX reports dominating large-scale TPU RL; PAPERS.md).

The sketch is a **fixed-bin masked histogram** computed *inside* the jitted
train step from stop-gradient'd intermediates the loss already materializes:

- fixed bins (``SKETCH_BINS`` over a per-quantity ``SKETCH_RANGES`` window,
  out-of-range values clamped into the edge bins — the edges double as
  "mass beyond the window" tail counters), so the array shape is static and
  the program never recompiles as the distribution moves;
- the counts pytree rides the existing stats fetch back to host — **zero
  new host syncs** — where :class:`DynamicsSummarizer` turns each histogram
  into ``dist/<name>_{p05,p50,p95}`` gauges (plus
  ``dist/ratio_outside_clip_frac``) for the tracker stream, and
  ``filter_non_scalars`` drops the raw arrays as before;
- every sketched quantity passes through ``stop_gradient`` and feeds nothing
  back into the objective, so the sketch-enabled step is **bit-identical**
  in loss and params to the sketch-free step (pinned by
  ``tests/test_health.py``).

Under gradient accumulation the train step *averages* stats over
microbatches, so the fetched counts are ``sum/accum`` — a uniform rescale
that leaves every percentile and mass fraction unchanged.

Emission is gated by ``method.dist_sketches`` (on by default); the host-side
summaries feed the windowed health detectors (``observability/health.py``).
Bins/ranges and the artifact formats: docs/OBSERVABILITY.md "Training
dynamics".
"""

import re
from typing import Any, Dict, Optional, Tuple

import numpy as np

SKETCH_BINS = 32

# Per-quantity histogram windows. Deliberately generous: healthy runs live
# well inside them, and a distribution escaping its window piles mass into
# the edge bins — which is itself the signal (p95 pegged at the window edge).
SKETCH_RANGES: Dict[str, Tuple[float, float]] = {
    "log_ratio": (-1.0, 1.0),  # new − old per-token logprob delta
    "kl": (0.0, 1.0),  # per-token k3 estimator vs the behavior policy
    "ref_kl": (0.0, 1.0),  # per-token k3 vs the frozen reference (rollout)
    "advantages": (-5.0, 5.0),  # whitened GAE / group-relative advantages
    "value_error": (-5.0, 5.0),  # value prediction − return
    "entropy": (0.0, 12.0),  # per-token policy entropy, nats (ln V ≈ 10.8)
    "reward_margin": (-10.0, 10.0),  # DPO chosen − rejected implicit reward
}

_HIST_KEY_RE = re.compile(r"^dist/(\w+)_hist$")


def sketch(x, mask=None, *, lo: float, hi: float, bins: int = SKETCH_BINS):
    """Masked fixed-bin histogram of ``x`` — pure JAX, trace-safe.

    Values are stop-gradient'd and clamped into ``[lo, hi)`` (the edge bins
    absorb the tails), masked-out positions contribute zero weight. Returns
    float32 counts of shape ``[bins]``.
    """
    import jax
    import jax.numpy as jnp

    x = jax.lax.stop_gradient(jnp.asarray(x).astype(jnp.float32))
    if mask is None:
        weights = jnp.ones(x.shape, jnp.float32)
    else:
        weights = jax.lax.stop_gradient(jnp.asarray(mask).astype(jnp.float32))
    scale = bins / (hi - lo)
    idx = jnp.clip(((x - lo) * scale).astype(jnp.int32), 0, bins - 1)
    counts = jnp.zeros((bins,), jnp.float32)
    return counts.at[idx.reshape(-1)].add(weights.reshape(-1))


def sketch_np(x, mask=None, *, lo: float, hi: float, bins: int = SKETCH_BINS):
    """Host (numpy) twin of :func:`sketch` — same bin math on already-fetched
    arrays. The rollout finalize stage uses it for the reference-KL sketch
    (the per-token ref logprobs only exist on host there)."""
    x = np.asarray(x, np.float32)
    weights = (
        np.ones(x.shape, np.float32)
        if mask is None
        else np.asarray(mask, np.float32)
    )
    scale = bins / (hi - lo)
    idx = np.clip(((x - lo) * scale).astype(np.int32), 0, bins - 1)
    counts = np.zeros((bins,), np.float32)
    np.add.at(counts, idx.reshape(-1), weights.reshape(-1))
    return counts


def loss_sketches(named: Dict[str, Tuple[Any, Any]]) -> Dict[str, Any]:
    """Sketch each ``name -> (values, mask)`` pair into the canonical
    ``dist/<name>_hist`` stats keys (ranges from :data:`SKETCH_RANGES`).
    The loss functions merge the result into their stats dict, so the counts
    ride the existing device→host stats fetch."""
    out = {}
    for name, (values, mask) in named.items():
        lo, hi = SKETCH_RANGES[name]
        out[f"dist/{name}_hist"] = sketch(values, mask, lo=lo, hi=hi)
    return out


def entropy_of_logits(logits):
    """Per-token policy entropy (nats) from ``[..., V]`` logits, computed in
    f32 under ``stop_gradient`` so sketching it perturbs nothing."""
    import jax

    logits = jax.lax.stop_gradient(logits.astype("float32"))
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -(jax.numpy.exp(logp) * logp).sum(axis=-1)


def hist_percentile(counts: np.ndarray, lo: float, hi: float, q: float) -> float:
    """Percentile ``q`` (0-100) from fixed-bin counts, linearly interpolated
    inside the containing bin. Caller guarantees ``counts.sum() > 0``."""
    counts = np.asarray(counts, np.float64)
    bins = counts.shape[0]
    width = (hi - lo) / bins
    cum = np.cumsum(counts)
    target = cum[-1] * (q / 100.0)
    i = int(np.searchsorted(cum, target))
    i = min(i, bins - 1)
    prev = cum[i - 1] if i > 0 else 0.0
    frac = (target - prev) / max(counts[i], 1e-12)
    return float(lo + (i + min(max(frac, 0.0), 1.0)) * width)


def hist_mass_outside(
    counts: np.ndarray, lo: float, hi: float, lower: float, upper: float
) -> float:
    """Fraction of histogram mass outside ``[lower, upper]``, with linear
    within-bin interpolation at the boundaries."""
    counts = np.asarray(counts, np.float64)
    total = counts.sum()
    if total <= 0:
        return 0.0
    bins = counts.shape[0]
    width = (hi - lo) / bins
    edges = lo + width * np.arange(bins + 1)
    # per-bin overlap fraction with [lower, upper]
    inside_lo = np.clip(edges[:-1], lower, upper)
    inside_hi = np.clip(edges[1:], lower, upper)
    inside_frac = np.clip((inside_hi - inside_lo) / width, 0.0, 1.0)
    inside_mass = float((counts * inside_frac).sum())
    return float(1.0 - inside_mass / total)


class DynamicsSummarizer:
    """Host-side collapse of the fetched ``dist/*_hist`` counts into scalar
    tracker gauges.

    One instance per trainer (``trainer.obs.dynamics``); the learn loop calls
    :meth:`summarize` on the host stats dict *before* ``filter_non_scalars``
    strips the raw arrays. Emits ``dist/<name>_p05|_p50|_p95`` per sketch,
    plus ``dist/ratio_outside_clip_frac`` — the fraction of per-token ratio
    mass beyond the PPO clip window ``[1−ε, 1+ε]``, the direct precursor of
    clipfrac saturation (a mean clipfrac of 0.3 can be one-third of tokens
    barely clipped or a bimodal ratio blowup; the tail mass tells them
    apart).
    """

    def __init__(self, cliprange: Optional[float] = None):
        self.cliprange = float(cliprange) if cliprange else None

    def summarize(self, host_stats: Dict[str, Any]) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for key, value in host_stats.items():
            m = _HIST_KEY_RE.match(key) if isinstance(key, str) else None
            if m is None:
                continue
            counts = np.asarray(value, np.float64).reshape(-1)
            if counts.sum() <= 0:  # empty mask — nothing to summarize
                continue
            name = m.group(1)
            lo, hi = SKETCH_RANGES.get(name, (0.0, 1.0))
            out[f"dist/{name}_p05"] = hist_percentile(counts, lo, hi, 5.0)
            out[f"dist/{name}_p50"] = hist_percentile(counts, lo, hi, 50.0)
            out[f"dist/{name}_p95"] = hist_percentile(counts, lo, hi, 95.0)
            if name == "log_ratio" and self.cliprange:
                lo_r = float(np.log(max(1.0 - self.cliprange, 1e-6)))
                hi_r = float(np.log(1.0 + self.cliprange))
                out["dist/ratio_outside_clip_frac"] = hist_mass_outside(
                    counts, lo, hi, lo_r, hi_r
                )
        return out
