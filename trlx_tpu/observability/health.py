"""Windowed RL health detectors over the metric stream.

The distribution sketches (``observability/dynamics.py``) put the *shape* of
training dynamics on the tracker stream; this module watches that stream and
turns it into a verdict. Each detector is a small windowed rule over recent
metric values — no model access, no device work — evaluated once per
optimizer step (:meth:`HealthMonitor.update`) and once per experience
collection (:meth:`HealthMonitor.observe_rollout`):

``kl_runaway``
    Rollout-measured KL vs the frozen reference (``policy/sqrt_kl``²) holds
    above ``KL_RUNAWAY_FACTOR ×`` the KL-controller target — the controller
    has lost the policy.
``entropy_collapse``
    ``dist/entropy_p50`` sits below ``ENTROPY_FLOOR`` nats for a full window
    — the policy has gone (near-)deterministic and exploration is dead.
``clipfrac_saturation``
    ``policy/clipfrac`` windowed mean above ``CLIPFRAC_SATURATION`` — most
    tokens are clipped, so the surrogate gradient no longer reflects the
    objective.
``value_ev_collapse``
    Explained variance ``1 − E[(v−R)²]/Var[R]`` of the value head goes
    negative for a full window — the critic is worse than predicting the
    mean return and GAE advantages are noise.
``reward_flatline``
    The per-collection reward mean stops moving entirely (std below
    ``REWARD_FLATLINE_STD`` over ``REWARD_FLATLINE_WINDOW`` collections) —
    reward hacking saturation or a dead reward fn.
``gen_canary``
    The engine-harvest repetition canary (``rollout/repetition_frac``) holds
    above ``REPEAT_FRAC_CEIL`` — degenerate looping generations.

Each detector publishes a ``health/<name>`` 0/1 gauge; ``health/verdict``
summarizes (0 = ok). The string verdict (``"ok"`` or the first tripped
detector) feeds the bench headline. A trip transition logs once per
detector, records a structured ``health`` flight-recorder event, and sets
:attr:`just_tripped` for exactly one step so the trainer can dump the flight
record and the offending batch (``triage/step<N>.npz`` — trainer/base.py).

The ``health_trip@step:N`` fault-plan kind (resilience/faults.py) forces a
trip via :meth:`force_trip`, exercising the full detector→triage path
deterministically in tier-1. Set ``TRLX_TPU_HEALTH=0`` to disable detectors
(gauges still publish as 0/ok). Thresholds are module constants, documented
in docs/OBSERVABILITY.md "Training dynamics".
"""

import logging
import os
from collections import deque
from typing import Any, Deque, Dict, Optional

import numpy as np

logger = logging.getLogger(__name__)

# Detector evaluation order; the first tripped one names the verdict.
DETECTORS = (
    "kl_runaway",
    "entropy_collapse",
    "clipfrac_saturation",
    "value_ev_collapse",
    "reward_flatline",
    "gen_canary",
)

DEFAULT_WINDOW = 8  # optimizer steps (override: TRLX_TPU_HEALTH_WINDOW)
KL_RUNAWAY_FACTOR = 4.0  # × controller target, sustained over ≥2 collections
ENTROPY_FLOOR = 0.05  # nats; ~0 ⇒ deterministic policy
CLIPFRAC_SATURATION = 0.9  # mean fraction of clipped tokens
EV_FLOOR = 0.0  # explained variance below this ⇒ critic useless
REWARD_FLATLINE_STD = 1e-6
REWARD_FLATLINE_WINDOW = 4  # experience collections
REPEAT_FRAC_CEIL = 0.8  # fraction of adjacent repeated response tokens


def _finite(value: Any) -> Optional[float]:
    try:
        f = float(value)
    except (TypeError, ValueError):
        return None
    return f if np.isfinite(f) else None


class HealthMonitor:
    """Stateful per-trainer monitor; lives on the observability bundle as
    ``trainer.obs.health``.

    ``metrics``/``flightrec`` are the shared :class:`MetricsRegistry` and
    :class:`FlightRecorder` (either may be None in bare unit tests);
    ``kl_target`` is the KL-controller setpoint (None disables
    ``kl_runaway``).
    """

    def __init__(
        self,
        metrics=None,
        flightrec=None,
        kl_target: Optional[float] = None,
        window: Optional[int] = None,
    ):
        self.metrics = metrics
        self.flightrec = flightrec
        self.kl_target = _finite(kl_target)
        if window is None:
            window = int(os.environ.get("TRLX_TPU_HEALTH_WINDOW", DEFAULT_WINDOW))
        self.window = max(int(window), 2)
        self.enabled = os.environ.get("TRLX_TPU_HEALTH", "1") != "0"
        self.verdict: str = "ok"
        #: Detector name for exactly one :meth:`update` call after a trip
        #: transition — the trainer's cue to dump flightrec + triage.
        self.just_tripped: Optional[str] = None
        self.trip_counts: Dict[str, int] = {name: 0 for name in DETECTORS}
        self._tripped: Dict[str, bool] = {name: False for name in DETECTORS}
        self._warned: set = set()
        self._forced: Optional[str] = None
        # Per-step windows (optimizer-step cadence).
        self._entropy: Deque[float] = deque(maxlen=self.window)
        self._clipfrac: Deque[float] = deque(maxlen=self.window)
        self._value_ev: Deque[float] = deque(maxlen=self.window)
        # Per-collection windows (experience-collection cadence).
        self._rollout_kl: Deque[float] = deque(maxlen=self.window)
        self._reward_mean: Deque[float] = deque(maxlen=REWARD_FLATLINE_WINDOW)
        self._repeat_frac: Deque[float] = deque(maxlen=self.window)

    # ------------------------------------------------------------------ feeds

    def observe_rollout(self, stats: Dict[str, Any]) -> None:
        """Fold one experience collection's stats into the rollout windows
        (called from ``make_experience``; all four collection paths funnel
        through it)."""
        sqrt_kl = _finite(stats.get("policy/sqrt_kl"))
        if sqrt_kl is not None:
            self._rollout_kl.append(sqrt_kl * sqrt_kl)
        mean = _finite(stats.get("exp_scores/mean"))
        if mean is not None:
            self._reward_mean.append(mean)
        rep = _finite(stats.get("rollout/repetition_frac"))
        if rep is not None:
            self._repeat_frac.append(rep)

    def force_trip(self, reason: str, step: Optional[int] = None) -> None:
        """Arm an injected trip (``health_trip`` fault kind); consumed by the
        next :meth:`update`, which reports verdict ``injected:<reason>`` and
        fires the same flightrec/triage path as an organic trip."""
        self._forced = f"injected:{reason}"
        logger.warning(
            "health: forced trip %r armed (step %s)", reason, step
        )

    # ------------------------------------------------------------ evaluation

    def _detect(self) -> Dict[str, bool]:
        full = self.window
        out = {name: False for name in DETECTORS}
        if not self.enabled:
            return out
        if self.kl_target and len(self._rollout_kl) >= 2:
            recent = list(self._rollout_kl)[-2:]
            out["kl_runaway"] = all(
                v > KL_RUNAWAY_FACTOR * self.kl_target for v in recent
            )
        if len(self._entropy) >= full:
            out["entropy_collapse"] = (
                float(np.mean(self._entropy)) < ENTROPY_FLOOR
            )
        if len(self._clipfrac) >= full:
            out["clipfrac_saturation"] = (
                float(np.mean(self._clipfrac)) > CLIPFRAC_SATURATION
            )
        if len(self._value_ev) >= full:
            out["value_ev_collapse"] = float(np.mean(self._value_ev)) < EV_FLOOR
        if len(self._reward_mean) >= REWARD_FLATLINE_WINDOW:
            out["reward_flatline"] = (
                float(np.std(self._reward_mean)) < REWARD_FLATLINE_STD
            )
        if len(self._repeat_frac) >= 2:
            recent = list(self._repeat_frac)[-2:]
            out["gen_canary"] = all(v > REPEAT_FRAC_CEIL for v in recent)
        return out

    def update(self, stats: Dict[str, Any], step: int) -> Dict[str, float]:
        """Fold one optimizer step's stats in, evaluate every detector, and
        publish gauges. Returns the ``health/*`` gauge dict so the caller can
        merge it into the same step's tracker line (the registry snapshot for
        this step was already taken)."""
        entropy = _finite(stats.get("dist/entropy_p50"))
        if entropy is not None:
            self._entropy.append(entropy)
        clipfrac = _finite(stats.get("policy/clipfrac"))
        if clipfrac is not None:
            self._clipfrac.append(clipfrac)
        verr = _finite(stats.get("values/values_error"))
        ret_std = _finite(stats.get("returns/std"))
        if verr is not None and ret_std is not None:
            self._value_ev.append(1.0 - verr / max(ret_std * ret_std, 1e-8))

        detections = self._detect()
        self.just_tripped = None
        verdict = "ok"
        for name in DETECTORS:
            hit = detections[name]
            if hit and not self._tripped[name]:
                self.just_tripped = name
                self.trip_counts[name] += 1
                if name not in self._warned:
                    self._warned.add(name)
                    logger.warning(
                        "health: detector %s tripped at step %d "
                        "(see docs/OBSERVABILITY.md 'Training dynamics')",
                        name,
                        step,
                    )
            self._tripped[name] = hit
            if hit and verdict == "ok":
                verdict = name
        if self._forced is not None:
            verdict = self._forced
            self.just_tripped = self._forced
            self._forced = None
        self.verdict = verdict

        gauges = {f"health/{name}": float(detections[name]) for name in DETECTORS}
        gauges["health/verdict"] = 0.0 if verdict == "ok" else 1.0
        if self.metrics is not None:
            for key, value in gauges.items():
                self.metrics.set_gauge(key, value)
        if self.just_tripped is not None and self.flightrec is not None:
            self.flightrec.record(
                "health",
                {
                    "step": step,
                    "verdict": verdict,
                    "tripped": self.just_tripped,
                    "detectors": {k: bool(v) for k, v in detections.items()},
                    "windows": {
                        "rollout_kl": list(self._rollout_kl),
                        "entropy_p50": list(self._entropy),
                        "clipfrac": list(self._clipfrac),
                        "value_ev": list(self._value_ev),
                        "reward_mean": list(self._reward_mean),
                        "repetition_frac": list(self._repeat_frac),
                    },
                },
            )
        return gauges
