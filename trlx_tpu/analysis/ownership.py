"""Ownership/lifecycle pass (GL80x): every manual acquire/release protocol
in the package — refcounted KV blocks, prefix-cache entry refs, spool
chunks, checkpoint staging, tracer spans, spawned threads — is released on
EVERY exit of the acquiring function, including exception paths.

**The registry.** Acquire/release pairs are declared by trailing comments
on the *defining* methods' ``def`` lines::

    def alloc(self, n: int) -> List[int]:  # acquires: kv-block-ref
        ...
    def release(self, blocks) -> List[int]:  # releases: kv-block-ref(arg)
        ...

The parenthesized handle spec says where the owned value lives at a CALL
site of the method:

- ``result`` (acquire default) — the call's return value; tracked when
  assigned to a plain local (``fresh = self._alloc_blocks(n)``). A bare
  expression statement discards the only handle — an immediate GL801.
- ``arg`` (release default) — the first positional argument
  (``self.allocator.release(blocks)`` releases ``blocks``).
- ``receiver`` — the object the method is called on (``t.join()``
  releases ``t``); only plain local receivers are tracked.
- ``object`` — ownership lives on the receiver object across calls
  (``PrefixCache.insert`` retains into the cache's own entry table); the
  registry documents the protocol, but per-function tracking is skipped.

``threading.Thread`` / ``multiprocessing.Process`` carry a built-in pair
(``start`` acquires / ``join`` releases, resource ``thread``) applied to
locals constructed from those classes in the same function.

Call sites resolve through receiver types, not bare names: ``self.m()``
via the class closure, annotated params (``allocator: BlockAllocator``),
locals assigned from a package-class constructor, and ``self.<attr>``
assigned from one anywhere in the class — so an unrelated ``d.get(...)``
never matches an annotated ``get``.

**The checks** (exception-edge model: ``callgraph.ExceptionFlow``):

- GL801 — an acquired handle is live at an exit: an early ``return`` or
  ``raise`` between acquire and release, or function end without release
  (the classic leaked block ref on an exception path). A ``try/finally``
  whose finalbody releases the handle covers every exit crossing the try;
  an acquire spelled as a ``with`` context expression is covered by
  ``__exit__``.
- GL802 — double release of one handle on a straight-line path.
- GL803 — a read of the handle after its release (the same local dataflow
  shape as the donation pass's read-after-donate; rebinding clears).
- GL804 — the handle is released only under a conditional with no
  error-path counterpart: an exit where the release *may* not have
  happened (``if ok: release(b)`` … ``return``).

Ownership transfer ends tracking (under-approximation, fewer findings):
storing the handle into a ``self.*`` attribute / any subscripted target,
returning or yielding it, aliasing it to another local, appending it to a
container (``self._threads.append(thread)``), or passing it to another
*package* function (the callee may assume ownership). The defining
methods themselves are exempt for their own resource — their bodies ARE
the protocol implementation.
"""

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from trlx_tpu.analysis.callgraph import (
    CallGraph,
    ExceptionFlow,
    FunctionInfo,
    THREAD_CONSTRUCTORS,
    attr_chain,
)
from trlx_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    LintPass,
    register_pass,
)

__all__ = ["OwnershipPass", "OwnershipRegistry"]

_ANNOT_RE = re.compile(
    r"#\s*(acquires|releases):\s*([A-Za-z_][A-Za-z0-9_\-]*)"
    r"\s*(?:\((arg|result|receiver|object)\))?"
)

# container-mutator names whose argument escapes into the container
_ESCAPE_MUTATORS = {
    "append", "extend", "add", "insert", "appendleft", "update",
    "setdefault", "put", "put_nowait",
}

Chain = Tuple[str, ...]


@dataclass
class ProtocolMethod:
    """One annotated acquire/release method."""

    fn: FunctionInfo
    role: str  # "acquires" | "releases"
    resource: str
    spec: str  # "result" | "arg" | "receiver" | "object"


@dataclass
class _Event:
    call: ast.Call
    role: str  # "acquire" | "release"
    resource: str
    spec: str
    handle: Optional[Chain]


@dataclass
class _Track:
    resource: str
    state: str  # "live" | "released" | "cond" | "covered"
    acquire_line: int
    release_line: int = 0

    def copy(self) -> "_Track":
        return _Track(self.resource, self.state, self.acquire_line, self.release_line)


class OwnershipRegistry:
    """The annotated acquire/release protocol methods, plus the receiver
    typing needed to match their call sites."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        # method name -> annotated methods with that name
        self.by_name: Dict[str, List[ProtocolMethod]] = {}
        # FunctionInfo.full -> its own annotations (defining-method exemption)
        self.own: Dict[str, List[ProtocolMethod]] = {}
        self._class_attr_types: Dict[str, Dict[str, str]] = {}
        self._collect()

    def _collect(self) -> None:
        for fn in self.graph.functions:
            node = fn.node
            if isinstance(node, ast.Lambda):
                continue
            body = getattr(node, "body", None)
            if not body:
                continue
            # the signature region: the def line up to (excluding) the first
            # body statement — docstring examples can never live here
            lines = fn.module.lines
            for lineno in range(node.lineno, max(body[0].lineno, node.lineno + 1)):
                if lineno - 1 >= len(lines):
                    break
                m = _ANNOT_RE.search(lines[lineno - 1])
                if not m:
                    continue
                role, resource, spec = m.group(1), m.group(2), m.group(3)
                if spec is None:
                    spec = "result" if role == "acquires" else "arg"
                pm = ProtocolMethod(fn, role, resource, spec)
                name = fn.qualname.rsplit(".", 1)[-1]
                self.by_name.setdefault(name, []).append(pm)
                self.own.setdefault(fn.full, []).append(pm)

    def own_resources(self, fn: FunctionInfo) -> Set[str]:
        return {pm.resource for pm in self.own.get(fn.full, ())}

    # -- receiver typing --------------------------------------------------

    def class_attr_types(self, class_full: str) -> Dict[str, str]:
        """attr -> class full (or "@thread") for ``self.<attr> = Cls(...)``
        assignments anywhere in the class."""
        cached = self._class_attr_types.get(class_full)
        if cached is not None:
            return cached
        out: Dict[str, str] = {}
        info = self.graph.classes.get(class_full)
        if info is not None:
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Assign) or not isinstance(
                    node.value, ast.Call
                ):
                    continue
                scope = self.graph.enclosing_function(info.module, node)
                ctor = self._ctor_class(node.value, scope, info.module)
                if ctor is None:
                    continue
                for t in node.targets:
                    chain = attr_chain(t)
                    if chain and len(chain) == 2 and chain[0] == "self":
                        out[chain[1]] = ctor
        self._class_attr_types[class_full] = out
        return out

    def _ctor_class(
        self, call: ast.Call, scope: Optional[FunctionInfo], mod
    ) -> Optional[str]:
        """Class full of a ``Cls(...)`` constructor call ("@thread" for the
        built-in thread/process constructors); None when unresolvable."""
        name = self.graph.external_name(call.func, scope, mod)
        if name in THREAD_CONSTRUCTORS:
            return "@thread"
        chain = attr_chain(call.func)
        if not chain:
            return None
        cls = self.graph._resolve_dotted_class(".".join(chain), mod)
        return cls.full if cls else None

    def local_types(self, fn: FunctionInfo) -> Dict[str, str]:
        """local name -> class full / "@thread", from annotated params
        (``allocator: BlockAllocator``) and constructor assignments."""
        out: Dict[str, str] = dict(fn.var_types)
        for node in fn.body_nodes():
            if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call
            ):
                continue
            ctor = self._ctor_class(node.value, fn, fn.module)
            if ctor is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = ctor
        return out

    # -- call-site classification ----------------------------------------

    def classify(
        self, call: ast.Call, fn: FunctionInfo, local_types: Dict[str, str]
    ) -> Optional[_Event]:
        chain = attr_chain(call.func)
        if chain is None:
            return None
        if len(chain) == 1:
            # bare call: an imported/module-level annotated function
            for callee in self.graph.resolve_callable(call.func, fn, fn.module):
                for pm in self.own.get(callee.full, ()):
                    return self._event(call, pm)
            return None
        method, receiver = chain[-1], tuple(chain[:-1])
        rtype = self._receiver_type(receiver, fn, local_types)
        # built-in thread pair: start/join on a local Thread/Process
        if rtype == "@thread":
            if method == "start":
                return _Event(call, "acquire", "thread", "receiver", receiver)
            if method == "join":
                return _Event(call, "release", "thread", "receiver", receiver)
            return None
        candidates = self.by_name.get(method)
        if not candidates:
            return None
        if receiver == ("self",):
            cls = self.graph._enclosing_class(fn)
            if cls is None:
                return None
            resolved = {m.full for m in self.graph.resolve_method(cls, method)}
            for pm in candidates:
                if pm.fn.full in resolved:
                    return self._event(call, pm)
            return None
        if rtype is None:
            return None
        if rtype in self.graph.classes:
            related = self.graph.related_classes(rtype)
        else:
            related = {rtype}
        for pm in candidates:
            if pm.fn.class_full and pm.fn.class_full in related:
                return self._event(call, pm)
        return None

    def _receiver_type(
        self, receiver: Chain, fn: FunctionInfo, local_types: Dict[str, str]
    ) -> Optional[str]:
        if len(receiver) == 1 and receiver[0] != "self":
            return local_types.get(receiver[0])
        if len(receiver) == 2 and receiver[0] == "self":
            cls = self.graph._enclosing_class(fn)
            if cls is None:
                return None
            for related in sorted(self.graph.related_classes(cls)):
                hit = self.class_attr_types(related).get(receiver[1])
                if hit:
                    return hit
        return None

    def _event(self, call: ast.Call, pm: ProtocolMethod) -> _Event:
        role = "acquire" if pm.role == "acquires" else "release"
        handle: Optional[Chain] = None
        if pm.spec == "arg" and call.args:
            chain = attr_chain(call.args[0])
            handle = tuple(chain) if chain else None
        elif pm.spec == "receiver":
            chain = attr_chain(call.func)
            if chain and len(chain) == 2 and chain[0] != "self":
                handle = (chain[0],)
        # "result" handles are derived from the enclosing statement shape
        return _Event(call, role, pm.resource, pm.spec, handle)


def _stmt_subnodes(stmt: ast.AST):
    """The statement's own expression nodes: nested defs/lambdas/classes
    and nested *statements* are skipped — compound bodies are walked as
    their own interpreter steps."""
    work: List[ast.AST] = [stmt]
    while work:
        node = work.pop()
        if node is not stmt and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            continue
        if node is not stmt and isinstance(node, ast.stmt):
            continue
        yield node
        work.extend(ast.iter_child_nodes(node))


def _mentions(node: ast.AST, handle: Chain) -> bool:
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Name)
            and isinstance(sub.ctx, ast.Load)
            and sub.id == handle[0]
        ):
            return True
    return False


@register_pass
class OwnershipPass(LintPass):
    name = "ownership"
    codes = ("GL801", "GL802", "GL803", "GL804")
    description = "acquired resources not released on every exit path"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        graph = ctx.callgraph
        registry = OwnershipRegistry(graph)
        findings: List[Finding] = []
        for fn in graph.functions:
            if isinstance(fn.node, ast.Lambda):
                continue
            findings.extend(_FunctionCheck(graph, registry, fn).run())
        findings.sort(key=lambda f: (f.path, f.line, f.code))
        return findings


class _FunctionCheck:
    """Per-function abstract interpretation: handle states over the
    statement tree, with try/finally and ``with`` exception edges."""

    def __init__(self, graph: CallGraph, registry: OwnershipRegistry, fn: FunctionInfo):
        self.graph = graph
        self.registry = registry
        self.fn = fn
        self.flow = ExceptionFlow(fn)
        self.with_calls = self.flow.with_context_calls()
        self.local_types = registry.local_types(fn)
        self.own = registry.own_resources(fn)
        self.findings: List[Finding] = []
        self.escaped: Set[Chain] = set()
        self._reported: Set[Tuple[str, Chain]] = set()

    def run(self) -> List[Finding]:
        body = self.fn.body_statements()
        if not body:
            return []
        state: Dict[Chain, _Track] = {}
        terminal = self._walk(body, state)
        if not terminal:
            line = getattr(body[-1], "end_lineno", body[-1].lineno)
            self._check_exit(state, line, "function end")
        return self.findings

    # -- findings ---------------------------------------------------------

    def _emit(self, code: str, line: int, handle: Chain, resource: str,
              message: str) -> None:
        key = (code, handle)
        if key in self._reported:
            return
        self._reported.add(key)
        self.findings.append(
            Finding(
                code=code,
                path=self.fn.module.relpath,
                line=line,
                symbol=self.fn.qualname,
                detail=f"{'.'.join(handle)}:{resource}",
                message=message,
            )
        )

    def _check_exit(self, state: Dict[Chain, _Track], line: int, where: str) -> None:
        for handle, track in sorted(state.items()):
            name = ".".join(handle)
            if track.state == "live":
                self._emit(
                    "GL801", line, handle, track.resource,
                    f"`{name}` holds a `{track.resource}` acquired on line "
                    f"{track.acquire_line} but is not released on this exit "
                    f"path ({where}) — release it in a finally, use a "
                    "with-block, or transfer ownership explicitly",
                )
            elif track.state == "cond":
                self._emit(
                    "GL804", line, handle, track.resource,
                    f"`{name}` (`{track.resource}`, acquired on line "
                    f"{track.acquire_line}) is released only under a "
                    "conditional with no counterpart on this exit path — "
                    "the other branch (or an error path) leaks it; release "
                    "unconditionally or in a finally",
                )

    # -- the walk ---------------------------------------------------------

    def _walk(self, stmts: List[ast.stmt], state: Dict[Chain, _Track]) -> bool:
        """Interpret ``stmts`` mutating ``state``; True when every path
        through the body leaves the function (return/raise)."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(stmt, ast.If):
                # the test expression runs first on every path — releases,
                # reads, and double releases spelled in the condition count
                # (_stmt_subnodes skips the nested branch statements)
                self._simple(stmt, state)
                s1 = _copy_state(state)
                t1 = self._walk(stmt.body, s1)
                s2 = _copy_state(state)
                t2 = self._walk(stmt.orelse, s2) if stmt.orelse else False
                self._merge(state, [(s1, t1), (s2, t2)])
                if t1 and t2:
                    return True
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                self._simple(stmt, state)
                s1 = _copy_state(state)
                self._walk(stmt.body, s1)
                # the body may run zero times: merge entry and one-iteration
                self._merge(state, [(s1, False), (_copy_state(state), False)])
                if stmt.orelse:
                    self._walk(stmt.orelse, state)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._simple(stmt, state)
                if self._walk(stmt.body, state):
                    return True
                continue
            if isinstance(stmt, ast.Try):
                if self._try(stmt, state):
                    return True
                continue
            self._simple(stmt, state)
            if isinstance(stmt, ast.Return):
                self._check_exit(state, stmt.lineno, "early return")
                return True
            if isinstance(stmt, ast.Raise):
                self._check_exit(state, stmt.lineno, "raise")
                return True
        return False

    def _merge(self, state: Dict[Chain, _Track],
               branches: List[Tuple[Dict[Chain, _Track], bool]]) -> None:
        """Merge branch outcomes back into ``state``. A terminal branch
        (its exits were already checked) contributes nothing; live+released
        across surviving branches becomes "cond" — the GL804 signal."""
        live_branches = [s for s, terminal in branches if not terminal]
        state.clear()
        if not live_branches:
            return
        keys: Set[Chain] = set()
        for s in live_branches:
            keys |= set(s)
        for h in sorted(keys):
            if h in self.escaped:
                continue  # transferred on some path: ownership moved
            tracks = [s[h].copy() for s in live_branches if h in s]
            states = {t.state for t in tracks}
            first = tracks[0]
            if len(tracks) < len(live_branches):
                # tracked on some paths only (acquired under a conditional):
                # a path still holding it keeps the leak check alive
                holding = [t for t in tracks if t.state in ("live", "cond", "covered")]
                if holding:
                    state[h] = holding[0]
            elif len(states) == 1:
                state[h] = first
            elif "live" in states or "cond" in states:
                merged = _Track(first.resource, "cond", first.acquire_line)
                for t in tracks:
                    merged.release_line = max(merged.release_line, t.release_line)
                state[h] = merged
            else:  # released/covered mixtures: the resource is safe
                state[h] = first

    def _try(self, stmt: ast.Try, state: Dict[Chain, _Track]) -> bool:
        # handles released in the finalbody are covered on EVERY exit
        # crossing the try — the exception edge the model exists for
        covered: List[Chain] = []
        final_releases = self._release_handles(stmt.finalbody)
        for handle, track in state.items():
            if track.state in ("live", "cond") and handle in final_releases:
                track.state = "covered"
                covered.append(handle)
        entry = _copy_state(state)
        t_body = self._walk(stmt.body, state)
        if stmt.orelse and not t_body:
            t_body = self._walk(stmt.orelse, state)
        # handlers run from the conservative ENTRY state: an acquire inside
        # the try may or may not have happened when the exception fired
        branches: List[Tuple[Dict[Chain, _Track], bool]] = [(state, t_body)]
        handlers_terminal = bool(stmt.handlers)
        for handler in stmt.handlers:
            hs = _copy_state(entry)
            ht = self._walk(handler.body, hs)
            handlers_terminal = handlers_terminal and ht
            branches.append((hs, ht))
        merged = _copy_state(state)
        self._merge(merged, branches)
        state.clear()
        state.update(merged)
        if stmt.finalbody:
            self._walk(stmt.finalbody, state)
            for handle in covered:
                track = state.get(handle)
                if track is not None and track.state == "covered":
                    track.state = "released"
        return t_body and handlers_terminal

    def _release_handles(self, stmts: List[ast.stmt]) -> Set[Chain]:
        out: Set[Chain] = set()
        for stmt in stmts:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                ev = self.registry.classify(node, self.fn, self.local_types)
                if ev is not None and ev.role == "release" and ev.handle:
                    out.add(ev.handle)
        return out

    # -- one simple statement (or a compound statement's header) ----------

    def _simple(self, stmt: ast.stmt, state: Dict[Chain, _Track]) -> None:
        calls: List[ast.Call] = []
        loads: List[Tuple[Chain, ast.Name]] = []
        for node in _stmt_subnodes(stmt):
            if isinstance(node, ast.Call):
                calls.append(node)
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                loads.append(((node.id,), node))
        events = [
            ev
            for ev in (
                self.registry.classify(c, self.fn, self.local_types) for c in calls
            )
            if ev is not None
        ]
        event_calls = {id(ev.call) for ev in events}
        # a release's own argument load is the release, not a read — a
        # repeated release must report GL802 alone, not GL802+GL803
        release_arg_nodes: Set[int] = set()
        for ev in events:
            if ev.role == "release":
                for sub in ast.walk(ev.call):
                    release_arg_nodes.add(id(sub))

        # GL803: reads of an already-released handle (checked against the
        # state BEFORE this statement's own releases apply)
        for chain, load_node in loads:
            if id(load_node) in release_arg_nodes:
                continue
            track = state.get(chain)
            if track is not None and track.state == "released":
                self._emit(
                    "GL803", stmt.lineno, chain, track.resource,
                    f"`{'.'.join(chain)}` is read after its "
                    f"`{track.resource}` was released on line "
                    f"{track.release_line} — a released resource may already "
                    "belong to another owner (read before releasing, or "
                    "re-acquire)",
                )

        # releases
        for ev in events:
            if ev.role != "release" or ev.handle is None:
                continue
            track = state.get(ev.handle)
            if track is None or ev.handle in self.escaped:
                continue
            if track.state == "released":
                self._emit(
                    "GL802", ev.call.lineno, ev.handle, track.resource,
                    f"`{'.'.join(ev.handle)}`'s `{track.resource}` is "
                    f"released twice (first on line {track.release_line}) — "
                    "a double release corrupts the refcount and can free a "
                    "resource another owner still shares",
                )
            else:
                track.state = "released"
                track.release_line = ev.call.lineno

        # transfers of tracked handles END tracking (the callee/container/
        # object owns the resource now)
        self._transfers(stmt, state, event_calls)

        # acquires
        for ev in events:
            if ev.role != "acquire" or ev.resource in self.own:
                continue
            if id(ev.call) in self.with_calls:
                continue  # with-context acquire: __exit__ covers every exit
            handle = ev.handle
            if ev.spec == "result":
                handle = self._result_handle(stmt, ev.call)
                if (
                    handle is None
                    and isinstance(stmt, ast.Expr)
                    and stmt.value is ev.call
                ):
                    self._emit(
                        "GL801", ev.call.lineno, ("<discarded>",), ev.resource,
                        f"the only handle to an acquired `{ev.resource}` is "
                        "discarded (bare expression statement) — nothing can "
                        "ever release it",
                    )
                    continue
            if handle is None or handle in self.escaped or len(handle) > 1:
                # unresolvable / escaped / attr-rooted handles are
                # object-scoped: out of per-function scope
                continue
            if self._finally_covers(ev):
                continue
            state[handle] = _Track(ev.resource, "live", ev.call.lineno)

    def _finally_covers(self, ev: _Event) -> bool:
        """A release of the same handle (or, for result-handles bound this
        statement, the same resource) in a finalbody enclosing the acquire
        covers every exit inside that try."""
        for t in self.flow.covering_finallys(ev.call):
            handles = self._release_handles(t.finalbody)
            if ev.handle is not None and ev.handle in handles:
                return True
            for stmt in t.finalbody:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    fev = self.registry.classify(node, self.fn, self.local_types)
                    if fev is not None and fev.role == "release" and (
                        fev.resource == ev.resource
                    ):
                        return True
        return False

    def _result_handle(self, stmt: ast.stmt, call: ast.Call) -> Optional[Chain]:
        if isinstance(stmt, ast.Assign) and stmt.value is call:
            if len(stmt.targets) == 1 and isinstance(stmt.targets[0], ast.Name):
                return (stmt.targets[0].id,)
        if isinstance(stmt, ast.AnnAssign) and stmt.value is call:
            if isinstance(stmt.target, ast.Name):
                return (stmt.target.id,)
        return None

    def _transfers(self, stmt: ast.stmt, state: Dict[Chain, _Track],
                   event_calls: Set[int]) -> None:
        # candidates include typed-but-not-yet-acquired locals: a Thread
        # appended to self._threads BEFORE .start() has already transferred
        # ownership — the later receiver-acquire must not start tracking
        tracked = [h for h, t in state.items() if t.state in ("live", "cond")]
        tracked += [
            (n,) for n in self.local_types
            if (n,) not in state and (n,) not in self.escaped
        ]
        moved: Set[Chain] = set()

        if isinstance(stmt, ast.Return) and stmt.value is not None:
            for h in tracked:
                if _mentions(stmt.value, h):
                    moved.add(h)
        for node in _stmt_subnodes(stmt):
            if isinstance(node, (ast.Yield, ast.YieldFrom)) and node.value is not None:
                for h in tracked:
                    if _mentions(node.value, h):
                        moved.add(h)
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            value = stmt.value
            for t in targets:
                if isinstance(t, ast.Name):
                    h = (t.id,)
                    # rebinding the handle name clears tracking (unless the
                    # value is this statement's own acquire, which re-tracks)
                    if h in state and not (
                        isinstance(value, ast.Call) and id(value) in event_calls
                    ):
                        del state[h]
                    # direct aliasing (`b = handle` / `pair = (h, x)`): the
                    # alias shares ownership — stop tracking. Reads through
                    # calls (`n = len(handle)`) do NOT transfer.
                    if value is not None:
                        for h2 in tracked:
                            if _alias_value(value, h2):
                                moved.add(h2)
                else:
                    # store into self.*, a subscript, tuple unpack: escapes
                    if value is not None:
                        for h in tracked:
                            if _mentions(value, h):
                                moved.add(h)
                    chain = attr_chain(t)
                    if chain and tuple(chain) in state:
                        del state[tuple(chain)]
        for node in _stmt_subnodes(stmt):
            if not isinstance(node, ast.Call) or id(node) in event_calls:
                continue
            is_escape_mutator = (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _ESCAPE_MUTATORS
            )
            is_package_callee = bool(
                self.graph.resolve_callable(node.func, self.fn, self.fn.module)
            )
            if not (is_escape_mutator or is_package_callee):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for h in tracked:
                    if _mentions(arg, h):
                        moved.add(h)
        for h in moved:
            state.pop(h, None)
            self.escaped.add(h)


def _alias_value(value: ast.AST, handle: Chain) -> bool:
    """Does an assignment VALUE alias the handle into a new binding? Bare
    names and tuple/list/binop compositions alias; a call result does not
    (``n = len(handle)`` is a read)."""
    if isinstance(value, ast.Name):
        return value.id == handle[0]
    if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
        return any(_alias_value(e, handle) for e in value.elts)
    if isinstance(value, ast.BinOp):
        return _alias_value(value.left, handle) or _alias_value(value.right, handle)
    if isinstance(value, ast.Starred):
        return _alias_value(value.value, handle)
    return False


def _copy_state(state: Dict[Chain, _Track]) -> Dict[Chain, _Track]:
    return {h: t.copy() for h, t in state.items()}
