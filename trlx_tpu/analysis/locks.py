"""Lock-discipline pass (GL4xx): attributes annotated ``# guarded-by:
<lock>`` must only be mutated inside ``with self.<lock>:``.

The annotation lives as a trailing comment on the attribute's assignment
line (typically in ``__init__``)::

    self._stats_lock = threading.Lock()
    self.stats = PipelineStats()  # guarded-by: _stats_lock

Enforcement covers every method of the class *except* the method that
declares the annotation (``__init__``-time construction happens before
the object escapes to another thread):

- GL401 — assignment / augmented assignment to ``self.<attr>`` (or any
  deeper chain, ``self.stats.host_work_s += dt``) outside a ``with
  self.<lock>:`` block;
- GL401 — mutating container-method call (``append``/``update``/...) on a
  guarded chain outside the lock;
- GL402 — the annotation names a lock attribute never assigned in the
  class (a typo'd lock name silently guards nothing).

Scope: annotation-driven, so any module can opt in; the threaded pipeline
modules (``pipeline/rollout_pipeline.py``) and the tracer
(``observability/tracing.py``) carry annotations today.
"""

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from trlx_tpu.analysis.callgraph import attr_chain
from trlx_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    LintPass,
    SourceModule,
    register_pass,
)

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_ATTR_ON_LINE_RE = re.compile(r"self\.([A-Za-z_][A-Za-z0-9_]*)\s*[:=]")

_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popleft", "appendleft",
    "clear", "update", "add", "discard", "setdefault", "sort", "reverse",
}


def _find_annotations(mod: SourceModule) -> List[Tuple[int, str, str]]:
    """(lineno, attr, lockname) for every ``# guarded-by:`` line."""
    out = []
    for lineno, line in enumerate(mod.lines, start=1):
        m = _GUARDED_RE.search(line)
        if not m:
            continue
        attr = _ATTR_ON_LINE_RE.search(line)
        if attr:
            out.append((lineno, attr.group(1), m.group(1)))
    return out


def _enclosing_class(mod: SourceModule, lineno: int) -> Optional[ast.ClassDef]:
    best: Optional[ast.ClassDef] = None
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= lineno <= end:
                if best is None or node.lineno > best.lineno:
                    best = node
    return best


def _holds_lock(mod: SourceModule, node: ast.AST, lockname: str) -> bool:
    for anc in mod.ancestors(node):
        if not isinstance(anc, (ast.With, ast.AsyncWith)):
            continue
        for item in anc.items:
            expr = item.context_expr
            # unwrap `with self._lock:` and `with lock.acquire_timeout(..)`
            chain = attr_chain(expr)
            if chain is None and isinstance(expr, ast.Call):
                chain = attr_chain(expr.func)
            if chain and lockname in chain:
                return True
    return False


def _method_of(cls: ast.ClassDef, node: ast.AST, mod: SourceModule) -> Optional[str]:
    for anc in [node] + list(mod.ancestors(node)):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for parent in mod.ancestors(anc):
                if parent is cls:
                    return anc.name
    return None


@register_pass
class LockDisciplinePass(LintPass):
    name = "lock-discipline"
    codes = ("GL401", "GL402")
    description = "guarded-by annotated state mutated outside its lock"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for mod in ctx.modules:
            annotations = _find_annotations(mod)
            if not annotations:
                continue
            mod.build_parents()
            # class → {attr: (lockname, declaring method)}
            guarded: Dict[ast.ClassDef, Dict[str, Tuple[str, Optional[str]]]] = {}
            for lineno, attr, lock in annotations:
                cls = _enclosing_class(mod, lineno)
                if cls is None:
                    continue
                decl_method = None
                for node in ast.walk(cls):
                    if (
                        isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign))
                        and node.lineno == lineno
                    ):
                        decl_method = _method_of(cls, node, mod)
                        break
                guarded.setdefault(cls, {})[attr] = (lock, decl_method)
            for cls, attrs in guarded.items():
                findings.extend(self._check_class(mod, cls, attrs))
        return findings

    def _check_class(
        self,
        mod: SourceModule,
        cls: ast.ClassDef,
        attrs: Dict[str, Tuple[str, Optional[str]]],
    ) -> List[Finding]:
        findings: List[Finding] = []
        # GL402: the named lock must exist as an attribute of the class
        assigned: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    chain = attr_chain(t)
                    if chain and len(chain) == 2 and chain[0] == "self":
                        assigned.add(chain[1])
        for attr, (lock, _decl) in sorted(attrs.items()):
            if lock not in assigned:
                findings.append(
                    Finding(
                        code="GL402",
                        path=mod.relpath,
                        line=cls.lineno,
                        symbol=cls.name,
                        detail=f"{attr}->{lock}",
                        message=f"`{attr}` is annotated guarded-by `{lock}`, "
                        f"but `{cls.name}` never assigns `self.{lock}` — "
                        "typo'd lock name guards nothing",
                    )
                )

        for node in ast.walk(cls):
            mutated = self._mutated_chain(node)
            if mutated is None:
                continue
            chain, verb = mutated
            if len(chain) < 2 or chain[0] != "self" or chain[1] not in attrs:
                continue
            attr = chain[1]
            lock, decl_method = attrs[attr]
            method = _method_of(cls, node, mod)
            if method is None or method == decl_method or method == "__init__":
                # construction before the object escapes needs no lock
                continue
            if _holds_lock(mod, node, lock):
                continue
            findings.append(
                Finding(
                    code="GL401",
                    path=mod.relpath,
                    line=node.lineno,
                    symbol=f"{cls.name}.{method}",
                    detail=f"{'.'.join(chain)}:{verb}",
                    message=f"`{'.'.join(chain)}` ({verb}) is guarded by "
                    f"`self.{lock}` but this mutation is outside any "
                    f"`with self.{lock}:` block",
                )
            )
        return findings

    def _mutated_chain(self, node: ast.AST) -> Optional[Tuple[List[str], str]]:
        """(chain, verb) when ``node`` mutates a self.* chain."""
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                base = t
                # subscript store mutates the container: self.d[k] = v
                while isinstance(base, ast.Subscript):
                    base = base.value
                chain = attr_chain(base)
                if chain and chain[0] == "self":
                    return chain, "assign"
            return None
        if isinstance(node, ast.AugAssign):
            base = node.target
            while isinstance(base, ast.Subscript):
                base = base.value
            chain = attr_chain(base)
            if chain and chain[0] == "self":
                return chain, "augassign"
            return None
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _MUTATORS:
                base = node.func.value
                while isinstance(base, ast.Subscript):
                    base = base.value
                chain = attr_chain(base)
                if chain and chain[0] == "self":
                    return chain, node.func.attr
        return None
