"""Lock-discipline (GL401/402) and thread-escape (GL403/404) passes.

**GL401/402 — annotation checking.** Attributes annotated ``# guarded-by:
<lock>`` must only be mutated inside ``with self.<lock>:``. The annotation
lives as a trailing comment on the attribute's assignment line (typically
in ``__init__``)::

    self._stats_lock = threading.Lock()
    self.stats = PipelineStats()  # guarded-by: _stats_lock

Enforcement covers every method of the class *except* the method that
declares the annotation (``__init__``-time construction happens before
the object escapes to another thread):

- GL401 — assignment / augmented assignment to ``self.<attr>`` (or any
  deeper chain, ``self.stats.host_work_s += dt``) outside a ``with
  self.<lock>:`` block;
- GL401 — mutating container-method call (``append``/``update``/...) on a
  guarded chain outside the lock;
- GL402 — the annotation names a lock attribute never assigned in the
  class (a typo'd lock name silently guards nothing).

**GL403/404 — escape detection.** GL401 only fires where an annotation
exists; the scarier bug is shared state *nobody annotated*. The escape
pass builds the **thread-root set** (``callgraph.ThreadRoot``: every
``threading.Thread(target=...)`` / ``multiprocessing.Process`` /
``.submit(...)`` target, resolved through closures, ``partial``, bound
methods, and factories) and computes which thread root(s) reach each
function. An instance attribute **written under one root and read or
written under another** is a data race unless a ``# guarded-by:`` lock is
held on both sides:

- GL403 — cross-thread shared attribute with **no** guarded-by annotation
  (one finding per class+attr, at the escaping write), or an annotated
  attribute **read** outside its lock in a function another root also
  reaches (unlocked cross-thread writes stay GL401's);
- GL404 — a thread-target closure rebinding an enclosing-scope local via
  ``nonlocal``/``global`` (`total += dt` from a worker races the
  submitting frame non-atomically).

Exemptions (kept deliberately narrow): ``__init__``/declaring-method
construction (pre-escape); attributes that *are* synchronization or
thread-safe-queue objects (``threading.Lock``/``Condition``/``Event``,
``queue.Queue`` — method calls on them are their contract, though
re-*assigning* one post-init still counts); methods/callables (not
state); attributes never written outside construction (immutable config).
"""

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from trlx_tpu.analysis.callgraph import CallGraph, FunctionInfo, attr_chain
from trlx_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    LintPass,
    SourceModule,
    register_pass,
)

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_ATTR_ON_LINE_RE = re.compile(r"self\.([A-Za-z_][A-Za-z0-9_]*)\s*[:=]")

_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popleft", "appendleft",
    "clear", "update", "add", "discard", "setdefault", "sort", "reverse",
}


def _find_annotations(mod: SourceModule) -> List[Tuple[int, str, str]]:
    """(lineno, attr, lockname) for every ``# guarded-by:`` line."""
    out = []
    for lineno, line in enumerate(mod.lines, start=1):
        m = _GUARDED_RE.search(line)
        if not m:
            continue
        attr = _ATTR_ON_LINE_RE.search(line)
        if attr:
            out.append((lineno, attr.group(1), m.group(1)))
    return out


def _enclosing_class(mod: SourceModule, lineno: int) -> Optional[ast.ClassDef]:
    best: Optional[ast.ClassDef] = None
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= lineno <= end:
                if best is None or node.lineno > best.lineno:
                    best = node
    return best


def _holds_lock(mod: SourceModule, node: ast.AST, lockname: str) -> bool:
    for anc in mod.ancestors(node):
        if not isinstance(anc, (ast.With, ast.AsyncWith)):
            continue
        for item in anc.items:
            expr = item.context_expr
            # unwrap `with self._lock:` and `with lock.acquire_timeout(..)`
            chain = attr_chain(expr)
            if chain is None and isinstance(expr, ast.Call):
                chain = attr_chain(expr.func)
            if chain and lockname in chain:
                return True
    return False


def _method_of(cls: ast.ClassDef, node: ast.AST, mod: SourceModule) -> Optional[str]:
    for anc in [node] + list(mod.ancestors(node)):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for parent in mod.ancestors(anc):
                if parent is cls:
                    return anc.name
    return None


def _mutated_chain(node: ast.AST) -> Optional[Tuple[List[str], str]]:
    """(chain, verb) when ``node`` mutates a self.* chain — shared by the
    annotation check (GL401) and the escape analysis (GL403)."""
    if isinstance(node, (ast.Assign, ast.AnnAssign)):
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            base = t
            # subscript store mutates the container: self.d[k] = v
            while isinstance(base, ast.Subscript):
                base = base.value
            chain = attr_chain(base)
            if chain and chain[0] == "self":
                return chain, "assign"
        return None
    if isinstance(node, ast.AugAssign):
        base = node.target
        while isinstance(base, ast.Subscript):
            base = base.value
        chain = attr_chain(base)
        if chain and chain[0] == "self":
            return chain, "augassign"
        return None
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in _MUTATORS:
            base = node.func.value
            while isinstance(base, ast.Subscript):
                base = base.value
            chain = attr_chain(base)
            if chain and chain[0] == "self":
                return chain, node.func.attr
    return None


def _guarded_attr_map(
    mod: SourceModule,
) -> Dict[ast.ClassDef, Dict[str, Tuple[str, Optional[str]]]]:
    """class node → {attr: (lockname, declaring method)} for every
    ``# guarded-by:`` annotation in ``mod``."""
    annotations = _find_annotations(mod)
    out: Dict[ast.ClassDef, Dict[str, Tuple[str, Optional[str]]]] = {}
    if not annotations:
        return out
    mod.build_parents()
    for lineno, attr, lock in annotations:
        cls = _enclosing_class(mod, lineno)
        if cls is None:
            continue
        decl_method = None
        for node in ast.walk(cls):
            if (
                isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign))
                and node.lineno == lineno
            ):
                decl_method = _method_of(cls, node, mod)
                break
        out.setdefault(cls, {})[attr] = (lock, decl_method)
    return out


@register_pass
class LockDisciplinePass(LintPass):
    name = "lock-discipline"
    codes = ("GL401", "GL402")
    description = "guarded-by annotated state mutated outside its lock"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for mod in ctx.modules:
            for cls, attrs in _guarded_attr_map(mod).items():
                findings.extend(self._check_class(mod, cls, attrs))
        return findings

    def _check_class(
        self,
        mod: SourceModule,
        cls: ast.ClassDef,
        attrs: Dict[str, Tuple[str, Optional[str]]],
    ) -> List[Finding]:
        findings: List[Finding] = []
        # GL402: the named lock must exist as an attribute of the class
        assigned: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    chain = attr_chain(t)
                    if chain and len(chain) == 2 and chain[0] == "self":
                        assigned.add(chain[1])
        for attr, (lock, _decl) in sorted(attrs.items()):
            if lock not in assigned:
                findings.append(
                    Finding(
                        code="GL402",
                        path=mod.relpath,
                        line=cls.lineno,
                        symbol=cls.name,
                        detail=f"{attr}->{lock}",
                        message=f"`{attr}` is annotated guarded-by `{lock}`, "
                        f"but `{cls.name}` never assigns `self.{lock}` — "
                        "typo'd lock name guards nothing",
                    )
                )

        for node in ast.walk(cls):
            mutated = _mutated_chain(node)
            if mutated is None:
                continue
            chain, verb = mutated
            if len(chain) < 2 or chain[0] != "self" or chain[1] not in attrs:
                continue
            attr = chain[1]
            lock, decl_method = attrs[attr]
            method = _method_of(cls, node, mod)
            if method is None or method == decl_method or method == "__init__":
                # construction before the object escapes needs no lock
                continue
            if _holds_lock(mod, node, lock):
                continue
            findings.append(
                Finding(
                    code="GL401",
                    path=mod.relpath,
                    line=node.lineno,
                    symbol=f"{cls.name}.{method}",
                    detail=f"{'.'.join(chain)}:{verb}",
                    message=f"`{'.'.join(chain)}` ({verb}) is guarded by "
                    f"`self.{lock}` but this mutation is outside any "
                    f"`with self.{lock}:` block",
                )
            )
        return findings

    # _mutated_chain is module-level (shared with ThreadEscapePass)


# ---------------------------------------------------------------------------
# thread-escape analysis (GL403/404)
# ---------------------------------------------------------------------------

# attribute values that are themselves synchronization primitives or
# thread-safe channels: method calls on them are their contract, not a race
# (re-ASSIGNING one after construction still counts as a write)
_SYNC_TYPES = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Event",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
    "threading.Barrier",
    "queue.Queue",
    "queue.SimpleQueue",
    "queue.LifoQueue",
    "queue.PriorityQueue",
}


@dataclass
class _Access:
    fn: "FunctionInfo"
    method: Optional[str]  # enclosing method name on the class (or None)
    node: ast.AST
    line: int
    kind: str  # "read" | verb from _mutated_chain
    roots: frozenset


@register_pass
class ThreadEscapePass(LintPass):
    name = "thread-escape"
    codes = ("GL403", "GL404")
    description = "cross-thread shared state without a lock held on both sides"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        graph = ctx.callgraph
        if not graph.thread_roots:
            return []
        findings: List[Finding] = []
        findings.extend(self._closure_rebinds(graph))
        findings.extend(self._attr_escapes(graph))
        return findings

    # -- GL404: thread closures rebinding enclosing-scope locals ---------

    def _closure_rebinds(self, graph: CallGraph) -> List[Finding]:
        out: List[Finding] = []
        seen: Set[str] = set()
        for root in graph.thread_roots:
            fn = root.fn
            if fn.parent is None or root.via == "Process":
                # module-level targets share no frame; a child *process*
                # shares no memory at all — rebinds there are local
                continue
            shared: Set[str] = set()
            for node in fn.body_nodes():
                if isinstance(node, (ast.Nonlocal, ast.Global)):
                    shared.update(node.names)
            if not shared:
                continue
            for node in fn.body_nodes():
                names: List[str] = []
                if isinstance(node, ast.Assign):
                    names = [
                        t.id for t in node.targets
                        if isinstance(t, ast.Name) and t.id in shared
                    ]
                elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name
                ):
                    if node.target.id in shared:
                        names = [node.target.id]
                for name in names:
                    key = f"{fn.full}:{name}"
                    if key in seen:
                        continue
                    seen.add(key)
                    out.append(
                        Finding(
                            code="GL404",
                            path=fn.module.relpath,
                            line=node.lineno,
                            symbol=fn.qualname,
                            detail=name,
                            message=f"thread-target closure `{fn.qualname}` "
                            f"rebinds enclosing-scope local `{name}` "
                            "(nonlocal/global): the rebind races the "
                            "submitting frame non-atomically — return the "
                            "value, or move it onto a locked attribute",
                        )
                    )
        return out

    # -- GL403: cross-root attribute escapes ------------------------------

    def _sync_attrs(self, graph: CallGraph, cls_node: ast.ClassDef,
                    mod: SourceModule) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(cls_node):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            scope = graph.enclosing_function(mod, node)
            name = graph.external_name(node.value.func, scope, mod)
            if name not in _SYNC_TYPES:
                continue
            for t in node.targets:
                chain = attr_chain(t)
                if chain and len(chain) == 2 and chain[0] == "self":
                    out.add(chain[1])
        return out

    def _enclosing_method(self, fn: "FunctionInfo") -> Optional[str]:
        cur = fn
        while cur is not None:
            if cur.class_full is not None:
                node = cur.node
                return getattr(node, "name", None)
            cur = cur.parent
        return None

    def _attr_escapes(self, graph: CallGraph) -> List[Finding]:
        membership = graph.thread_membership()
        # class full → guarded-attr annotations / sync-typed attrs
        guarded: Dict[str, Dict[str, Tuple[str, Optional[str]]]] = {}
        sync_attrs: Dict[str, Set[str]] = {}
        node_to_full = {info.node: full for full, info in graph.classes.items()}
        for mod in graph.ctx.modules:
            for cls_node, attrs in _guarded_attr_map(mod).items():
                full = node_to_full.get(cls_node)
                if full:
                    guarded[full] = attrs
        # accesses grouped per (class full, attr)
        accesses: Dict[Tuple[str, str], List[_Access]] = {}
        for fn in graph.functions:
            cls_full = fn.class_full or graph._enclosing_class(fn)
            if cls_full is None:
                continue
            method = self._enclosing_method(fn)
            if method == "__init__":
                # pre-escape construction; covers closures nested in
                # __init__ too (they run before the object is shared
                # in every pattern this package uses)
                continue
            roots = membership.get(fn.full, frozenset(("main",)))
            cls_info = graph.classes.get(cls_full)
            if cls_info is not None and cls_full not in sync_attrs:
                sync_attrs[cls_full] = self._sync_attrs(
                    graph, cls_info.node, cls_info.module
                )
            # param-default expressions (`def work(fn=self._x)`) evaluate in
            # the ENCLOSING frame at def time — they are not accesses made
            # by this thread of control
            args = getattr(fn.node, "args", None)
            default_ids: Set[int] = set()
            if args is not None:
                for d in list(args.defaults) + list(args.kw_defaults):
                    if d is not None:
                        default_ids.update(id(n) for n in ast.walk(d))
            write_bases: Set[int] = set()
            for node in fn.body_nodes():
                if id(node) in default_ids:
                    continue
                mutated = _mutated_chain(node)
                if mutated is None:
                    continue
                chain, verb = mutated
                if len(chain) < 2:
                    continue
                attr = chain[1]
                if verb in _MUTATORS and attr in sync_attrs.get(cls_full, ()):
                    continue  # method call on a sync primitive: its contract
                # the write target's own attribute loads (`self.stats` inside
                # `self.stats.x += dt`) are part of the write, not reads
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets = list(node.targets)
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    targets = [node.target]
                elif isinstance(node, ast.Call):
                    targets = [node.func]
                for t in targets:
                    for sub in ast.walk(t):
                        write_bases.add(id(sub))
                accesses.setdefault((cls_full, attr), []).append(
                    _Access(fn, method, node, node.lineno, verb, roots)
                )
            for node in fn.body_nodes():
                if not (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and id(node) not in write_bases
                    and id(node) not in default_ids
                ):
                    continue
                chain = attr_chain(node)
                if not chain or chain[0] != "self" or len(chain) < 2:
                    continue
                accesses.setdefault((cls_full, chain[1]), []).append(
                    _Access(fn, method, node, node.lineno, "read", roots)
                )
        return self._verdicts(graph, accesses, guarded)

    def _verdicts(
        self,
        graph: CallGraph,
        accesses: Dict[Tuple[str, str], List[_Access]],
        guarded: Dict[str, Dict[str, Tuple[str, Optional[str]]]],
    ) -> List[Finding]:
        findings: List[Finding] = []
        for (cls_full, attr), acc in sorted(accesses.items()):
            ann = guarded.get(cls_full, {}).get(attr)
            decl_method = ann[1] if ann else None
            live = [a for a in acc if a.method != decl_method]
            writes = [a for a in live if a.kind != "read"]
            if not writes:
                continue  # written only at construction: immutable config
            roots: Set[str] = set()
            for a in live:
                roots |= a.roots
            if len(roots) <= 1:
                continue  # single thread of control touches it
            cls_info = graph.classes.get(cls_full)
            cls_name = cls_info.name if cls_info else cls_full.rsplit(".", 1)[-1]
            if ann is None:
                w = min(writes, key=lambda a: a.line)
                write_roots = set()
                for a in writes:
                    write_roots |= a.roots
                findings.append(
                    Finding(
                        code="GL403",
                        path=w.fn.module.relpath,
                        line=w.line,
                        symbol=cls_name,
                        detail=attr,
                        message=f"`self.{attr}` is written under thread "
                        f"root(s) {sorted(write_roots)} and accessed under "
                        f"{sorted(roots - write_roots) or sorted(roots)} "
                        "with no `# guarded-by:` lock — cross-thread shared "
                        "state needs a lock (and the annotation) on both "
                        "sides, or must move onto a single thread",
                    )
                )
                continue
            lock = ann[0]
            seen_sites: Set[str] = set()
            for a in live:
                if a.kind != "read":
                    continue  # unlocked cross-thread WRITES are GL401's
                if _holds_lock(a.fn.module, a.node, lock):
                    continue
                site = f"{cls_name}.{a.method or a.fn.qualname}"
                if site in seen_sites:
                    continue
                seen_sites.add(site)
                findings.append(
                    Finding(
                        code="GL403",
                        path=a.fn.module.relpath,
                        line=a.line,
                        symbol=site,
                        detail=f"{attr}:read",
                        message=f"`self.{attr}` is shared across thread "
                        f"roots and guarded by `self.{lock}`, but this read "
                        f"is outside any `with self.{lock}:` block — "
                        "unlocked reads of cross-thread state see torn/"
                        "stale values",
                    )
                )
        return findings
