"""Intra-package call graph with jit-root reachability.

Static (AST-only) approximation of "which functions execute under a JAX
trace": every ``jax.jit`` / ``pjit`` / ``shard_map`` call or decorator whose
target resolves to a function defined in the package becomes a **root**,
and reachability over resolved intra-package edges marks the **traced**
set the jax-aware passes (``jax_passes.py``) inspect.

Resolution is deliberately heuristic — sound enough for a linter, never for
a compiler:

- lexical scoping: a called name resolves to a nested ``def`` in an
  enclosing function, then a module-level function, then an import;
- imports follow re-export chains (``trlx_tpu.parallel.make_mesh`` →
  ``trlx_tpu.parallel.mesh.make_mesh``) with a cycle guard;
- ``self.m()`` resolves to ``m`` on the enclosing class, its package
  superclasses, AND all package subclasses (over-approximation: the
  abstract ``loss_fn`` pulls every trainer's implementation into the
  traced set — exactly what the host-sync gate wants);
- annotated locals/params (``method: PPOConfig = ...``) resolve one more
  attribute hop (``method.loss`` → ``PPOConfig.loss``);
- a bare *reference* to a package function inside a traced body counts as
  an edge (functions passed to ``lax.while_loop``/``scan``/``vmap`` are
  traced even though never "called" syntactically).

Higher-order flow through parameters (``adjust_logits=...``) is not
tracked; the traced set is an under-approximation there and an
over-approximation for shared helpers — both documented in
docs/STATIC_ANALYSIS.md.
"""

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from trlx_tpu.analysis.core import AnalysisContext, SourceModule

__all__ = [
    "CallGraph",
    "ExceptionFlow",
    "FunctionInfo",
    "ClassInfo",
    "JitRoot",
    "ThreadRoot",
    "attr_chain",
]

# canonical dotted names that open a trace when called with a function
JIT_WRAPPERS = {
    "jax.jit",
    "jax.pjit",
    "pjit.pjit",
    "jax.experimental.pjit.pjit",
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
}
PARTIAL_NAMES = {"functools.partial", "partial"}

# canonical dotted names whose `target=` keyword starts a new thread of
# control (the thread-root constructors the escape analysis keys on)
THREAD_CONSTRUCTORS = {
    "threading.Thread",
    "multiprocessing.Process",
}

# stdlib HTTP handler base classes: a ``ThreadingHTTPServer`` runs every
# ``do_*`` method of its handler class on a per-connection thread, so
# handler methods are thread roots with no visible Thread(...) spawn —
# the serve frontend's "handlers only touch the submit surface" contract
# is exactly what the escape analysis must see them as (docs/SERVING.md)
HTTP_HANDLER_BASES = {
    "http.server.BaseHTTPRequestHandler",
    "http.server.SimpleHTTPRequestHandler",
    "http.server.CGIHTTPRequestHandler",
    "socketserver.BaseRequestHandler",
    "socketserver.StreamRequestHandler",
}


def attr_chain(node: ast.AST) -> Optional[List[str]]:
    """``a.b.c`` → ["a","b","c"]; None if any link isn't a plain Name/attr."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


@dataclass
class FunctionInfo:
    qualname: str  # module-relative, e.g. "Cls.m.<locals>.step_fn"
    full: str  # modname + "." + qualname
    module: SourceModule
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    class_full: Optional[str] = None  # innermost enclosing class
    parent: Optional["FunctionInfo"] = None
    # name → every nested def with that name (branches re-define `fn`)
    nested: Dict[str, List["FunctionInfo"]] = field(default_factory=dict)
    params: List[str] = field(default_factory=list)
    bound: Set[str] = field(default_factory=set)  # names assigned in scope
    var_types: Dict[str, str] = field(default_factory=dict)  # name -> class full

    def body_nodes(self) -> Iterator[ast.AST]:
        """Walk this function's own body, not descending into nested
        functions/lambdas/classes (their bodies belong to their own infos)."""
        stack: List[ast.AST] = list(ast.iter_child_nodes(self.node))
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
            ):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def body_statements(self) -> List[ast.stmt]:
        body = getattr(self.node, "body", None)
        return body if isinstance(body, list) else []


@dataclass
class ClassInfo:
    name: str
    full: str
    module: SourceModule
    node: ast.ClassDef
    base_names: List[str] = field(default_factory=list)  # resolved dotted
    methods: Dict[str, FunctionInfo] = field(default_factory=dict)
    # names assigned at class scope (fields, `from_dict = classmethod(...)`)
    class_attrs: Set[str] = field(default_factory=set)


@dataclass
class JitRoot:
    fn: FunctionInfo
    wrapper: str  # the jit-family name used
    module: SourceModule
    line: int
    static_argnums: Tuple[int, ...] = ()
    donate_argnums: Tuple[int, ...] = ()


@dataclass
class ThreadRoot:
    """One function that starts executing on its own thread of control:
    the ``target=`` of a ``threading.Thread``/``multiprocessing.Process``
    constructor, or the callable handed to an ``.submit(...)`` call
    (``concurrent.futures`` executors AND the package's own
    ``RolloutPipeline.submit`` — both run the callable on a worker
    thread). Resolution reuses the jit-root machinery: closures, bound
    ``self.m`` methods, ``partial(f, x)`` wrapping, factory returns, and
    lambdas all resolve (``resolve_callable_deep``). ``do_*`` methods of
    ``BaseHTTPRequestHandler`` subclasses are roots too (via
    "http-handler"): a ``ThreadingHTTPServer`` dispatches each request
    on a per-connection thread the stdlib spawns internally."""

    fn: FunctionInfo
    via: str  # "Thread" | "Process" | "submit" | "http-handler"
    module: SourceModule
    line: int


def _int_tuple(node: Optional[ast.AST]) -> Tuple[int, ...]:
    """Literal int / tuple-of-ints keyword value (else empty)."""
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    return ()


class _ModuleIndexer(ast.NodeVisitor):
    """One pass over a module: imports, functions (incl. nested + lambdas),
    classes and their methods."""

    def __init__(self, graph: "CallGraph", module: SourceModule):
        self.graph = graph
        self.module = module
        self.scope: List[FunctionInfo] = []
        self.classes: List[ClassInfo] = []

    # -- imports --------------------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            target = alias.name if alias.asname else alias.name.split(".")[0]
            self.graph.imports[self.module.modname][name] = target
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        base = node.module or ""
        if node.level:
            parts = self.module.modname.split(".")
            is_package = self.module.relpath.endswith("__init__.py")
            # level 1 from a package = the package itself; from a module =
            # its parent package; each further level pops one more
            drop = node.level - 1 if is_package else node.level
            parts = parts[: len(parts) - drop] if drop else parts
            base = ".".join(parts + ([node.module] if node.module else []))
        for alias in node.names:
            if alias.name == "*":
                continue
            name = alias.asname or alias.name
            self.graph.imports[self.module.modname][name] = f"{base}.{alias.name}"
        self.generic_visit(node)

    # -- scopes ---------------------------------------------------------

    def _qualname(self, name: str) -> str:
        if self.scope:
            return f"{self.scope[-1].qualname}.<locals>.{name}"
        if self.classes:
            return f"{self.classes[-1].name}.{name}"
        return name

    def _make_function(self, node, name: str) -> FunctionInfo:
        qual = self._qualname(name)
        info = FunctionInfo(
            qualname=qual,
            full=f"{self.module.modname}.{qual}",
            module=self.module,
            node=node,
            class_full=(
                self.classes[-1].full if self.classes and not self.scope else None
            ),
            parent=self.scope[-1] if self.scope else None,
        )
        args = node.args
        for a in (
            list(args.posonlyargs)
            + list(args.args)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            info.params.append(a.arg)
            info.bound.add(a.arg)
            ann = getattr(a, "annotation", None)
            cls_full = self.graph._annotation_class(ann, self.module)
            if cls_full:
                info.var_types[a.arg] = cls_full
        return info

    def _enter_function(self, node, name: str) -> None:
        info = self._make_function(node, name)
        if info.full in self.graph.function_index:
            # same-named defs in sibling branches (`def fn` per sampler
            # flavor): `full` must be unique for reachability bookkeeping;
            # `qualname` (the baseline symbol) intentionally stays shared
            k = 2
            while f"{info.full}#{k}" in self.graph.function_index:
                k += 1
            info.full = f"{info.full}#{k}"
        self.graph.functions.append(info)
        self.graph.function_index[info.full] = info
        if info.parent is not None:
            info.parent.nested.setdefault(name, []).append(info)
            info.parent.bound.add(name)
        elif self.classes:
            self.classes[-1].methods[name] = info
            self.classes[-1].class_attrs.add(name)
        else:
            self.graph.module_functions[self.module.modname][name] = info
        self.scope.append(info)
        # bind/type locals of the new scope
        for child in ast.walk(node):
            if isinstance(child, ast.Assign):
                for tgt in child.targets:
                    for sub in ast.walk(tgt):
                        if isinstance(sub, ast.Name):
                            info.bound.add(sub.id)
            elif isinstance(child, ast.AnnAssign) and isinstance(
                child.target, ast.Name
            ):
                info.bound.add(child.target.id)
                cls_full = self.graph._annotation_class(
                    child.annotation, self.module
                )
                if cls_full:
                    info.var_types[child.target.id] = cls_full
            elif isinstance(child, (ast.For, ast.AsyncFor)):
                for sub in ast.walk(child.target):
                    if isinstance(sub, ast.Name):
                        info.bound.add(sub.id)
            elif isinstance(child, ast.withitem) and child.optional_vars:
                for sub in ast.walk(child.optional_vars):
                    if isinstance(sub, ast.Name):
                        info.bound.add(sub.id)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_function(node, node.name)
        self.generic_visit(node)
        self.scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._enter_function(node, f"<lambda:L{node.lineno}>")
        self.generic_visit(node)
        self.scope.pop()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if self.scope:  # classes inside functions: skip (rare, test-only)
            self.generic_visit(node)
            return
        qual = f"{self.classes[-1].name}.{node.name}" if self.classes else node.name
        info = ClassInfo(
            name=node.name,
            full=f"{self.module.modname}.{qual}",
            module=self.module,
            node=node,
        )
        for base in node.bases:
            chain = attr_chain(base)
            if chain:
                info.base_names.append(".".join(chain))
        for stmt in node.body:
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        info.class_attrs.add(tgt.id)
            elif isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                info.class_attrs.add(stmt.target.id)
        self.graph.classes[info.full] = info
        self.graph.classes_by_name.setdefault(info.name, []).append(info)
        self.classes.append(info)
        self.generic_visit(node)
        self.classes.pop()


class CallGraph:
    def __init__(self, ctx: AnalysisContext):
        self.ctx = ctx
        self.functions: List[FunctionInfo] = []
        self.function_index: Dict[str, FunctionInfo] = {}
        self.module_functions: Dict[str, Dict[str, FunctionInfo]] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.classes_by_name: Dict[str, List[ClassInfo]] = {}
        self.imports: Dict[str, Dict[str, str]] = {}
        self.modules_by_name: Dict[str, SourceModule] = {}
        self.jit_roots: List[JitRoot] = []
        self.traced: Set[str] = set()  # FunctionInfo.full
        self.traced_via: Dict[str, str] = {}  # full -> root qualname
        self.thread_roots: List[ThreadRoot] = []
        self._thread_membership: Optional[Dict[str, FrozenSet[str]]] = None
        self._build()

    # -- construction ----------------------------------------------------

    def _build(self) -> None:
        for mod in self.ctx.modules:
            self.imports[mod.modname] = {}
            self.module_functions[mod.modname] = {}
            self.modules_by_name[mod.modname] = mod
        for mod in self.ctx.modules:
            _ModuleIndexer(self, mod).visit(mod.tree)
        self._link_classes()
        self._collect_jit_roots()
        self._mark_traced()
        self._collect_thread_roots()

    def _link_classes(self) -> None:
        self._supers: Dict[str, Set[str]] = {}
        self._subs: Dict[str, Set[str]] = {}
        for full, info in self.classes.items():
            for base in info.base_names:
                resolved = self._resolve_dotted_class(base, info.module)
                if resolved:
                    self._supers.setdefault(full, set()).add(resolved.full)
                    self._subs.setdefault(resolved.full, set()).add(full)

    def _closure(self, start: str, edges: Dict[str, Set[str]]) -> Set[str]:
        seen = {start}
        work = [start]
        while work:
            for nxt in edges.get(work.pop(), ()):
                if nxt not in seen:
                    seen.add(nxt)
                    work.append(nxt)
        return seen

    def related_classes(self, full: str) -> Set[str]:
        """The class plus its package super- and subclass closure — the
        candidate set for ``self.m()`` resolution."""
        return self._closure(full, self._supers) | self._closure(full, self._subs)

    # -- name resolution -------------------------------------------------

    def _resolve_import_target(
        self, target: str, _seen: Optional[Set[str]] = None
    ) -> Optional[FunctionInfo]:
        """A dotted import target → package function, following re-exports."""
        if target in self.function_index:
            return self.function_index[target]
        _seen = _seen or set()
        if target in _seen or "." not in target:
            return None
        _seen.add(target)
        modpath, name = target.rsplit(".", 1)
        fn = self.module_functions.get(modpath, {}).get(name)
        if fn is not None:
            return fn
        re_export = self.imports.get(modpath, {}).get(name)
        if re_export:
            return self._resolve_import_target(re_export, _seen)
        return None

    def _resolve_dotted_class(
        self, dotted: str, module: SourceModule, _seen: Optional[Set[str]] = None
    ) -> Optional[ClassInfo]:
        _seen = _seen or set()
        if dotted in _seen:
            return None
        _seen.add(dotted)
        if dotted in self.classes:
            return self.classes[dotted]
        head, _, rest = dotted.partition(".")
        target = self.imports.get(module.modname, {}).get(head)
        if target:
            full = f"{target}.{rest}" if rest else target
            if full in self.classes:
                return self.classes[full]
            if "." in full:
                modpath, name = full.rsplit(".", 1)
                re_export = self.imports.get(modpath, {}).get(name)
                if re_export:
                    mod = self.modules_by_name.get(modpath)
                    if mod is not None:
                        return self._resolve_dotted_class(re_export, mod, _seen)
                    if re_export in self.classes:
                        return self.classes[re_export]
        # same-module class
        local = f"{module.modname}.{dotted}"
        return self.classes.get(local)

    def _annotation_class(
        self, ann: Optional[ast.AST], module: SourceModule
    ) -> Optional[str]:
        if ann is None:
            return None
        chain = attr_chain(ann)
        if not chain:
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                cls = self._resolve_dotted_class(ann.value, module)
                return cls.full if cls else None
            return None
        cls = self._resolve_dotted_class(".".join(chain), module)
        return cls.full if cls else None

    def external_name(
        self, expr: ast.AST, scope: Optional[FunctionInfo], module: SourceModule
    ) -> Optional[str]:
        """Canonical dotted name of ``expr`` when its root is an imported
        module/name (``jnp.asarray`` → "jax.numpy.asarray"); None when the
        root is a local variable or unknown."""
        chain = attr_chain(expr)
        if not chain:
            return None
        root = chain[0]
        fn = scope
        while fn is not None:
            if root in fn.bound:
                return None  # a local variable, not an import
            fn = fn.parent
        target = self.imports.get(module.modname, {}).get(root)
        if target is None:
            # builtins (print/float/...) and module-level names
            return ".".join(chain) if len(chain) >= 1 else None
        return ".".join([target] + chain[1:])

    def resolve_name(
        self, name: str, scope: Optional[FunctionInfo], module: SourceModule
    ) -> List[FunctionInfo]:
        fn = scope
        while fn is not None:
            if name in fn.nested:
                return list(fn.nested[name])
            if name in fn.bound:
                return []  # shadowed by a non-function local
            fn = fn.parent
        mod_fn = self.module_functions.get(module.modname, {}).get(name)
        if mod_fn is not None:
            return [mod_fn]
        target = self.imports.get(module.modname, {}).get(name)
        if target:
            resolved = self._resolve_import_target(target)
            return [resolved] if resolved else []
        return []

    def returned_functions(self, fn: FunctionInfo) -> List[FunctionInfo]:
        """Nested defs a factory function returns (``def ring(...): ...;
        return ring``) — one extra hop for ``f = factory(); jax.jit(f)``."""
        out: List[FunctionInfo] = []
        for node in fn.body_nodes():
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            if isinstance(node.value, ast.Name):
                out.extend(fn.nested.get(node.value.id, []))
            elif isinstance(node.value, ast.Lambda):
                for cand in self.functions:
                    if cand.module is fn.module and cand.node is node.value:
                        out.append(cand)
        return out

    def resolve_callable_deep(
        self, expr: ast.AST, scope: Optional[FunctionInfo], module: SourceModule
    ) -> List[FunctionInfo]:
        """`resolve_callable` plus two jit-site-only hops: unwrap
        ``partial(f, ...)`` and follow ``name = factory(...)`` to the
        factory's returned nested defs."""
        if (
            isinstance(expr, ast.Call)
            and self.external_name(expr.func, scope, module) in PARTIAL_NAMES
            and expr.args
        ):
            return self.resolve_callable_deep(expr.args[0], scope, module)
        direct = self.resolve_callable(expr, scope, module)
        if direct:
            return direct
        if isinstance(expr, ast.Name) and scope is not None:
            out: List[FunctionInfo] = []
            look = scope
            while look is not None:
                for node in look.body_nodes():
                    if not isinstance(node, ast.Assign):
                        continue
                    if not any(
                        isinstance(t, ast.Name) and t.id == expr.id
                        for t in node.targets
                    ):
                        continue
                    value = node.value
                    if (
                        isinstance(value, ast.Call)
                        and self.external_name(value.func, look, module)
                        in PARTIAL_NAMES
                        and value.args
                    ):
                        out.extend(
                            self.resolve_callable_deep(value.args[0], look, module)
                        )
                    elif isinstance(value, ast.Call):
                        for factory in self.resolve_callable(
                            value.func, look, module
                        ):
                            out.extend(self.returned_functions(factory))
                if out:
                    return out
                look = look.parent
        return []

    def resolve_method(self, class_full: str, method: str) -> List[FunctionInfo]:
        out = []
        for full in sorted(self.related_classes(class_full)):
            info = self.classes.get(full)
            if info and method in info.methods:
                out.append(info.methods[method])
        return out

    def resolve_callable(
        self, expr: ast.AST, scope: Optional[FunctionInfo], module: SourceModule
    ) -> List[FunctionInfo]:
        """Package-internal candidates for a call/reference expression."""
        if isinstance(expr, ast.Name):
            return self.resolve_name(expr.id, scope, module)
        chain = attr_chain(expr)
        if not chain:
            return []
        if chain[0] == "self" and scope is not None and len(chain) == 2:
            cls = self._enclosing_class(scope)
            if cls:
                return self.resolve_method(cls, chain[1])
            return []
        if len(chain) == 2 and scope is not None:
            # annotated local: method.loss with method: PPOConfig
            fn = scope
            while fn is not None:
                cls_full = fn.var_types.get(chain[0])
                if cls_full:
                    return self.resolve_method(cls_full, chain[1])
                if chain[0] in fn.bound:
                    break
                fn = fn.parent
        # module-alias chain: stats.whiten with `import ... as stats`
        root_target = None
        fn = scope
        shadowed = False
        while fn is not None:
            if chain[0] in fn.bound:
                shadowed = True
                break
            fn = fn.parent
        if not shadowed:
            root_target = self.imports.get(module.modname, {}).get(chain[0])
        if root_target:
            resolved = self._resolve_import_target(
                ".".join([root_target] + chain[1:])
            )
            return [resolved] if resolved else []
        return []

    def _enclosing_class(self, scope: FunctionInfo) -> Optional[str]:
        fn = scope
        while fn is not None:
            if fn.class_full:
                return fn.class_full
            fn = fn.parent
        return None

    # -- jit roots & reachability ----------------------------------------

    def is_jit_name(self, dotted: Optional[str]) -> bool:
        return dotted in JIT_WRAPPERS

    def _jit_kwargs(self, call: ast.Call) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        static = donate = ()
        for kw in call.keywords:
            if kw.arg in ("static_argnums", "static_argnames"):
                static = _int_tuple(kw.value) or (-1,)
            if kw.arg == "donate_argnums":
                donate = _int_tuple(kw.value)
        return static, donate

    def enclosing_function(
        self, module: SourceModule, node: ast.AST
    ) -> Optional[FunctionInfo]:
        """Innermost FunctionInfo whose own body contains ``node``."""
        module.build_parents()
        cur = module.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                for fn in self.functions:
                    if fn.module is module and fn.node is cur:
                        return fn
                return None
            cur = module.parents.get(cur)
        return None

    def _add_root(
        self,
        fn: FunctionInfo,
        wrapper: str,
        module: SourceModule,
        line: int,
        static: Tuple[int, ...],
        donate: Tuple[int, ...],
    ) -> None:
        self.jit_roots.append(
            JitRoot(fn, wrapper, module, line, static, donate)
        )

    def _collect_jit_roots(self) -> None:
        for mod in self.ctx.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._roots_from_decorators(mod, node)
                if not isinstance(node, ast.Call):
                    continue
                scope = self.enclosing_function(mod, node)
                name = self.external_name(node.func, scope, mod)
                if not self.is_jit_name(name):
                    continue
                if not node.args:
                    continue
                target = node.args[0]
                static, donate = self._jit_kwargs(node)
                if isinstance(target, ast.Lambda):
                    for fn in self.functions:
                        if fn.module is mod and fn.node is target:
                            self._add_root(fn, name, mod, node.lineno, static, donate)
                    continue
                for fn in self.resolve_callable_deep(target, scope, mod):
                    self._add_root(fn, name, mod, node.lineno, static, donate)

    def _roots_from_decorators(self, mod: SourceModule, node) -> None:
        for dec in node.decorator_list:
            scope = self.enclosing_function(mod, node)
            target = dec
            static = donate = ()
            if isinstance(dec, ast.Call):
                fname = self.external_name(dec.func, scope, mod)
                if fname in PARTIAL_NAMES and dec.args:
                    inner = dec.args[0]
                    if self.is_jit_name(self.external_name(inner, scope, mod)):
                        static, donate = self._jit_kwargs(dec)
                        target = inner
                    else:
                        continue
                elif self.is_jit_name(fname):
                    static, donate = self._jit_kwargs(dec)
                    target = dec.func
                else:
                    continue
            name = self.external_name(target, scope, mod)
            if not self.is_jit_name(name):
                continue
            for fn in self.functions:
                if fn.module is mod and fn.node is node:
                    self._add_root(fn, name, mod, node.lineno, static, donate)

    def edges(self, fn: FunctionInfo) -> List[FunctionInfo]:
        """Resolved intra-package callees + referenced package functions."""
        out: List[FunctionInfo] = []
        seen: Set[str] = set()
        for node in fn.body_nodes():
            exprs: List[ast.AST] = []
            if isinstance(node, ast.Call):
                exprs.append(node.func)
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                exprs.append(node)
            for expr in exprs:
                for callee in self.resolve_callable(expr, fn, fn.module):
                    if callee.full not in seen:
                        seen.add(callee.full)
                        out.append(callee)
        # nested defs referenced by name count via the Name rule above;
        # decorator-jitted nested defs are roots on their own
        return out

    def reach_from(self, roots: List[FunctionInfo]) -> Dict[str, str]:
        """``FunctionInfo.full`` → root qualname for every function reachable
        from ``roots`` over the same edges jit tracing uses: resolved calls,
        bare package-function references (while_loop/scan/vmap bodies), and
        nested defs/lambdas. The generic engine behind jit-root tracing and
        the determinism pass's bit-equivalence-critical root set."""
        via: Dict[str, str] = {}
        work: List[FunctionInfo] = []
        for root in roots:
            if root.full not in via:
                via[root.full] = root.qualname
                work.append(root)
        while work:
            fn = work.pop()
            v = via[fn.full]
            callees = list(self.edges(fn))
            # nested defs/lambdas of reached code are part of the region even
            # when only ever passed by reference (while_loop/scan/vmap args)
            for group in fn.nested.values():
                callees.extend(group)
            for callee in callees:
                if callee.full not in via:
                    via[callee.full] = v
                    work.append(callee)
        return via

    def resolve_root_names(self, patterns) -> List[FunctionInfo]:
        """FunctionInfos matching registry patterns: a dotted pattern
        (``FileExperienceQueue.put``) matches the exact qualname or a
        ``.``-suffix of it; a bare name (``make_experience``) matches every
        function/method with that name, in any class. Used by passes that
        declare root sets by name (``analysis/determinism.py``)."""
        out: List[FunctionInfo] = []
        seen: Set[str] = set()
        for fn in self.functions:
            last = fn.qualname.rsplit(".", 1)[-1]
            for pat in patterns:
                if "." in pat:
                    hit = fn.qualname == pat or fn.qualname.endswith("." + pat)
                else:
                    hit = last == pat
                if hit and fn.full not in seen:
                    seen.add(fn.full)
                    out.append(fn)
                    break
        return out

    def _mark_traced(self) -> None:
        self.traced_via = self.reach_from([r.fn for r in self.jit_roots])
        self.traced = set(self.traced_via)

    def traced_functions(self) -> List[FunctionInfo]:
        return [fn for fn in self.functions if fn.full in self.traced]

    # -- thread roots & per-root reachability -----------------------------

    def _resolve_thread_target(
        self, expr: ast.AST, scope: Optional[FunctionInfo], mod: SourceModule
    ) -> List[FunctionInfo]:
        if isinstance(expr, ast.Lambda):
            return [fn for fn in self.functions if fn.module is mod and fn.node is expr]
        return self.resolve_callable_deep(expr, scope, mod)

    def _collect_thread_roots(self) -> None:
        seen: Set[Tuple[str, str]] = set()  # (full, via): one root per pair
        for mod in self.ctx.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                scope = self.enclosing_function(mod, node)
                target: Optional[ast.AST] = None
                via = None
                name = self.external_name(node.func, scope, mod)
                if name in THREAD_CONSTRUCTORS:
                    via = name.rsplit(".", 1)[-1]
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = kw.value
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "submit"
                    and node.args
                ):
                    # executor.submit(f, ...) / pipe.submit(work): the first
                    # positional arg runs on a worker thread
                    via = "submit"
                    target = node.args[0]
                if target is None:
                    continue
                for fn in self._resolve_thread_target(target, scope, mod):
                    if (fn.full, via) in seen:
                        continue
                    seen.add((fn.full, via))
                    self.thread_roots.append(
                        ThreadRoot(fn=fn, via=via, module=mod, line=node.lineno)
                    )
        # HTTP handler classes: each request's do_* dispatch runs on a
        # ThreadingHTTPServer per-connection thread — no Thread(...) call
        # exists to discover, the spawn is inside the stdlib
        for full in sorted(self.classes):
            if not self._is_http_handler(full):
                continue
            info = self.classes[full]
            for mname in sorted(info.methods):
                if not mname.startswith("do_"):
                    continue
                fn = info.methods[mname]
                if (fn.full, "http-handler") in seen:
                    continue
                seen.add((fn.full, "http-handler"))
                self.thread_roots.append(
                    ThreadRoot(
                        fn=fn,
                        via="http-handler",
                        module=info.module,
                        line=fn.node.lineno,
                    )
                )

    def _is_http_handler(self, class_full: str) -> bool:
        """Does ``class_full`` (or any package superclass of it) extend a
        stdlib HTTP/socketserver request-handler base?"""
        for full in self._closure(class_full, self._supers):
            info = self.classes.get(full)
            if info is None:
                continue
            for base in info.base_names:
                head, _, rest = base.partition(".")
                target = self.imports.get(info.module.modname, {}).get(head)
                canonical = (
                    (f"{target}.{rest}" if rest else target)
                    if target
                    else base
                )
                if canonical in HTTP_HANDLER_BASES:
                    return True
        return False

    def thread_membership(self) -> Dict[str, FrozenSet[str]]:
        """``FunctionInfo.full`` → the set of thread-root labels (root
        ``FunctionInfo.full``\\ s, plus the implicit ``"main"``) whose
        execution can reach the function. Functions not reachable from any
        spawned-thread root belong to ``"main"`` alone; a thread-reachable
        function that main-side code ALSO calls carries ``"main"`` *and*
        its thread labels, so a shared helper's accesses count on both
        sides of the escape check (a stats accumulator touched by the
        trainer loop and an actor worker is cross-thread, not
        worker-private).

        Reachability follows the same edges as jit-root tracing (resolved
        calls, bare function references, nested defs), so a thread target
        that fans out through ``self.m()`` dispatch or factory closures is
        followed the same way a jitted root is.
        """
        if self._thread_membership is not None:
            return self._thread_membership

        def reach(fn: FunctionInfo, seen: Set[str], skip: Set[str]) -> None:
            work = [fn]
            seen.add(fn.full)
            while work:
                cur = work.pop()
                callees = list(self.edges(cur))
                for group in cur.nested.values():
                    callees.extend(group)
                for callee in callees:
                    if callee.full not in seen and callee.full not in skip:
                        seen.add(callee.full)
                        work.append(callee)

        membership: Dict[str, Set[str]] = {}
        thread_reachable: Set[str] = set()
        root_fulls = {r.fn.full for r in self.thread_roots}
        for root in self.thread_roots:
            seen: Set[str] = set()
            reach(root.fn, seen, set())
            thread_reachable |= seen
            for full in seen:
                membership.setdefault(full, set()).add(root.fn.full)
        # main reaches everything not exclusively behind a spawn point:
        # BFS from every function outside the thread-reachable set re-adds
        # "main" to shared helpers main-side code also calls. The BFS never
        # descends INTO a thread-root function: the spawning frame holds a
        # bare reference to its target (`Thread(target=work)` is a Name
        # edge), and a spawn is not a main-side execution of the body.
        main_seen: Set[str] = set()
        for fn in self.functions:
            if fn.full not in thread_reachable and fn.full not in main_seen:
                reach(fn, main_seen, root_fulls)
        out: Dict[str, FrozenSet[str]] = {}
        for fn in self.functions:
            roots = set(membership.get(fn.full, ()))
            if fn.full in main_seen or not roots:
                roots.add("main")
            out[fn.full] = frozenset(roots)
        self._thread_membership = out
        return out


# ---------------------------------------------------------------------------
# exception-edge modeling (the ownership/lifecycle pass, analysis/ownership.py)
# ---------------------------------------------------------------------------


class ExceptionFlow:
    """Structural exception-edge facts for one function body.

    Python has two constructs that guarantee cleanup on EVERY exit —
    normal fall-through, early ``return``, and a raising statement:
    ``try/finally`` (the finalbody runs on all three) and ``with`` (the
    context manager's ``__exit__`` runs on all three). The ownership pass
    treats a resource released inside a covering finalbody — or acquired
    as a ``with`` context expression — as release-covered on all exits;
    everything else must be proven released path-by-path.
    """

    def __init__(self, fn: FunctionInfo):
        self.fn = fn
        self.fn.module.build_parents()

    def covering_finallys(self, node: ast.AST) -> List[ast.Try]:
        """Innermost-first ``try`` statements (within this function) whose
        TRY BODY contains ``node`` and which carry a ``finally`` — the
        finalbodies that execute on every exception edge crossing
        ``node``'s position. Handler and finalbody positions themselves are
        NOT covered (an exception there escapes the same try)."""
        out: List[ast.Try] = []
        mod = self.fn.module
        cur: Optional[ast.AST] = node
        while cur is not None and cur is not self.fn.node:
            parent = mod.parents.get(cur)
            if (
                isinstance(parent, ast.Try)
                and parent.finalbody
                and cur in parent.body
            ):
                out.append(parent)
            cur = parent
        return out

    def in_excepthandler(self, node: ast.AST) -> bool:
        """Is ``node`` inside an ``except`` handler body of this function?
        Releases there cover only the exception edge, not the normal path —
        the pass must not treat them as the main-path release."""
        mod = self.fn.module
        cur: Optional[ast.AST] = node
        while cur is not None and cur is not self.fn.node:
            if isinstance(cur, ast.ExceptHandler):
                return True
            cur = mod.parents.get(cur)
        return False

    def with_context_calls(self) -> Set[int]:
        """``id()`` of every Call node used as a ``with`` context expression
        in this function's own body — an acquire spelled that way is
        release-covered by the context manager's ``__exit__`` on all
        exits (``with tracer.span(...):``)."""
        out: Set[int] = set()
        for node in self.fn.body_nodes():
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call):
                    out.add(id(expr))
        return out
