"""``python -m trlx_tpu.analysis`` — the graftlint CLI (core.main)."""

import sys

from trlx_tpu.analysis.core import main

if __name__ == "__main__":
    sys.exit(main())
