"""The jax-aware passes: host-sync-in-traced-code (GL1xx), recompile
hazards (GL2xx), donation safety (GL3xx). All three share the
``callgraph.CallGraph`` jit-root reachability.

Code catalog (docs/STATIC_ANALYSIS.md):

- GL101 ``.item()`` inside jit-reachable code
- GL102 ``float()/int()/bool()`` on an array-valued expression in traced code
- GL103 ``np.asarray``/``np.array`` in traced code (host transfer / trace break)
- GL104 ``jax.device_get`` in traced code
- GL105 ``print`` in traced code (host callback per trace, silent sync)
- GL106 tracker/metrics publish call in traced code
- GL201 jitted closure captures shape-derived Python values (per-shape
  silent recompile; intentional shape-bucket caches get baselined)
- GL202 ``jax.jit``/``pjit`` called inside a loop (fresh executable per
  iteration: the jit cache keys on function object identity)
- GL203 jitted function uses a parameter as a Python shape/loop bound
  without ``static_argnums``
- GL204 ``jax.jit(lambda ...)`` in function scope (a fresh lambda object
  per call defeats the jit cache)
- GL301 read of a variable after it was passed in a donated position
  (donated buffers may alias the outputs — reads see garbage)
"""

import ast
from typing import Dict, List, Sequence, Set, Tuple

from trlx_tpu.analysis.callgraph import (
    CallGraph,
    FunctionInfo,
    attr_chain,
)
from trlx_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    LintPass,
    register_pass,
)

# array-producing method names: a float()/int()/bool() around one of these
# is a device scalar forced to host
_ARRAY_METHODS = {
    "sum", "mean", "max", "min", "prod", "any", "all", "dot", "norm",
    "astype", "squeeze", "reshape",
}
_HOST_CONVERTERS = {"float", "int", "bool"}


def _unparse(node: ast.AST, limit: int = 60) -> str:
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover - very old nodes
        text = "<expr>"
    return text if len(text) <= limit else text[: limit - 1] + "…"


def _builtin_unshadowed(
    graph: CallGraph, name: str, fn: FunctionInfo
) -> bool:
    scope = fn
    while scope is not None:
        if name in scope.bound:
            return False
        scope = scope.parent
    return name not in graph.imports.get(fn.module.modname, {})


def _contains_shape_access(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim", "size"):
            return True
    return False


def _looks_array_valued(graph: CallGraph, node: ast.AST, fn: FunctionInfo) -> bool:
    """Heuristic: the expression produces a device array (a jnp/jax call or
    an array-method call somewhere inside). Shape arithmetic is excluded —
    ``int(x.shape[1])`` is static, not a sync."""
    if _contains_shape_access(node):
        return False
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        name = graph.external_name(sub.func, fn, fn.module)
        if name and (name.startswith("jax.") or name.startswith("jnp.")):
            return True
        if isinstance(sub.func, ast.Attribute) and sub.func.attr in _ARRAY_METHODS:
            return True
    return False


@register_pass
class HostSyncPass(LintPass):
    name = "host-sync"
    codes = ("GL101", "GL102", "GL103", "GL104", "GL105", "GL106")
    description = "host round-trips inside jit-reachable code"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        graph = ctx.callgraph
        findings: List[Finding] = []
        for fn in graph.traced_functions():
            via = graph.traced_via.get(fn.full, "?")
            for node in fn.body_nodes():
                if not isinstance(node, ast.Call):
                    continue
                findings.extend(self._check_call(graph, fn, node, via))
        return findings

    def _check_call(
        self, graph: CallGraph, fn: FunctionInfo, node: ast.Call, via: str
    ) -> List[Finding]:
        out: List[Finding] = []

        def emit(code: str, detail: str, message: str) -> None:
            out.append(
                Finding(
                    code=code,
                    path=fn.module.relpath,
                    line=node.lineno,
                    symbol=fn.qualname,
                    detail=detail,
                    message=f"{message} inside jit-reachable code "
                    f"(traced via root `{via}`)",
                )
            )

        func = node.func
        if isinstance(func, ast.Attribute) and func.attr == "item" and not node.args:
            emit("GL101", ".item", f"`{_unparse(func)}()` forces a device→host sync")
            return out
        if isinstance(func, ast.Name) and func.id in _HOST_CONVERTERS:
            if (
                node.args
                and _builtin_unshadowed(graph, func.id, fn)
                and _looks_array_valued(graph, node.args[0], fn)
            ):
                emit(
                    "GL102",
                    f"{func.id}()",
                    f"`{func.id}()` on an array value concretizes the tracer "
                    "(host sync / ConcretizationError)",
                )
            return out
        name = graph.external_name(func, fn, fn.module)
        if name in ("numpy.asarray", "numpy.array"):
            emit(
                "GL103",
                name.split(".", 1)[1],
                f"`{_unparse(func)}` pulls the traced value to host "
                "(use jnp, or hoist to the host stage)",
            )
        elif name == "jax.device_get":
            emit("GL104", "device_get", "`jax.device_get` is a blocking host fetch")
        elif isinstance(func, ast.Name) and func.id == "print":
            if _builtin_unshadowed(graph, "print", fn):
                emit(
                    "GL105",
                    "print",
                    "`print` in traced code runs at trace time only (or "
                    "syncs via callback) — use jax.debug.print or hoist",
                )
        else:
            chain = attr_chain(func)
            if chain and any("tracker" in part for part in chain[:-1]):
                emit(
                    "GL106",
                    ".".join(chain),
                    f"tracker call `{_unparse(func)}` publishes from traced "
                    "code — trackers are host-side, log from the learn loop",
                )
        return out


# ---------------------------------------------------------------------------
# recompile hazards
# ---------------------------------------------------------------------------


def _rhs_is_shape_derived(node: ast.AST) -> bool:
    """RHS mentions ``.shape``/``len()`` or a name carrying "shape" — the
    classic per-shape constant that forks compilations when it changes."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim"):
            return True
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Name)
            and sub.func.id == "len"
        ):
            return True
        if isinstance(sub, ast.Name) and "shape" in sub.id.lower():
            return True
    return False


@register_pass
class RecompileHazardPass(LintPass):
    name = "recompile-hazard"
    codes = ("GL201", "GL202", "GL203", "GL204")
    description = "patterns that silently fork XLA compilations"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        graph = ctx.callgraph
        findings: List[Finding] = []
        findings.extend(self._jit_in_loop_and_lambda(graph))
        for root in graph.jit_roots:
            findings.extend(self._closure_hazards(graph, root))
            findings.extend(self._static_argnum_hazards(graph, root))
        # one finding per key (a fn jitted at 2 sites reports once)
        seen: Set[str] = set()
        unique = []
        for f in findings:
            if f.key not in seen:
                seen.add(f.key)
                unique.append(f)
        return unique

    def _jit_in_loop_and_lambda(self, graph: CallGraph) -> List[Finding]:
        out: List[Finding] = []
        for mod in graph.ctx.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                scope = graph.enclosing_function(mod, node)
                name = graph.external_name(node.func, scope, mod)
                if not graph.is_jit_name(name):
                    continue
                symbol = scope.qualname if scope else "-"
                in_loop = any(
                    isinstance(anc, (ast.For, ast.While))
                    for anc in mod.ancestors(node)
                )
                if in_loop:
                    out.append(
                        Finding(
                            code="GL202",
                            path=mod.relpath,
                            line=node.lineno,
                            symbol=symbol,
                            detail=name.rsplit(".", 1)[-1],
                            message=f"`{name}` called inside a loop: the jit "
                            "cache keys on function identity, so every "
                            "iteration may compile a fresh executable — "
                            "hoist the jit out of the loop",
                        )
                    )
                if (
                    node.args
                    and isinstance(node.args[0], ast.Lambda)
                    and scope is not None
                ):
                    out.append(
                        Finding(
                            code="GL204",
                            path=mod.relpath,
                            line=node.lineno,
                            symbol=symbol,
                            detail="lambda",
                            message=f"`{name}(lambda ...)` in function scope: "
                            "a fresh lambda object per call defeats the jit "
                            "cache (recompile every invocation) — name the "
                            "function once",
                        )
                    )
        return out

    def _closure_hazards(self, graph: CallGraph, root) -> List[Finding]:
        fn = root.fn
        if fn.parent is None:
            return []  # module-level function: captures are module constants
        free_shape_derived: List[str] = []
        loads = {
            sub.id
            for sub in ast.walk(fn.node)
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
        }
        for name in sorted(loads - fn.bound):
            # find the binding scope and how the name is assigned there
            scope = fn.parent
            while scope is not None and name not in scope.bound:
                scope = scope.parent
            if scope is None or name in scope.nested:
                continue
            if name in scope.params:
                if "shape" in name.lower():
                    free_shape_derived.append(name)
                continue
            for node in scope.body_nodes():
                if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == name
                    or isinstance(t, ast.Tuple)
                    and any(
                        isinstance(e, ast.Name) and e.id == name for e in t.elts
                    )
                    for t in node.targets
                ):
                    if _rhs_is_shape_derived(node.value):
                        free_shape_derived.append(name)
                        break
        if not free_shape_derived:
            return []
        names = ",".join(sorted(set(free_shape_derived)))
        return [
            Finding(
                code="GL201",
                path=fn.module.relpath,
                line=getattr(fn.node, "lineno", root.line),
                symbol=fn.qualname,
                detail=names,
                message=f"jitted closure captures shape-derived Python "
                f"value(s) `{names}`: every new shape silently compiles a "
                "new program — key a program cache on them (and baseline "
                "it) or pass them as static_argnums",
            )
        ]

    def _static_argnum_hazards(self, graph: CallGraph, root) -> List[Finding]:
        fn = root.fn
        if root.static_argnums:
            return []
        hazards: List[str] = []
        params = set(fn.params[1:] if fn.class_full else fn.params)
        for node in fn.body_nodes():
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "range"
                and node.args
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in params
            ):
                hazards.append(node.args[0].id)
        if not hazards:
            return []
        names = ",".join(sorted(set(hazards)))
        return [
            Finding(
                code="GL203",
                path=fn.module.relpath,
                line=getattr(fn.node, "lineno", root.line),
                symbol=fn.qualname,
                detail=names,
                message=f"jitted function drives `range()` with parameter(s) "
                f"`{names}` but the jit call has no static_argnums: the "
                "value is traced, so Python iteration fails or retraces — "
                "mark it static",
            )
        ]


# ---------------------------------------------------------------------------
# donation safety
# ---------------------------------------------------------------------------


def _flatten_targets(stmt: ast.stmt) -> List[Tuple[str, ...]]:
    """Assignment-target chains of a statement: ``self.state, x = ...`` →
    [("self","state"), ("x",)]."""
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)) and stmt.target is not None:
        targets = [stmt.target]
    out: List[Tuple[str, ...]] = []
    work = list(targets)
    while work:
        t = work.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            work.extend(t.elts)
            continue
        chain = attr_chain(t)
        if chain:
            out.append(tuple(chain))
    return out


def _linear_statements(fn: FunctionInfo) -> List[ast.stmt]:
    """The function's statements in source order, control-flow bodies
    flattened (if/else/loop/with/try bodies inline; nested defs excluded)."""
    out: List[ast.stmt] = []

    def walk(body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            out.append(stmt)
            for name in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, name, None)
                if isinstance(sub, list):
                    walk(sub)
            for handler in getattr(stmt, "handlers", []) or []:
                walk(handler.body)

    walk(fn.body_statements())
    return out


def _stmt_load_chains(stmt: ast.stmt) -> List[Tuple[Tuple[str, ...], int]]:
    """(chain, lineno) of every Name/attribute *load* in the statement,
    excluding nested function bodies."""
    out: List[Tuple[Tuple[str, ...], int]] = []
    skip_bodies: List[ast.AST] = []
    work: List[ast.AST] = [stmt]
    while work:
        node = work.pop()
        if node is not stmt and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        # only the *sub-statements'* own expressions matter; bodies are
        # visited as their own statements by _linear_statements
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                continue
            work.append(child)
        if isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
            chain = attr_chain(node)
            if chain:
                out.append((tuple(chain), node.lineno))
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            out.append(((node.id,), node.lineno))
    return out


@register_pass
class DonationSafetyPass(LintPass):
    name = "donation-safety"
    codes = ("GL301",)
    description = "reads of buffers already donated to a jitted call"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        graph = ctx.callgraph
        self._factories = self._donating_factories(graph)
        self._attrs = self._donating_attrs(graph)
        self._module_vars: Dict[Tuple[str, str], Tuple[int, ...]] = {}
        for mod in ctx.modules:
            for stmt in mod.tree.body:
                if not isinstance(stmt, ast.Assign):
                    continue
                donate = self._jit_donate(graph, stmt.value, None, mod)
                if not donate:
                    continue
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self._module_vars[(mod.modname, t.id)] = donate
        findings: List[Finding] = []
        for fn in graph.functions:
            if isinstance(fn.node, ast.Lambda):
                continue
            findings.extend(self._check_function(graph, fn))
        return findings

    # -- which callables donate -----------------------------------------

    def _jit_donate(self, graph: CallGraph, node: ast.AST, scope, mod) -> Tuple[int, ...]:
        """donate_argnums of a ``jax.jit(...)`` expression (else ())."""
        if not isinstance(node, ast.Call):
            return ()
        if not graph.is_jit_name(graph.external_name(node.func, scope, mod)):
            return ()
        _, donate = graph._jit_kwargs(node)
        return donate

    def _local_donators(
        self, graph: CallGraph, fn: FunctionInfo
    ) -> Dict[str, Tuple[int, ...]]:
        out: Dict[str, Tuple[int, ...]] = {}
        for stmt in _linear_statements(fn):
            if not isinstance(stmt, ast.Assign):
                continue
            donate = self._jit_donate(graph, stmt.value, fn, fn.module)
            if not donate:
                continue
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = donate
        return out

    def _donating_factories(self, graph: CallGraph) -> Dict[str, Tuple[int, ...]]:
        """FunctionInfo.full → argnums, for functions whose return value is
        a donating jitted callable."""
        out: Dict[str, Tuple[int, ...]] = {}
        for fn in graph.functions:
            if isinstance(fn.node, ast.Lambda):
                continue
            local = self._local_donators(graph, fn)
            for stmt in _linear_statements(fn):
                if not isinstance(stmt, ast.Return) or stmt.value is None:
                    continue
                donate = self._jit_donate(graph, stmt.value, fn, fn.module)
                if not donate and isinstance(stmt.value, ast.Name):
                    donate = local.get(stmt.value.id, ())
                if donate:
                    out[fn.full] = donate
        return out

    def _donating_attrs(self, graph: CallGraph) -> Dict[Tuple[str, str], Tuple[int, ...]]:
        """(class_full, attr) → argnums for ``self.attr = <donating>``."""
        out: Dict[Tuple[str, str], Tuple[int, ...]] = {}
        for fn in graph.functions:
            cls = fn.class_full or graph._enclosing_class(fn)
            if cls is None or isinstance(fn.node, ast.Lambda):
                continue
            for stmt in _linear_statements(fn):
                if not isinstance(stmt, ast.Assign):
                    continue
                donate = self._jit_donate(graph, stmt.value, fn, fn.module)
                if not donate and isinstance(stmt.value, ast.Call):
                    for callee in graph.resolve_callable(
                        stmt.value.func, fn, fn.module
                    ):
                        if callee.full in self._factories:
                            donate = self._factories[callee.full]
                            break
                if not donate:
                    continue
                for t in stmt.targets:
                    chain = attr_chain(t)
                    if chain and len(chain) == 2 and chain[0] == "self":
                        out[(cls, chain[1])] = donate
        return out

    def _call_donate_argnums(
        self, graph: CallGraph, fn: FunctionInfo, call: ast.Call,
        local: Dict[str, Tuple[int, ...]],
    ) -> Tuple[int, ...]:
        func = call.func
        # jax.jit(f, donate_argnums=...)(args) immediately invoked
        donate = self._jit_donate(graph, func, fn, fn.module)
        if donate:
            return donate
        if isinstance(func, ast.Name):
            hit = local.get(func.id, ())
            if hit:
                return hit
            scope = fn
            while scope is not None:
                if func.id in scope.bound and func.id not in local:
                    return ()  # shadowed by a non-donating local
                scope = scope.parent
            return self._module_vars.get((fn.module.modname, func.id), ())
        chain = attr_chain(func)
        if chain and len(chain) == 2 and chain[0] == "self":
            cls = fn.class_full or graph._enclosing_class(fn)
            if cls:
                for related in graph.related_classes(cls):
                    hit = self._attrs.get((related, chain[1]))
                    if hit:
                        return hit
        return ()

    # -- read-after-donate scan ------------------------------------------

    def _check_function(self, graph: CallGraph, fn: FunctionInfo) -> List[Finding]:
        local = self._local_donators(graph, fn)
        statements = _linear_statements(fn)
        donated: Dict[Tuple[str, ...], int] = {}  # chain -> donation line
        findings: List[Finding] = []
        reported: Set[Tuple[str, ...]] = set()
        for stmt in statements:
            rebinds = _flatten_targets(stmt)
            # 1) reads of already-donated chains (this statement's loads)
            if donated:
                for chain, line in _stmt_load_chains(stmt):
                    for d_chain, d_line in list(donated.items()):
                        if (
                            chain[: len(d_chain)] == d_chain
                            and line > d_line
                            and d_chain not in reported
                        ):
                            reported.add(d_chain)
                            findings.append(
                                Finding(
                                    code="GL301",
                                    path=fn.module.relpath,
                                    line=line,
                                    symbol=fn.qualname,
                                    detail=".".join(d_chain),
                                    message=f"`{'.'.join(chain)}` is read after "
                                    f"`{'.'.join(d_chain)}` was donated to a "
                                    f"jitted call on line {d_line} — donated "
                                    "buffers may alias the outputs (garbage "
                                    "reads / heap corruption)",
                                )
                            )
            # 2) rebinding clears tracking
            for chain in rebinds:
                for d_chain in list(donated):
                    if d_chain[: len(chain)] == tuple(chain):
                        del donated[d_chain]
            # 3) new donations from calls in this statement (skipping nested
            # function subtrees — their bodies are separate scopes, but the
            # rest of the statement must still be scanned)
            work: List[ast.AST] = [stmt]
            calls: List[ast.Call] = []
            while work:
                node = work.pop()
                if node is not stmt and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue
                work.extend(ast.iter_child_nodes(node))
                if isinstance(node, ast.Call):
                    calls.append(node)
            for node in calls:
                argnums = self._call_donate_argnums(graph, fn, node, local)
                for pos in argnums:
                    if pos < 0 or pos >= len(node.args):
                        continue
                    chain = attr_chain(node.args[pos])
                    if not chain:
                        continue
                    chain_t = tuple(chain)
                    if chain_t in [tuple(r) for r in rebinds]:
                        continue  # rebound by this very statement
                    donated.setdefault(chain_t, node.lineno)
        return findings
