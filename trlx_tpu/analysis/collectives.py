"""SPMD collective-discipline pass (GL7xx): host collectives must be
posted by EVERY rank, in the same order, with matching payloads — or the
pod hangs. ``ClusterDesyncError`` catches one class of divergence at
runtime, after a chip window is already burning; this pass proves the
classic divergence shapes absent statically.

**The catalog.** A *direct collective site* is a call whose callee name
ends in ``process_allgather`` / ``sync_global_devices`` /
``broadcast_one_to_all`` (``jax.experimental.multihost_utils`` — the gloo
host collectives every multihost path here rides, including the telemetry
beat). A function is *collective-bearing* when a collective site is
reachable from it over the call graph (so ``save_state`` is bearing via
its nested ``commit``'s ``_commit_barrier``, and ``ClusterTelemetry.beat``
via ``_default_allgather``).

**The codes.**

- GL701 — a collective (or collective-bearing call) reachable only under a
  **rank-dependent branch**: an ``if`` whose test calls
  ``process_index()``, calls a package *rank predicate* (a function whose
  return value derives from ``process_index()``, e.g. ``_is_primary``), or
  tests a local assigned from either. Ranks outside the branch never post
  the collective ⇒ the ranks inside hang. The legitimate pattern — rank 0
  authors host-side files while the *barrier stays outside the guard* —
  does not fire, because the collective itself is unguarded.
- GL702 — a **direct** collective inside a loop whose trip count is not
  provably rank-uniform: ``while`` loops with a non-literal condition, and
  ``for`` loops over anything but ``range()`` of constants / config
  attribute chains / literal sequences. One extra iteration on one rank is
  one unmatched collective: the pod hangs at the loop exit.
- GL703 — the same **barrier-name literal** passed to
  ``sync_global_devices`` (or a package wrapper that forwards its
  parameter into it) at more than one call site: jax pairs barriers by
  name, so two sites sharing a literal can pair rank A's site-1 with rank
  B's site-2 and desynchronize both. Parameterized names (f-strings,
  wrapper parameters) are the fix and are out of scope.
- GL704 — a collective (or bearing call) gated on a **config field** that
  is not registered rank-uniform (:data:`RANK_UNIFORM_FIELDS`). Config is
  normally identical across ranks, but nothing enforces it; fields that
  gate collectives are a contract and must be documented as such
  (docs/STATIC_ANALYSIS.md "The rank-uniformity contract").

Known limits (documented, deliberate): bearing-ness does not flow through
values (a collective closure stored in a module global and invoked later —
``wait_for_saves``'s deferred commit — is invisible); long attribute
chains (``self.obs.cluster.beat``) don't resolve, mirroring the call
graph's limits; rank-dependence through data (a per-rank flag allgathered
elsewhere) is out of scope.
"""

import ast
from typing import Dict, List, Optional, Set, Tuple

from trlx_tpu.analysis.callgraph import CallGraph, FunctionInfo, attr_chain
from trlx_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    LintPass,
    SourceModule,
    register_pass,
)

__all__ = ["CollectiveDisciplinePass", "RANK_UNIFORM_FIELDS"]

# host-collective callee names (attribute or bare): the gloo collectives
# every multihost path in this package posts
COLLECTIVE_NAMES = frozenset({
    "process_allgather",
    "sync_global_devices",
    "broadcast_one_to_all",
})

# Config fields DOCUMENTED as rank-uniform (the rank-uniformity contract,
# docs/STATIC_ANALYSIS.md): launchers must hand every rank the same value,
# because these fields gate whether a collective is posted at all. Gating
# a collective on any OTHER field is GL704 until the field is added here
# WITH a matching docs entry.
RANK_UNIFORM_FIELDS = frozenset({
    # resilience: gates the per-boundary preemption/telemetry allgather
    "coordinate_preemption",
    # resilience: gates the collective Orbax restore path on topology change
    "elastic",
    # train: gate interval/eval/best checkpoints — every checkpoint is a
    # collective Orbax shard write plus commit barriers, so every rank must
    # take the same save decision at the same boundary
    "checkpoint_interval",
    "eval_interval",
    "save_best",
    # async_rl: the fleet transport selection and its tree fanout. The
    # collective fleet's membership gauges ride the telemetry-beat
    # allgather's packed vector, and the coordinator/endpoint is authored
    # once per fleet — learner ranks disagreeing on the transport (or its
    # tree shape) would build mismatched fleets around the same beat
    # (docs/ASYNC_RL.md "Transports", docs/STATIC_ANALYSIS.md)
    "transport",
    "fanout",
})


def _is_terminal(stmt: ast.stmt) -> bool:
    """Statement unconditionally leaves the enclosing body."""
    if isinstance(stmt, (ast.Return, ast.Raise, ast.Continue, ast.Break)):
        return True
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        # sys.exit(...) — close enough for a linter
        chain = attr_chain(stmt.value.func)
        return bool(chain) and chain[-1] == "exit"
    return False


def _body_is_terminal(body: List[ast.stmt]) -> bool:
    return bool(body) and _is_terminal(body[-1])


class _RankDependence:
    """Per-function rank-dependence facts: which expressions/locals derive
    from ``process_index()``."""

    def __init__(self, graph: CallGraph, predicates: Set[str]):
        self.graph = graph
        self.predicates = predicates  # FunctionInfo.full of rank predicates

    def expr_is_rank_dependent(
        self, expr: ast.AST, fn: Optional[FunctionInfo], mod: SourceModule,
        local_ranky: Set[str],
    ) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                chain = attr_chain(sub.func)
                if chain and chain[-1] == "process_index":
                    return True
                for callee in self.graph.resolve_callable(sub.func, fn, mod):
                    if callee.full in self.predicates:
                        return True
            elif isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                if sub.id in local_ranky:
                    return True
        return False

    def local_rank_names(
        self, fn: FunctionInfo
    ) -> Set[str]:
        """Locals assigned from a rank-dependent expression in ``fn``."""
        out: Set[str] = set()
        # two sweeps: a name assigned from another ranky name still resolves
        for _ in range(2):
            for node in fn.body_nodes():
                if not isinstance(node, ast.Assign):
                    continue
                if not self.expr_is_rank_dependent(
                    node.value, fn, fn.module, out
                ):
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out


@register_pass
class CollectiveDisciplinePass(LintPass):
    name = "collective-discipline"
    codes = ("GL701", "GL702", "GL703", "GL704")
    description = "SPMD host collectives posted divergently across ranks"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        graph = ctx.callgraph
        direct = self._direct_sites(graph)
        if not direct:
            return []
        bearing = self._bearing_closure(graph, direct)
        predicates = self._rank_predicates(graph)
        rank = _RankDependence(graph, predicates)
        findings: List[Finding] = []
        findings.extend(self._check_guards(graph, direct, bearing, rank))
        findings.extend(self._check_loops(graph, direct))
        findings.extend(self._check_barrier_names(graph, direct))
        findings.sort(key=lambda f: (f.path, f.line, f.code))
        return findings

    # -- the catalog ------------------------------------------------------

    def _direct_sites(
        self, graph: CallGraph
    ) -> List[Tuple[SourceModule, ast.Call, Optional[FunctionInfo], str]]:
        out = []
        for mod in graph.ctx.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func)
                if not chain or chain[-1] not in COLLECTIVE_NAMES:
                    continue
                scope = graph.enclosing_function(mod, node)
                out.append((mod, node, scope, chain[-1]))
        return out

    def _bearing_closure(self, graph: CallGraph, direct) -> Set[str]:
        """FunctionInfo.full of every function from which a collective call
        site is reachable (callee fixed point; nested defs count as their
        own functions but are referenced by name, so edges cover them)."""
        bearing: Set[str] = set()
        for _mod, _node, scope, _name in direct:
            if scope is not None:
                bearing.add(scope.full)
        changed = True
        while changed:
            changed = False
            for fn in graph.functions:
                if fn.full in bearing:
                    continue
                callees = list(graph.edges(fn))
                if any(c.full in bearing for c in callees):
                    bearing.add(fn.full)
                    changed = True
        return bearing

    def _rank_predicates(self, graph: CallGraph) -> Set[str]:
        """Functions whose return value derives from ``process_index()``
        (``_is_primary``-style predicates), transitively."""
        out: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for fn in graph.functions:
                if fn.full in out:
                    continue
                for node in fn.body_nodes():
                    if not isinstance(node, ast.Return) or node.value is None:
                        continue
                    hit = False
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Call):
                            chain = attr_chain(sub.func)
                            if chain and chain[-1] == "process_index":
                                hit = True
                            else:
                                for callee in graph.resolve_callable(
                                    sub.func, fn, fn.module
                                ):
                                    if callee.full in out:
                                        hit = True
                    if hit:
                        out.add(fn.full)
                        changed = True
                        break
        return out

    # -- GL701 / GL704: rank- and config-gated collectives ----------------

    def _collective_calls_in(
        self, graph: CallGraph, fn: FunctionInfo, bearing: Set[str]
    ) -> List[Tuple[ast.Call, str]]:
        """(call node, label) for direct collectives and bearing-callee
        calls in ``fn``'s own body."""
        out: List[Tuple[ast.Call, str]] = []
        for node in fn.body_nodes():
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if chain and chain[-1] in COLLECTIVE_NAMES:
                out.append((node, chain[-1]))
                continue
            for callee in graph.resolve_callable(node.func, fn, fn.module):
                if callee.full in bearing:
                    label = chain[-1] if chain else callee.qualname
                    out.append((node, label))
                    break
        return out

    def _config_gate_field(
        self, test: ast.AST, fn: FunctionInfo
    ) -> Optional[str]:
        """The config field a guard tests, when the test references a
        ``...config...`` attribute chain (``config.resilience.elastic``,
        ``self.resilience.config.coordinate_preemption``) or a local
        assigned from one."""

        def field_of(expr: ast.AST) -> Optional[str]:
            for sub in ast.walk(expr):
                chain = attr_chain(sub) if isinstance(sub, ast.Attribute) else None
                if not chain or len(chain) < 2:
                    continue
                if "config" in chain[:-1] or chain[0].endswith("config"):
                    return chain[-1]
            return None

        hit = field_of(test)
        if hit:
            return hit
        # one hop through a local: `coordinate = <config chain>; if coordinate:`
        names = {
            sub.id
            for sub in ast.walk(test)
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
        }
        if not names:
            return None
        for node in fn.body_nodes():
            if not isinstance(node, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id in names for t in node.targets
            ):
                continue
            hit = field_of(node.value)
            if hit:
                return hit
        return None

    def _check_guards(
        self, graph: CallGraph, direct, bearing: Set[str], rank: _RankDependence
    ) -> List[Finding]:
        findings: List[Finding] = []
        seen: Set[str] = set()
        for fn in graph.functions:
            calls = self._collective_calls_in(graph, fn, bearing)
            if not calls:
                continue
            local_ranky = rank.local_rank_names(fn)
            # early-exit guards: statements after `if <rank-dep>: return`
            # in the same body are rank-conditional too
            guarded_after: Dict[int, Tuple[str, ast.AST]] = {}
            for stmt in ast.walk(fn.node):
                bodies = []
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if isinstance(sub, list) and sub and isinstance(
                        sub[0], ast.stmt
                    ):
                        bodies.append(sub)
                for body in bodies:
                    for i, s in enumerate(body):
                        if (
                            isinstance(s, ast.If)
                            and _body_is_terminal(s.body)
                            and not s.orelse
                            and rank.expr_is_rank_dependent(
                                s.test, fn, fn.module, local_ranky
                            )
                        ):
                            for later in body[i + 1:]:
                                for sub in ast.walk(later):
                                    guarded_after[id(sub)] = ("early-exit", s.test)
            for call, label in calls:
                guard: Optional[Tuple[str, ast.AST]] = None
                config_fields: List[str] = []
                for anc in fn.module.ancestors(call):
                    if anc is fn.node:
                        break
                    if not isinstance(anc, (ast.If, ast.IfExp)):
                        continue
                    if rank.expr_is_rank_dependent(
                        anc.test, fn, fn.module, local_ranky
                    ):
                        guard = ("branch", anc.test)
                        break
                    field = self._config_gate_field(anc.test, fn)
                    if field is not None:
                        config_fields.append(field)
                if guard is None and id(call) in guarded_after:
                    guard = guarded_after[id(call)]
                if guard is not None:
                    key = f"{fn.full}:{label}:701"
                    if key in seen:
                        continue
                    seen.add(key)
                    findings.append(
                        Finding(
                            code="GL701",
                            path=fn.module.relpath,
                            line=call.lineno,
                            symbol=fn.qualname,
                            detail=label,
                            message=f"collective `{label}` is reachable only "
                            "under a rank-dependent branch "
                            f"(`{_short(guard[1])}`): ranks outside the "
                            "branch never post it — the ranks inside hang. "
                            "Hoist the collective out of the guard; keep "
                            "only rank-local host work inside",
                        )
                    )
                elif guard is None:
                    for config_field in config_fields:
                        if config_field in RANK_UNIFORM_FIELDS:
                            continue
                        key = f"{fn.full}:{label}:{config_field}:704"
                        if key in seen:
                            continue
                        seen.add(key)
                        findings.append(
                            Finding(
                                code="GL704",
                                path=fn.module.relpath,
                                line=call.lineno,
                                symbol=fn.qualname,
                                detail=f"{config_field}->{label}",
                                message=f"collective `{label}` is gated on "
                                f"config field `{config_field}`, which is not "
                                "registered rank-uniform — a launcher handing "
                                "ranks different values hangs the pod. Add the "
                                "field to RANK_UNIFORM_FIELDS (analysis/"
                                "collectives.py) AND document the contract "
                                "(docs/STATIC_ANALYSIS.md), or derive the gate "
                                "from uniform state",
                            )
                        )
        return findings

    # -- GL702: per-rank loop trip counts ---------------------------------

    def _iter_is_uniform(self, it: ast.AST) -> bool:
        """Conservatively rank-uniform iterables: literals, dotted
        config/attr chains, range()/enumerate()/zip() of uniform things.
        A bare local name is NOT uniform — `pending = <per-rank filter>;
        for p in pending: allgather(...)` is exactly the hang GL702
        exists to catch, so a local must be spelled as its (uniform)
        source to pass."""
        if isinstance(it, (ast.List, ast.Tuple, ast.Constant)):
            return True
        chain = attr_chain(it)
        if chain and len(chain) >= 2:
            return True  # config.train.xs / self.epochs — uniform by contract
        if isinstance(it, ast.Call):
            fchain = attr_chain(it.func)
            if fchain and fchain[-1] in ("range", "enumerate", "zip", "len"):
                return all(self._iter_is_uniform(a) for a in it.args)
        return False

    def _check_loops(self, graph: CallGraph, direct) -> List[Finding]:
        findings: List[Finding] = []
        seen: Set[str] = set()
        for mod, call, scope, name in direct:
            for anc in mod.ancestors(call):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    break  # loops outside the defining function don't count
                hazard = None
                if isinstance(anc, ast.While):
                    if not (
                        isinstance(anc.test, ast.Constant) and anc.test.value
                    ):
                        hazard = f"while {_short(anc.test)}"
                elif isinstance(anc, ast.For):
                    if not self._iter_is_uniform(anc.iter):
                        hazard = f"for ... in {_short(anc.iter)}"
                if hazard is None:
                    continue
                symbol = scope.qualname if scope else "-"
                key = f"{mod.relpath}:{symbol}:{name}"
                if key in seen:
                    continue
                seen.add(key)
                findings.append(
                    Finding(
                        code="GL702",
                        path=mod.relpath,
                        line=call.lineno,
                        symbol=symbol,
                        detail=name,
                        message=f"collective `{name}` inside `{hazard}`: the "
                        "trip count is not provably rank-uniform, and one "
                        "extra iteration on one rank is one unmatched "
                        "collective (pod hang at loop exit) — drive the "
                        "loop from config/constants, or hoist the "
                        "collective",
                    )
                )
                break
        return findings

    # -- GL703: duplicated barrier-name literals --------------------------

    def _check_barrier_names(self, graph: CallGraph, direct) -> List[Finding]:
        # wrappers: package functions forwarding a parameter into the
        # barrier name (``_commit_barrier(name)``) — their literal call-site
        # args are barrier names too
        wrappers: Set[str] = set()
        for _mod, call, scope, name in direct:
            if name != "sync_global_devices" or scope is None or not call.args:
                continue
            arg_names = {
                sub.id for sub in ast.walk(call.args[0])
                if isinstance(sub, ast.Name)
            }
            if arg_names & set(scope.params):
                wrappers.add(scope.full)
        sites: Dict[str, List[Tuple[SourceModule, ast.Call, Optional[FunctionInfo]]]] = {}

        def record(mod, call, scope):
            if not call.args:
                return
            arg = call.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                sites.setdefault(arg.value, []).append((mod, call, scope))

        for mod, call, scope, name in direct:
            if name == "sync_global_devices":
                record(mod, call, scope)
        for mod in graph.ctx.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                scope = graph.enclosing_function(mod, node)
                for callee in graph.resolve_callable(node.func, scope, mod):
                    if callee.full in wrappers:
                        record(mod, node, scope)
                        break
        findings: List[Finding] = []
        for name, where in sorted(sites.items()):
            if len(where) < 2:
                continue
            for mod, call, scope in where:
                findings.append(
                    Finding(
                        code="GL703",
                        path=mod.relpath,
                        line=call.lineno,
                        symbol=scope.qualname if scope else "-",
                        detail=name,
                        message=f'barrier name "{name}" is used at '
                        f"{len(where)} call sites: jax pairs barriers by "
                        "name, so interleaved arrivals can pair one rank's "
                        "site with another rank's different site — give "
                        "each site a distinct (or parameterized) name",
                    )
                )
        return findings


def _short(node: ast.AST, limit: int = 50) -> str:
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover
        text = "<expr>"
    return text if len(text) <= limit else text[: limit - 1] + "…"
