"""Convention passes: metric-name namespace (GL501), span-name namespace
(GL502), and config-key resolution (GL601).

``metric-names`` is the framework home of the former standalone
``scripts/check_metric_names.py`` (that script is now a thin shim over
this module — same public helpers, same semantics): every literal
string-keyed ``stats[...]`` subscript and ``metrics.inc/set_gauge(...)``
call site must use a ``namespace/name`` key. ``LEGACY_KEYS`` is frozen;
``RESILIENCE_KEYS`` registers the canonical resilience counters the
static scan can't see (parameterized helper emissions).

``span-names`` (GL502) holds span/instant/complete-event names to the SAME
``namespace/name`` rule: spans land in the same dashboards and merged
multi-rank traces as metrics, so one naming convention covers both.
``LEGACY_SPAN_NAMES`` freezes the five pre-convention trainer spans
(``rollout``/``generate``/``score``/``reward``/``train_step``) — do not
add to it; new spans must be namespaced. AST-based (unlike the GL501 line
scan) so multi-line calls and docstring examples are handled correctly;
dynamically-named spans (f-strings, variables) are out of scope.

``config-keys`` resolves ``config.<section>.<field>`` attribute chains
against the dataclasses in ``data/configs.py`` (sections) and every
``MethodConfig`` subclass in the package (the ``method`` section's field
union). A typo'd knob (``config.train.rollout_pipeline_dept``) otherwise
reads nothing and silently trains with the default.
"""

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from trlx_tpu.analysis.callgraph import attr_chain
from trlx_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    LintPass,
    register_pass,
)

# ---------------------------------------------------------------------------
# metric names (the former scripts/check_metric_names.py, verbatim rules)
# ---------------------------------------------------------------------------

# \bstats\[ : the dict must be *named* stats (not spec_stats, device_stats…)
# Second alternative: MetricsRegistry writes — receivers named/suffixed
# "metrics" calling inc()/set_gauge() with a literal first argument (the
# registry's observe() is excluded: RecompileWatchdog.observe's first arg is
# a program name, not a metric key).
_KEY_RE = re.compile(
    r'\bstats\[\s*f?"([^"]+)"'
    r'|\bmetrics\.(?:inc|set_gauge)\(\s*f?"([^"]+)"'
)

# namespace/name: lowercase_snake namespace, then anything non-empty (names
# may carry f-string fields, sweep suffixes, dots, @-qualifiers)
_CONVENTION_RE = re.compile(r"^[a-z][a-z0-9_]*/\S+$")

# Pre-convention keys, kept for dashboard/log continuity. Do not add to this
# list — new metrics must be namespaced.
LEGACY_KEYS = frozenset({
    "learning_rate",
    "kl_ctl_value",
})

# Canonical resilience/* metric keys (docs/RESILIENCE.md). The retry
# counters are emitted through a parameterized helper
# (HostCallGuard._inc(f"resilience/{name}_retries")) the static scan can't
# see, so the full set is registered here; tests/test_metric_names.py
# asserts every entry follows the convention and that the statically
# visible ones reach the scanner.
RESILIENCE_KEYS = frozenset({
    "resilience/update_ok",
    "resilience/nonfinite_updates",
    "resilience/skipped_updates",
    "resilience/rollbacks",
    "resilience/goodput_frac",
    "resilience/preemptions",
    "resilience/reward_retries",
    "resilience/reward_failures",
    "resilience/reward_fallbacks",
    "resilience/publish_retries",
    "resilience/publish_failures",
    "resilience/publish_fallbacks",
    # elastic topology-change restore (docs/RESILIENCE.md "Elastic
    # restore"): wall-seconds of the host-side reshard, and how many
    # restores took the elastic path this run
    "resilience/reshard_s",
    "resilience/elastic_restores",
})

# Canonical generation-engine metric keys (trlx_tpu/engine/,
# docs/PERFORMANCE.md): the paged-KV block-pool and prefix-cache gauges,
# plus the KV-memory gauge both backends (and the serial sampler) report.
# All are statically visible stats[...] / set_gauge sites, but the registry
# is the single list tests assert convention + visibility against —
# tests/test_metric_names.py.
ENGINE_KEYS = frozenset({
    "engine/kv_blocks_in_use",
    "engine/block_pool_occupancy",
    "engine/prefix_hit_rate",
    "engine/prefix_tokens_saved",
    "engine/queue_wait_s",
    "memory/kv_cache_bytes",
    # paged decode/prefill compute path gauges (0/1): engine.decode_kernel
    # / engine.prefill_kernel — the in-place Pallas kernels
    # (ops/paged_attention.py, ops/paged_prefill.py) vs the gather/scatter
    # references (docs/PERFORMANCE.md "Pallas kernels")
    "engine/decode_kernel_pallas",
    "engine/prefill_kernel_pallas",
    # analytic bytes the refill prefills move through transient dense
    # views (pool→view gather on entry, span→pool scatter on exit):
    # exactly 0 under the in-place prefill kernel — the acceptance number
    # of benchmarks/ENGINE_PREFILL_cpu.json
    "engine/refill_gather_bytes",
    "engine/refill_scatter_bytes",
    # chunked-prefill scheduling (engine.prefill_chunk,
    # docs/PERFORMANCE.md "Chunked prefill"): mid-chunk program calls, and
    # the measured wall-seconds live decode slots spent waiting on prefill
    # work — one sample per stalling prefill event
    "rollout/prefill_chunks",
    "rollout/decode_stall_p50",
    "rollout/decode_stall_p95",
    "rollout/decode_stall_max",
    # speculative continuous batching (engine.speculative,
    # docs/PERFORMANCE.md "Speculative continuous batching"): fraction of
    # draft proposals the target accepted, committed tokens per live
    # row-round (the throughput multiplier, ∈ [1, gamma+1]), and
    # draft-propose/verify rounds run this collection
    "engine/spec_acceptance_rate",
    "engine/spec_tokens_per_round",
    "rollout/spec_rounds",
    # spec verify compute path gauge (0/1): the in-place multi-position
    # verify kernel (ops/paged_attention.py::paged_verify_attention, runs
    # when engine.decode_kernel: pallas composes with engine.speculative)
    # vs the gather → shared round → scatter reference
    "engine/spec_verify_kernel_pallas",
    # fused learner-step kernel gauge (0/1): method.loss_kernel: pallas
    # ran with the Mosaic (pallas TPU) backend importable
    # (ops/fused_loss.py) — a Mosaic-less build's staged fallback reports
    # 0, so an artifact can't claim kernel=1 it never ran
    # (docs/PERFORMANCE.md "Fused learner kernels")
    "train/loss_kernel_pallas",
    # serving extensions on the engine (docs/SERVING.md): per-request
    # queue-wait percentiles from the enqueue→prefill spans, priority-
    # preemption count, and the host-tier re-land accounting (blocks
    # written back device-side instead of re-prefilled, and the prefill
    # tokens that saved)
    "engine/queue_wait_p50",
    "engine/queue_wait_p95",
    "engine/preempted_rows",
    "engine/host_tier_hit_blocks",
    "engine/host_tier_tokens_saved",
})

# Canonical serving-frontend keys (trlx_tpu/serve/, docs/SERVING.md): the
# FLAT aggregate gauges ServeMetrics.metrics() emits into the training
# metric stream — TTFT/TPOT/queue-wait percentiles over all serve traffic,
# admission counters (SLO 429s, drain 503s, flood-drill sheds), terminal
# counts, and the host-tier occupancy counters. Per-tenant/per-class
# breakdowns deliberately stay OFF this registry (unbounded cardinality)
# and live on the HTTP /metrics endpoint instead. All literal stats[...]
# sites in serve/metrics.py.
SERVE_KEYS = frozenset({
    "serve/ttft_p50",
    "serve/ttft_p95",
    "serve/tpot_p50",
    "serve/tpot_p95",
    "serve/queue_wait_p50",
    "serve/queue_wait_p95",
    "serve/admitted",
    "serve/rejected",
    "serve/drain_rejected",
    "serve/flood_rejected",
    "serve/completed",
    "serve/failed",
    "serve/dropped",
    "serve/active",
    "serve/streamed_tokens",
    "serve/host_tier_blocks",
    "serve/host_tier_spilled",
    "serve/host_tier_relanded",
    "serve/params_version",
})

# Canonical cross-rank telemetry gauges (observability/distributed.py,
# docs/OBSERVABILITY.md "Distributed telemetry"): published every step
# boundary from the packed allgather matrix — min/mean/max/skew of the
# per-rank scalars plus the straggler verdict. All literal set_gauge sites.
CLUSTER_KEYS = frozenset({
    "cluster/size",
    "cluster/step_time_min_s",
    "cluster/step_time_mean_s",
    "cluster/step_time_max_s",
    "cluster/step_skew_s",
    "cluster/host_wait_mean_s",
    "cluster/host_wait_max_s",
    "cluster/tokens_per_sec_min",
    "cluster/tokens_per_sec_sum",
    "cluster/device_bytes_in_use_max",
    "cluster/straggler_rank",
    "cluster/fleet_size",
})

# Canonical async actor/learner keys (trlx_tpu/async_rl/, docs/ASYNC_RL.md):
# the learner-side collection gauges (queue depth, staleness at consumption,
# actor idle fraction) plus the counters the queue/channel/supervisor emit.
# async/staleness is additionally observed as a histogram, so the tracker
# stream carries async/staleness_mean|_max|_count summaries per window.
ASYNC_KEYS = frozenset({
    "async/chunks",
    "async/queue_depth",
    "async/staleness_mean",
    "async/staleness_max",
    "async/learner_wait_s",
    "async/actor_idle_frac",
    "async/dropped_chunks",
    "async/requeued_chunks",
    "async/actor_restarts",
    "async/weight_syncs",
    "async/weight_sync_drops",
    # collective fleet transport (async_rl/transport.py, docs/ASYNC_RL.md
    # "Transports"): dissemination-tree publish egress + ack latency,
    # live membership, and elastic join/shrink counters
    "async/dissemination_latency_s",
    "async/publish_bytes",
    "async/fleet_size",
    "async/fleet_joins",
    "async/fleet_shrinks",
})

# Canonical async span names (GL502-namespaced; the actor's per-chunk span
# lands on its own thread track in the merged trace).
ASYNC_SPAN_NAMES = frozenset({
    "async/actor_chunk",
})

# Crash flight recorder accounting (observability/flightrec.py,
# docs/OBSERVABILITY.md "Flight recorder").
FLIGHTREC_KEYS = frozenset({
    "flightrec/dumps",
    "flightrec/records",
})

# Observability self-accounting (docs/OBSERVABILITY.md): the span tracer's
# silent drop counter surfaced as a gauge.
OBS_KEYS = frozenset({
    "obs/spans_dropped",
})

# Canonical training-dynamics sketch keys (observability/dynamics.py,
# docs/OBSERVABILITY.md "Training dynamics"). The ``*_hist`` keys carry the
# on-device fixed-bin histogram counts through the stats fetch; the host
# summarizer collapses each into ``_p05/_p50/_p95`` percentile gauges (the
# summary keys are emitted through parameterized f-strings, so the registry
# is their single canonical list).
DIST_KEYS = frozenset({
    "dist/log_ratio_hist",
    "dist/kl_hist",
    "dist/ref_kl_hist",
    "dist/advantages_hist",
    "dist/value_error_hist",
    "dist/entropy_hist",
    "dist/reward_margin_hist",
    # host-side summaries (DynamicsSummarizer): one triple per histogram
    "dist/log_ratio_p05", "dist/log_ratio_p50", "dist/log_ratio_p95",
    "dist/kl_p05", "dist/kl_p50", "dist/kl_p95",
    "dist/ref_kl_p05", "dist/ref_kl_p50", "dist/ref_kl_p95",
    "dist/advantages_p05", "dist/advantages_p50", "dist/advantages_p95",
    "dist/value_error_p05", "dist/value_error_p50", "dist/value_error_p95",
    "dist/entropy_p05", "dist/entropy_p50", "dist/entropy_p95",
    "dist/reward_margin_p05", "dist/reward_margin_p50",
    "dist/reward_margin_p95",
    # mass of per-token ratio beyond the PPO clip window [1−ε, 1+ε]
    "dist/ratio_outside_clip_frac",
})

# Canonical RL health keys (observability/health.py, docs/OBSERVABILITY.md
# "Training dynamics"): one 0/1 gauge per windowed detector plus the overall
# verdict (detector gauges are published through a parameterized f-string —
# registered here), the rollout canary gauges, and the counters the NaN
# guards bump (kl-controller skips, sanitized scores/KL chunks, triage
# artifact dumps).
HEALTH_KEYS = frozenset({
    "health/kl_runaway",
    "health/entropy_collapse",
    "health/clipfrac_saturation",
    "health/value_ev_collapse",
    "health/reward_flatline",
    "health/gen_canary",
    "health/verdict",
    "health/kl_ctl_skips",
    "health/triage_dumps",
    "health/nonfinite_scores",
    "health/nonfinite_kl_chunks",
    # rollout-side generation canary (engine harvest + finalize host twin)
    "rollout/gen_len_p50",
    "rollout/gen_len_p95",
    "rollout/repetition_frac",
})


def _iter_line_keys(lines) -> "List[Tuple[int, str]]":
    """(lineno, key) for every literal metric-key site in ``lines`` — the
    single scanning loop behind the shim helpers and the GL501 pass."""
    out: List[Tuple[int, str]] = []
    for lineno, line in enumerate(lines, start=1):
        for groups in _KEY_RE.findall(line):
            out.append((lineno, groups[0] or groups[1]))
    return out


def _iter_dir_keys(scan_dir: str):
    """(relpath, lineno, key) over every .py under ``scan_dir``; relpaths
    relative to the scan dir's parent (the shim's historical repo-root-
    relative output)."""
    base = os.path.dirname(os.path.abspath(scan_dir))
    for dirpath, _dirnames, filenames in os.walk(scan_dir):
        if "__pycache__" in dirpath:
            continue
        for filename in sorted(filenames):
            if not filename.endswith(".py"):
                continue
            path = os.path.join(dirpath, filename)
            with open(path) as f:
                for lineno, key in _iter_line_keys(f):
                    yield os.path.relpath(path, base), lineno, key


def _breaks_convention(key: str) -> bool:
    return key not in LEGACY_KEYS and not _CONVENTION_RE.match(key)


def find_violations(scan_dir: str) -> List[Tuple[str, int, str]]:
    """All (relpath, lineno, key) whose key breaks the convention."""
    return [
        (relpath, lineno, key)
        for relpath, lineno, key in _iter_dir_keys(scan_dir)
        if _breaks_convention(key)
    ]


def scanned_keys(scan_dir: str) -> Dict[str, int]:
    """key → occurrence count over the tree (for the test's sanity check
    that the scanner actually sees the codebase's stats writes)."""
    counts: Dict[str, int] = {}
    for _relpath, _lineno, key in _iter_dir_keys(scan_dir):
        counts[key] = counts.get(key, 0) + 1
    return counts


@register_pass
class MetricNamesPass(LintPass):
    name = "metric-names"
    codes = ("GL501",)
    description = "metric keys must follow the namespace/name convention"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for mod in ctx.modules:
            for lineno, key in _iter_line_keys(mod.lines):
                if not _breaks_convention(key):
                    continue
                findings.append(
                    Finding(
                        code="GL501",
                        path=mod.relpath,
                        line=lineno,
                        symbol="-",
                        detail=key,
                        message=f'metric key "{key}" violates the '
                        "namespace/name convention "
                        "(docs/OBSERVABILITY.md; LEGACY_KEYS is frozen)",
                    )
                )
        return findings


# ---------------------------------------------------------------------------
# span names
# ---------------------------------------------------------------------------

# call names whose first literal-string argument is a span/track name:
# Tracer.span / Observability.span / module-level span(), Tracer.instant,
# Tracer.add_complete_event, and the engine's injected `self._span` seam
_SPAN_FUNCS = frozenset({"span", "_span", "instant", "add_complete_event"})

# Pre-convention trainer span names, kept for trace/dashboard continuity
# (they predate the namespace rule and appear in every committed trace).
# FROZEN — new spans must be namespaced.
LEGACY_SPAN_NAMES = frozenset({
    "rollout",
    "generate",
    "score",
    "reward",
    "train_step",
})


def _span_name_violation(name: str) -> bool:
    return name not in LEGACY_SPAN_NAMES and not _CONVENTION_RE.match(name)


@register_pass
class SpanNamesPass(LintPass):
    name = "span-names"
    codes = ("GL502",)
    description = "span names must follow the namespace/name convention"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        graph = ctx.callgraph
        for mod in ctx.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                func = node.func
                if isinstance(func, ast.Attribute):
                    fname = func.attr
                elif isinstance(func, ast.Name):
                    fname = func.id
                else:
                    continue
                if fname not in _SPAN_FUNCS:
                    continue
                arg = node.args[0]
                if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
                    continue  # dynamic names are out of static scope
                name = arg.value
                if not _span_name_violation(name):
                    continue
                scope = graph.enclosing_function(mod, node)
                findings.append(
                    Finding(
                        code="GL502",
                        path=mod.relpath,
                        line=node.lineno,
                        symbol=scope.qualname if scope else "-",
                        detail=name,
                        message=f'span name "{name}" violates the '
                        "namespace/name convention (docs/OBSERVABILITY.md; "
                        "LEGACY_SPAN_NAMES is frozen)",
                    )
                )
        return findings


# ---------------------------------------------------------------------------
# config keys
# ---------------------------------------------------------------------------

# receivers we trust to be a TRLConfig: `config.train.x`, `self.config.train.x`
_CONFIG_RECEIVERS = {"config", "cfg", "baseconfig"}


def _dataclass_members(node: ast.ClassDef) -> Set[str]:
    out: Set[str] = set()
    for stmt in node.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            out.add(stmt.target.id)
        elif isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(stmt.name)
    return out


@register_pass
class ConfigKeysPass(LintPass):
    name = "config-keys"
    codes = ("GL601",)
    description = "config.<section>.<field> must resolve to a declared field"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        sections = self._collect_sections(ctx)
        if not sections:
            return []
        graph = ctx.callgraph
        findings: List[Finding] = []
        for mod in ctx.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Attribute):
                    continue
                chain = attr_chain(node)
                if not chain or len(chain) < 3:
                    continue
                hit = self._match_section(chain, sections)
                if hit is None:
                    continue
                section, fieldname = hit
                if fieldname in sections[section]:
                    continue
                scope = graph.enclosing_function(mod, node)
                symbol = scope.qualname if scope else "-"
                findings.append(
                    Finding(
                        code="GL601",
                        path=mod.relpath,
                        line=node.lineno,
                        symbol=symbol,
                        detail=f"{section}.{fieldname}",
                        message=f"`config.{section}.{fieldname}` does not "
                        f"resolve to a declared field of the `{section}` "
                        "config dataclass (data/configs.py) — typo'd knobs "
                        "silently read defaults",
                    )
                )
        # one finding per (file, detail): repeated uses of the same bad key
        # in one file are one decision
        seen: Set[str] = set()
        unique: List[Finding] = []
        for f in sorted(findings, key=lambda f: (f.path, f.line)):
            k = f"{f.path}:{f.detail}"
            if k not in seen:
                seen.add(k)
                unique.append(f)
        return unique

    def _collect_sections(self, ctx: AnalysisContext) -> Dict[str, Set[str]]:
        """section name → allowed member names. Sections come from
        TRLConfig's fields; `method` is the union over MethodConfig and
        every class in the package inheriting (transitively, by name) from
        it."""
        classes: Dict[str, ast.ClassDef] = {}
        bases: Dict[str, List[str]] = {}
        trl: Optional[ast.ClassDef] = None
        for mod in ctx.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    classes[node.name] = node
                    bases[node.name] = [
                        ".".join(attr_chain(b) or ["?"]) for b in node.bases
                    ]
                    if node.name == "TRLConfig":
                        trl = node
        if trl is None:
            return {}

        def inherits_method_config(name: str, seen: Set[str]) -> bool:
            if name == "MethodConfig":
                return True
            if name in seen:
                return False
            seen.add(name)
            return any(
                inherits_method_config(b.rsplit(".", 1)[-1], seen)
                for b in bases.get(name, [])
            )

        method_members: Set[str] = set()
        for name, node in classes.items():
            if inherits_method_config(name, set()):
                method_members |= _dataclass_members(node)

        sections: Dict[str, Set[str]] = {}
        for stmt in trl.body:
            if not (
                isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            ):
                continue
            section = stmt.target.id
            ann = stmt.annotation
            ann_name = (attr_chain(ann) or ["?"])[-1]
            if ann_name == "MethodConfig" or section == "method":
                sections[section] = set(method_members)
            elif ann_name in classes:
                sections[section] = _dataclass_members(classes[ann_name])
        return sections

    def _match_section(
        self, chain: List[str], sections: Dict[str, Set[str]]
    ) -> Optional[Tuple[str, str]]:
        """Match ``[..., <config-receiver>, <section>, <field>, ...]``."""
        for i in range(len(chain) - 2):
            recv, section, fieldname = chain[i], chain[i + 1], chain[i + 2]
            if section not in sections:
                continue
            if recv in _CONFIG_RECEIVERS or recv.endswith("config"):
                return section, fieldname
        return None
