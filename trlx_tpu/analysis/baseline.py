"""graftlint baseline: the committed allowlist of intentional findings.

Format (one entry per line, ``#`` comments):

    <code> <path>:<symbol>:<detail> :: <justification>

e.g.::

    GL201 trlx_tpu/trainer/ppo.py:PPOTrainer._get_score_fn.<locals>.score_fn:B :: per-shape program cache keyed on batch_shape

Rules (enforced here and by ``tests/test_analysis.py``):

- every entry MUST carry a non-empty justification after ``::`` — a
  suppression without a written reason is a parse error;
- every entry MUST still match a live finding — a stale entry (the
  violation was fixed, or the key drifted) fails the run, so the baseline
  can only ever shrink to match reality. This is also what makes each
  entry load-bearing: deleting one resurfaces its finding.

Keys deliberately omit line numbers (see ``core.Finding``): edits above a
finding don't invalidate the baseline, while renaming/moving the function
does — at which point the entry must be re-justified anyway.
"""

import os
from dataclasses import dataclass
from typing import Dict, List, Tuple

from trlx_tpu.analysis.core import Finding

__all__ = ["Baseline", "BaselineEntry", "BaselineError"]

_FIXME = "FIXME: justify this suppression"


class BaselineError(Exception):
    """Malformed baseline file (bad syntax or missing justification)."""


@dataclass
class BaselineEntry:
    key: str  # "<code> <path>:<symbol>:<detail>"
    justification: str
    line: int = 0

    @property
    def needs_justification(self) -> bool:
        return self.justification.startswith("FIXME")


class Baseline:
    def __init__(self, entries: Dict[str, BaselineEntry] = None):
        self.entries: Dict[str, BaselineEntry] = entries or {}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        entries: Dict[str, BaselineEntry] = {}
        with open(path, encoding="utf-8") as f:
            for lineno, raw in enumerate(f, start=1):
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                if " :: " not in line:
                    raise BaselineError(
                        f"{path}:{lineno}: entry has no ' :: <justification>' "
                        f"— every suppression needs a written reason: {line!r}"
                    )
                key, justification = line.split(" :: ", 1)
                key = key.strip()
                justification = justification.strip()
                if not justification:
                    raise BaselineError(
                        f"{path}:{lineno}: empty justification for {key!r}"
                    )
                if len(key.split(" ", 1)) != 2 or ":" not in key:
                    raise BaselineError(
                        f"{path}:{lineno}: malformed key (want "
                        f"'<code> <path>:<symbol>:<detail>'): {key!r}"
                    )
                if key in entries:
                    raise BaselineError(f"{path}:{lineno}: duplicate entry {key!r}")
                entries[key] = BaselineEntry(key, justification, lineno)
        return cls(entries)

    def apply(
        self, findings: List[Finding]
    ) -> Tuple[List[Finding], List[BaselineEntry]]:
        """Split ``findings`` against the baseline: returns (new findings
        not covered by any entry, stale entries matching no finding)."""
        used = set()
        new: List[Finding] = []
        for f in findings:
            if f.key in self.entries:
                used.add(f.key)
            else:
                new.append(f)
        stale = [e for k, e in self.entries.items() if k not in used]
        stale.sort(key=lambda e: e.line)
        return new, stale

    def update(self, findings: List[Finding]) -> None:
        """Rewrite the entry set to exactly the current findings, keeping
        justifications of surviving entries (``--update-baseline``)."""
        fresh: Dict[str, BaselineEntry] = {}
        for f in findings:
            if f.key in fresh:
                continue
            old = self.entries.get(f.key)
            fresh[f.key] = old or BaselineEntry(f.key, _FIXME)
        self.entries = fresh

    def save(self, path: str) -> None:
        lines = [
            "# graftlint baseline — intentional findings, each with a written",
            "# justification (docs/STATIC_ANALYSIS.md). Entries must match a",
            "# live finding: fix a violation, then delete its entry here.",
            "# Format: <code> <path>:<symbol>:<detail> :: <justification>",
            "",
        ]
        for key in sorted(self.entries):
            entry = self.entries[key]
            lines.append(f"{key} :: {entry.justification}")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + "\n")
        os.replace(tmp, path)
