"""graftlint core: findings, the pass registry, the analysis context, and
the CLI driver.

The linter is a whole-program static analysis over the ``trlx_tpu`` source
tree (AST-based — nothing is imported, so linting never initializes jax).
Each :class:`LintPass` inspects the parsed tree (plus the shared
intra-package call graph, ``callgraph.py``) and emits :class:`Finding`
records with a per-finding code (``GL1xx`` host-sync, ``GL2xx`` recompile,
``GL3xx`` donation, ``GL4xx`` locks/thread-escape, ``GL5xx``
metric/span names, ``GL6xx`` config keys, ``GL7xx`` collective
discipline, ``GL8xx`` ownership/lifecycle, ``GL9xx`` determinism
discipline — catalog in docs/STATIC_ANALYSIS.md).

Findings are keyed by ``(code, path, symbol, detail)`` — deliberately **not**
by line number, so the committed baseline (``GRAFTLINT_BASELINE.txt``)
survives unrelated edits. The baseline is a strict allowlist: every entry
must carry a justification and must still match a live finding
(``baseline.py``; stale entries fail the run), which is what makes the
tier-1 self-run (``tests/test_analysis.py``) a standing CI gate.
"""

import argparse
import ast
import os
import sys
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple, Type

__all__ = [
    "Finding",
    "SourceModule",
    "AnalysisContext",
    "LintPass",
    "register_pass",
    "all_passes",
    "get_pass",
    "run_analysis",
    "main",
]


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    """One lint violation.

    ``key`` intentionally omits the line number: baselines must survive
    unrelated edits above the finding. ``detail`` is the stable
    discriminator within a function (the offending call/attribute text) —
    two identical violations in one function share a key, and one baseline
    entry suppresses both (they are the same decision).
    """

    code: str  # e.g. "GL101"
    path: str  # posix relpath, e.g. "trlx_tpu/trainer/base.py"
    line: int  # 1-indexed, for humans; not part of the key
    symbol: str  # enclosing function qualname, or "-" (module level)
    detail: str  # stable discriminator (offending expression text)
    message: str  # human explanation

    @property
    def key(self) -> str:
        return f"{self.code} {self.path}:{self.symbol}:{self.detail}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} [{self.symbol}] {self.message}"


# ---------------------------------------------------------------------------
# source loading
# ---------------------------------------------------------------------------


@dataclass
class SourceModule:
    """One parsed source file."""

    path: str  # absolute
    relpath: str  # posix, relative to the scan root's parent
    modname: str  # dotted module name, e.g. "trlx_tpu.trainer.base"
    text: str
    lines: List[str]
    tree: ast.Module
    # parent links for "is this statement inside that with-block" queries
    parents: Dict[ast.AST, ast.AST] = field(default_factory=dict)

    def build_parents(self) -> None:
        if self.parents:
            return
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        self.build_parents()
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)


class AnalysisContext:
    """Parsed view of one scan root (a package directory).

    ``root`` is the package dir (e.g. ``trlx_tpu/``); relpaths are computed
    against its parent so findings read ``trlx_tpu/trainer/base.py``. The
    intra-package call graph is built lazily (only the jax-aware passes
    need it).
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.base = os.path.dirname(self.root)
        self.package = os.path.basename(self.root)
        self.modules: List[SourceModule] = []
        self.errors: List[Tuple[str, str]] = []  # (relpath, parse error)
        self._callgraph = None
        self._load()

    def _load(self) -> None:
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__" and not d.startswith(".")
            )
            for filename in sorted(filenames):
                if not filename.endswith(".py"):
                    continue
                path = os.path.join(dirpath, filename)
                relpath = os.path.relpath(path, self.base).replace(os.sep, "/")
                text = open(path, encoding="utf-8").read()
                try:
                    tree = ast.parse(text, filename=relpath)
                except SyntaxError as e:
                    self.errors.append((relpath, str(e)))
                    continue
                mod = relpath[: -len(".py")].replace("/", ".")
                if mod.endswith(".__init__"):
                    mod = mod[: -len(".__init__")]
                self.modules.append(
                    SourceModule(
                        path=path,
                        relpath=relpath,
                        modname=mod,
                        text=text,
                        lines=text.splitlines(),
                        tree=tree,
                    )
                )

    @property
    def callgraph(self):
        if self._callgraph is None:
            from trlx_tpu.analysis.callgraph import CallGraph

            self._callgraph = CallGraph(self)
        return self._callgraph


# ---------------------------------------------------------------------------
# pass registry
# ---------------------------------------------------------------------------


class LintPass:
    """Base class for one analysis pass. Subclasses set ``name`` (the CLI
    selector), ``codes`` (the finding codes they may emit), and implement
    :meth:`run`."""

    name: str = ""
    codes: Tuple[str, ...] = ()
    description: str = ""

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, Type[LintPass]] = {}


def register_pass(cls: Type[LintPass]) -> Type[LintPass]:
    if not cls.name:
        raise ValueError(f"{cls.__name__} must set a pass name")
    if cls.name in _REGISTRY:
        raise ValueError(f"duplicate pass name {cls.name!r}")
    _REGISTRY[cls.name] = cls
    return cls


def _ensure_builtin_passes() -> None:
    # importing the pass modules populates the registry
    from trlx_tpu.analysis import (  # noqa: F401
        collectives,
        conventions,
        determinism,
        jax_passes,
        kernels,
        locks,
        ownership,
    )


def all_passes() -> Dict[str, Type[LintPass]]:
    _ensure_builtin_passes()
    return dict(_REGISTRY)


def get_pass(name: str) -> Type[LintPass]:
    _ensure_builtin_passes()
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown pass {name!r}; available: {sorted(_REGISTRY)}"
        ) from None


def run_analysis(
    root,
    passes: Optional[Iterable[str]] = None,
    ctx: Optional[AnalysisContext] = None,
):
    """Run ``passes`` (default: all registered) over ``root``; findings are
    sorted by (path, line, code) for stable output.

    ``root`` may be one package directory or a list of them (the CI gate
    scans ``trlx_tpu/`` and ``scripts/`` in ONE run so a single baseline
    covers both without cross-root staleness). Single root returns
    ``(findings, ctx)``; a list returns ``(findings, [ctx, ...])``.
    """
    single = isinstance(root, (str, os.PathLike))
    roots = [root] if single else list(root)
    if ctx is not None:
        ctxs = [ctx]
    else:
        ctxs = [AnalysisContext(os.fspath(r)) for r in roots]
    names = list(passes) if passes is not None else sorted(all_passes())
    findings: List[Finding] = []
    for c in ctxs:
        for name in names:
            findings.extend(get_pass(name)().run(c))
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.detail))
    return findings, (ctxs[0] if single else ctxs)


# ---------------------------------------------------------------------------
# structured output (--format json|sarif)
# ---------------------------------------------------------------------------


def _finding_dict(f: Finding) -> Dict:
    return {
        "code": f.code,
        "path": f.path,
        "line": f.line,
        "symbol": f.symbol,
        "detail": f.detail,
        "key": f.key,
        "message": f.message,
    }


def _json_doc(new, stale, suppressed: int, errors) -> Dict:
    return {
        "findings": [_finding_dict(f) for f in new],
        "stale_baseline_entries": [e.key for e in stale],
        "baselined": suppressed,
        "parse_errors": [{"path": p, "error": e} for p, e in errors],
    }


def _code_descriptions() -> Dict[str, str]:
    out: Dict[str, str] = {}
    for cls in all_passes().values():
        for code in cls.codes:
            out[code] = cls.description
    return out


def _sarif_doc(new, stale, errors) -> Dict:
    """SARIF 2.1.0: one run, one result per non-baselined finding (plus one
    per stale baseline entry under the synthetic ``GL000`` rule), so CI can
    annotate findings inline on the PR diff.

    EVERY result carries a ``partialFingerprints`` entry
    (``graftlintKey/v1``) derived from the baseline's line-number-free
    finding key — never from positions — so CI inline annotations survive
    rebases and line drift exactly the way baseline entries do: edits above
    a finding change ``region.startLine`` but not the fingerprint, and the
    annotation platform keeps treating it as the same result."""
    desc = _code_descriptions()
    rules_seen: Dict[str, Dict] = {}
    results = []
    for f in new:
        rules_seen.setdefault(
            f.code,
            {
                "id": f.code,
                "shortDescription": {"text": desc.get(f.code, f.code)},
                "helpUri": "docs/STATIC_ANALYSIS.md",
            },
        )
        results.append(
            {
                "ruleId": f.code,
                "level": "error",
                "message": {"text": f.message},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {"uri": f.path},
                            "region": {"startLine": max(1, f.line)},
                        }
                    }
                ],
                "partialFingerprints": {"graftlintKey/v1": f.key},
            }
        )
    for entry in stale:
        rules_seen.setdefault(
            "GL000",
            {
                "id": "GL000",
                "shortDescription": {
                    "text": "stale baseline entry (fix shipped? delete it)"
                },
            },
        )
        results.append(
            {
                "ruleId": "GL000",
                "level": "error",
                "message": {
                    "text": "stale baseline entry no longer matches any "
                    f"finding: {entry.key}"
                },
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {"uri": "GRAFTLINT_BASELINE.txt"},
                            "region": {"startLine": max(1, entry.line)},
                        }
                    }
                ],
                "partialFingerprints": {"graftlintKey/v1": f"GL000 stale:{entry.key}"},
            }
        )
    for path, err in errors:
        results.append(
            {
                "ruleId": "GL000",
                "level": "error",
                "message": {"text": f"unparseable source: {err}"},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {"uri": path},
                            "region": {"startLine": 1},
                        }
                    }
                ],
                "partialFingerprints": {"graftlintKey/v1": f"GL000 parse:{path}"},
            }
        )
    if errors and "GL000" not in rules_seen:
        rules_seen["GL000"] = {
            "id": "GL000",
            "shortDescription": {"text": "graftlint gate integrity"},
        }
    return {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
        "master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        "informationUri": "docs/STATIC_ANALYSIS.md",
                        "rules": [rules_seen[k] for k in sorted(rules_seen)],
                    }
                },
                "results": results,
            }
        ],
    }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _default_root() -> str:
    # the installed package itself (scripts/graftlint.py and -m invocations
    # from anywhere lint the real tree by default)
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _default_baseline(root: str) -> Optional[str]:
    """``GRAFTLINT_BASELINE.txt`` next to the scan root (the repo root when
    scanning ``trlx_tpu/``) — deliberately NOT $CWD, so linting a scratch
    package from the repo root never applies (or, with
    ``--update-baseline``, clobbers) the repo's committed baseline."""
    cand = os.path.join(
        os.path.dirname(os.path.abspath(root)), "GRAFTLINT_BASELINE.txt"
    )
    return cand if os.path.exists(cand) else None


def main(argv: Optional[List[str]] = None) -> int:
    from trlx_tpu.analysis.baseline import Baseline, BaselineError

    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="JAX-aware whole-program static analysis for trlx_tpu "
        "(docs/STATIC_ANALYSIS.md)",
    )
    parser.add_argument(
        "root",
        nargs="*",
        default=None,
        help="package director(y/ies) to lint (default: the installed "
        "trlx_tpu). Multiple roots share one run — and one baseline, "
        "resolved next to the FIRST root",
    )
    parser.add_argument(
        "--baseline",
        default=None,
        help="baseline/allowlist file (default: GRAFTLINT_BASELINE.txt next "
        "to the scan root; see docs/STATIC_ANALYSIS.md)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore any baseline file: report every finding",
    )
    parser.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline to exactly the current findings, keeping "
        "existing justifications; new entries get a FIXME justification "
        "that must be written before committing",
    )
    parser.add_argument(
        "--select",
        default=None,
        help="comma-separated pass names to run (default: all)",
    )
    parser.add_argument(
        "--list-passes", action="store_true", help="list passes and exit"
    )
    parser.add_argument(
        "--format",
        choices=("human", "json", "sarif"),
        default="human",
        help="output format (default human). json/sarif print the "
        "structured document to stdout — or to --output, keeping the "
        "human rendering on stdout for the terminal",
    )
    parser.add_argument(
        "--output",
        default=None,
        help="write the --format json|sarif document to this path instead "
        "of stdout (human output still prints; CI annotates from the file)",
    )
    args = parser.parse_args(argv)

    if args.list_passes:
        for name, cls in sorted(all_passes().items()):
            codes = ",".join(cls.codes)
            print(f"{name:18s} {codes:22s} {cls.description}")
        return 0

    roots = list(args.root) if args.root else [_default_root()]
    for root in roots:
        if not os.path.isdir(root):
            print(f"graftlint: not a directory: {root}", file=sys.stderr)
            return 2
    if args.no_baseline and args.update_baseline:
        print(
            "graftlint: --no-baseline with --update-baseline would rewrite "
            "the baseline without loading it, destroying every committed "
            "justification — pick one",
            file=sys.stderr,
        )
        return 2
    if args.output and args.format == "human":
        print(
            "graftlint: --output needs --format json|sarif (human output "
            "already goes to stdout)",
            file=sys.stderr,
        )
        return 2
    passes = args.select.split(",") if args.select else None
    try:
        findings, ctxs = run_analysis(roots, passes=passes)
        selected_codes = set()
        for name in passes if passes is not None else sorted(all_passes()):
            selected_codes.update(get_pass(name).codes)
    except KeyError as e:
        print(f"graftlint: {e.args[0]}", file=sys.stderr)
        return 2
    errors: List[Tuple[str, str]] = [e for c in ctxs for e in c.errors]
    n_modules = sum(len(c.modules) for c in ctxs)
    for relpath, err in errors:
        print(f"graftlint: syntax error in {relpath}: {err}", file=sys.stderr)

    baseline_path = args.baseline or _default_baseline(roots[0])
    baseline = Baseline()
    if baseline_path and not args.no_baseline:
        try:
            baseline = Baseline.load(baseline_path)
        except BaselineError as e:
            print(f"graftlint: {e}", file=sys.stderr)
            return 2
        except OSError as e:
            print(f"graftlint: cannot read baseline: {e}", file=sys.stderr)
            return 2
    # entries for passes NOT selected this run are out of scope: they are
    # neither stale (their pass didn't look) nor rewritable by
    # --update-baseline (a pass-filtered update must not delete them)
    out_of_scope = {
        k: e
        for k, e in baseline.entries.items()
        if k.split(" ", 1)[0] not in selected_codes
    }
    baseline = Baseline(
        {k: e for k, e in baseline.entries.items() if k not in out_of_scope}
    )

    if args.update_baseline:
        if errors:
            print(
                "graftlint: refusing --update-baseline with unparseable "
                "sources — their findings would silently drop out",
                file=sys.stderr,
            )
            return 2
        path = baseline_path or _default_baseline(roots[0]) or os.path.join(
            os.path.dirname(os.path.abspath(roots[0])), "GRAFTLINT_BASELINE.txt"
        )
        baseline.update(findings)
        baseline.entries.update(out_of_scope)
        baseline.save(path)
        print(f"graftlint: wrote {len(baseline.entries)} entries to {path}")
        fixmes = [e for e in baseline.entries.values() if e.needs_justification]
        if fixmes:
            print(
                f"graftlint: {len(fixmes)} new entries carry a FIXME "
                "justification — write a real one before committing"
            )
        return 0

    new, stale = baseline.apply(findings)
    suppressed = len(findings) - len(new)

    import json as _json

    if args.format != "human":
        doc = (
            _json_doc(new, stale, suppressed, errors)
            if args.format == "json"
            else _sarif_doc(new, stale, errors)
        )
        if args.output:
            with open(args.output, "w", encoding="utf-8") as f:
                _json.dump(doc, f, indent=2)
                f.write("\n")
            print(f"graftlint: wrote {args.format} to {args.output}")
        else:
            print(_json.dumps(doc, indent=2))
    emit_human = args.format == "human" or bool(args.output)
    if emit_human:
        for f in new:
            print(f.render())
        for entry in stale:
            print(
                f"{baseline_path}: stale baseline entry no longer matches any "
                f"finding (fix shipped? delete the entry): {entry.key}"
            )
    counts: Dict[str, int] = {}
    for f in new:
        counts[f.code] = counts.get(f.code, 0) + 1
    summary = ", ".join(f"{c}×{n}" for c, n in sorted(counts.items()))
    if new or stale:
        if emit_human:
            print(
                f"\ngraftlint: {len(new)} finding(s)"
                + (f" ({summary})" if summary else "")
                + (f", {len(stale)} stale baseline entr(y/ies)" if stale else "")
                + (f"; {suppressed} baselined" if suppressed else "")
                + " — see docs/STATIC_ANALYSIS.md"
            )
        return 1
    if errors:
        if emit_human:
            print(
                f"graftlint: FAILED — {len(errors)} unparseable file(s) "
                "(see stderr); their findings are unknown"
            )
        return 1
    if emit_human:
        print(
            f"graftlint: OK ({n_modules} modules, "
            f"{suppressed} baselined finding(s), 0 new)"
        )
    return 0
