"""Pallas kernel-discipline passes (GL1001-GL1004).

Everything that landed with the native-kernel PRs is guarded by
*convention*: every ``pallas_call`` hides behind the shared
``ops/pallas_utils.py`` gate (``has_pallas_tpu()`` routes Mosaic-less
builds to the XLA reference, ``resolve_interpret()`` selects interpret
mode off-TPU), every ``*_pallas`` metric gauge is stamped from the gate
(a fallback build must not claim kernel=1 in an A/B artifact — a bug
that shipped twice), every kernel body obeys the documented lowering
landmines, and every kernel flavor has an XLA reference pinned
bit-identical by a parity test. This pass family turns each convention
into a whole-program check (docs/STATIC_ANALYSIS.md, "The kernel
discipline contract"):

- **GL1001 — fallback-gate integrity.** A ``pallas_call`` site must not
  be reachable from an entry point without crossing a function that
  consults the shared gate (a call resolving to
  ``pallas_utils.has_pallas_tpu`` / ``resolve_interpret`` /
  ``default_interpret``). The walk goes UP the caller graph from the
  site's enclosing function; ``custom_vjp`` fwd/bwd rules — which have
  no syntactic caller — are stitched to their primal via module-level
  ``X.defvjp(fwd, bwd)`` statements, so ``_flash_bwd_rule`` inherits
  ``flash_attention``'s gate instead of looking like an ungated root.

- **GL1002 — gauge-stamp discipline.** Any store whose key/attribute
  name ends in ``_pallas`` (subscript store, dict literal entry,
  attribute assignment, keyword argument) must not be a truthy literal,
  even wrapped in ``float()``/``bool()``/``asarray()``. Values derived
  from ``has_pallas_tpu()`` (or any non-literal expression) pass; falsy
  literals pass too — a ``False`` default is the pre-gate placeholder,
  and the bug class is exactly "claims kernel=1 unconditionally".

- **GL1003 — kernel-body purity.** Functions passed to ``pallas_call``
  (resolved through the ``functools.partial`` / local-assignment
  machinery the jit-root tracer uses) and ``BlockSpec`` index maps must
  not call host-sync / wall-clock / global-RNG primitives, and must not
  close over a name bound to a concrete ndarray constructor
  (``np.asarray(...)`` et al.) — a captured array constant-folds into
  the lowered program and fakes 1-ulp parity (lowering landmine #4).
  Closing over scalars/ints (block shapes, head counts) is fine; index
  maps stay pure over grid indices + scalar-prefetch refs.

- **GL1004 — parity-coverage registry.** :data:`KERNEL_PARITY` names
  each kernel flavor, its entry point, its XLA reference, and the test
  file pinning bit-parity (the ``RANK_UNIFORM_FIELDS`` pattern: the
  registry IS the justification mechanism, so a GL1004 finding should
  almost never be baselined). A ``pallas_call`` site with no registered
  entry in its upward caller closure is a finding; a registered entry
  whose reference no longer resolves, or whose parity test file is
  gone, is a finding. Growing the kernel surface means growing the
  registry — and the parity suite — in the same PR.

Like every graftlint module this file is stdlib-only: it must import
(and run) in the jax-free CI lint job.
"""

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from trlx_tpu.analysis.callgraph import (
    CallGraph,
    FunctionInfo,
    attr_chain,
)
from trlx_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    LintPass,
    SourceModule,
    register_pass,
)

__all__ = ["KernelDisciplinePass", "KERNEL_PARITY"]


# ---------------------------------------------------------------------------
# the parity registry (GL1004)
# ---------------------------------------------------------------------------

# (flavor, entry point, XLA reference, parity test file) — one row per
# kernel flavor shipped in ops/. The entry point is the function a
# pallas_call site must reach in its upward caller closure; the reference
# is the staged-XLA oracle the parity test pins the kernel against; the
# test path is relative to the repo root. Registering a flavor here is a
# CONTRACT: the reference stays callable and the test file keeps pinning
# bit-equality (docs/STATIC_ANALYSIS.md, "The kernel discipline
# contract").
KERNEL_PARITY: Tuple[Tuple[str, str, str, str], ...] = (
    # in-place paged decode attention (PR 12)
    ("paged-decode", "paged_attention_decode",
     "paged_attention_decode_reference", "tests/test_paged_attention.py"),
    # chunked paged prefill (PR 13)
    ("paged-prefill", "paged_prefill_attention",
     "paged_prefill_attention_reference", "tests/test_paged_attention.py"),
    # multi-position speculative verify — deliberately DELEGATES to the
    # prefill kernel body (one grid, one op sequence); the flavor is
    # registered separately because it has its own entry seam and its own
    # parity pin (the spec-engine acceptance suite)
    ("paged-verify", "paged_verify_attention",
     "paged_prefill_attention_reference", "tests/test_spec_engine.py"),
    # fused temperature/top-k/top-p sampling (PR 16)
    ("fused-sample", "fused_sample",
     "sample_token_from_logits", "tests/test_paged_attention.py"),
    # fused GAE + whiten + PPO loss, fwd + bwd custom_vjp pair (PR 18)
    ("fused-loss", "fused_ppo_loss",
     "fused_ppo_loss_reference", "tests/test_fused_loss.py"),
    # flash attention forward (PR 16)
    ("flash-fwd", "flash_attention",
     "attention_reference", "tests/test_flash_attention.py"),
    # flash attention fused backward (dq+dk+dv)
    ("flash-bwd", "flash_attention_bwd_chunk",
     "attention_reference", "tests/test_flash_attention.py"),
)


# ---------------------------------------------------------------------------
# name classifiers
# ---------------------------------------------------------------------------

# the shared fallback gate: any call resolving (through import aliases)
# to one of these marks its enclosing function gate-bearing. Matching on
# the trailing ``pallas_utils.<fn>`` keeps fixtures honest: a mini-tree
# must route through a module NAMED pallas_utils, same as the real ops/.
_GATE_FNS = ("has_pallas_tpu", "resolve_interpret", "default_interpret")


def _is_gate_name(name: Optional[str]) -> bool:
    if not name:
        return False
    parts = name.split(".")
    return (
        len(parts) >= 2
        and parts[-1] in _GATE_FNS
        and parts[-2] == "pallas_utils"
    )


def _is_pallas_call_name(name: Optional[str]) -> bool:
    if not name:
        return False
    return name == "pallas_call" or name.endswith(".pallas_call")


def _is_block_spec_name(name: Optional[str]) -> bool:
    if not name:
        return False
    return name == "BlockSpec" or name.endswith(".BlockSpec")


# wall-clock / RNG / host-sync primitives a kernel body must never call:
# the body is traced once at lowering time, so a host read bakes a
# constant into the program (and differs between lowerings)
_IMPURE_CALLS = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today",
    "jax.device_get",
    "numpy.asarray", "numpy.array", "numpy.frombuffer",
    "print", "input",
})
_IMPURE_PREFIXES = ("random.", "numpy.random.")
_IMPURE_METHODS = frozenset({"item", "tolist", "block_until_ready"})

# array constructors whose result, captured by a kernel closure, becomes
# a folded constant in the lowered program (lowering landmine #4)
_ARRAY_CONSTRUCTORS = frozenset({
    "numpy.asarray", "numpy.array", "numpy.arange", "numpy.zeros",
    "numpy.ones", "numpy.full", "numpy.linspace", "numpy.eye",
    "jax.numpy.asarray", "jax.numpy.array", "jax.numpy.arange",
    "jax.numpy.zeros", "jax.numpy.ones", "jax.numpy.full",
    "jax.numpy.linspace", "jax.numpy.eye",
})

# literal-unwrapping for GL1002: `float(True)` / `jnp.asarray(1.0)` /
# `np.float32(1)` still stamp a literal
_WRAPPER_FNS = frozenset({"float", "int", "bool", "round", "abs"})
_WRAPPER_METHODS = frozenset({
    "asarray", "array", "float32", "float64", "int32", "int64", "bool_",
})


def _literal_stamp(value: ast.AST) -> Optional[bool]:
    """Truthiness of ``value`` when it is a (possibly wrapped) bool/int/
    float literal; None for any non-literal expression."""
    node = value
    while isinstance(node, ast.Call) and node.args:
        f = node.func
        if isinstance(f, ast.Name) and f.id in _WRAPPER_FNS:
            node = node.args[0]
        elif isinstance(f, ast.Attribute) and f.attr in _WRAPPER_METHODS:
            node = node.args[0]
        else:
            break
    if isinstance(node, ast.Constant) and isinstance(
        node.value, (bool, int, float)
    ):
        return bool(node.value)
    return None


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------


class _Site:
    """One ``pallas_call`` call site."""

    def __init__(
        self,
        call: ast.Call,
        mod: SourceModule,
        fn: Optional[FunctionInfo],
    ):
        self.call = call
        self.mod = mod
        self.fn = fn  # enclosing function (None: module level)


@register_pass
class KernelDisciplinePass(LintPass):
    name = "kernel-discipline"
    codes = ("GL1001", "GL1002", "GL1003", "GL1004")
    description = (
        "Pallas kernel discipline: fallback-gate reachability, *_pallas "
        "gauge stamps, kernel-body purity, parity-registry coverage"
    )

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        graph = ctx.callgraph
        findings: List[Finding] = []
        findings.extend(self._check_gauge_stamps(graph))
        sites = self._collect_sites(graph)
        if sites:
            callers = self._caller_map(graph)
            gated = self._gate_bearing(graph)
            findings.extend(self._check_gates(sites, callers, gated))
            findings.extend(self._check_purity(graph, sites))
            findings.extend(self._check_registry(ctx, graph, sites, callers))
        else:
            findings.extend(self._check_registry(ctx, graph, [], {}))
        findings.sort(key=lambda f: (f.path, f.line, f.code, f.detail))
        return findings

    # -- shared graph views ----------------------------------------------

    def _collect_sites(self, graph: CallGraph) -> List[_Site]:
        sites: List[_Site] = []
        for mod in graph.ctx.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                scope = graph.enclosing_function(mod, node)
                if _is_pallas_call_name(
                    graph.external_name(node.func, scope, mod)
                ):
                    sites.append(_Site(node, mod, scope))
        return sites

    def _caller_map(self, graph: CallGraph) -> Dict[str, List[FunctionInfo]]:
        """Reverse adjacency over the same edges jit tracing uses, plus
        two synthetic rules: a parent function "calls" its nested defs
        (the parent frame is the only way control reaches them), and a
        ``custom_vjp`` primal "calls" the fwd/bwd rules registered by a
        ``X.defvjp(fwd, bwd)`` statement — the rules have no syntactic
        caller, but execute exactly when the primal's callers do."""
        callers: Dict[str, List[FunctionInfo]] = {}
        seen: Set[Tuple[str, str]] = set()

        def add(callee: FunctionInfo, caller: FunctionInfo) -> None:
            if (callee.full, caller.full) in seen:
                return
            seen.add((callee.full, caller.full))
            callers.setdefault(callee.full, []).append(caller)

        for fn in graph.functions:
            for callee in graph.edges(fn):
                add(callee, fn)
            for group in fn.nested.values():
                for nested in group:
                    add(nested, fn)
        for mod in graph.ctx.modules:
            for node in ast.walk(mod.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "defvjp"
                    and len(node.args) >= 2
                ):
                    continue
                scope = graph.enclosing_function(mod, node)
                primals = graph.resolve_callable(node.func.value, scope, mod)
                if not primals:
                    continue
                for arg in node.args[:2]:
                    for rule in graph.resolve_callable_deep(arg, scope, mod):
                        for primal in primals:
                            add(rule, primal)
        return callers

    def _gate_bearing(self, graph: CallGraph) -> Set[str]:
        """``FunctionInfo.full`` of every function whose own body calls
        the shared pallas_utils gate."""
        out: Set[str] = set()
        for fn in graph.functions:
            for node in fn.body_nodes():
                if isinstance(node, ast.Call) and _is_gate_name(
                    graph.external_name(node.func, fn, fn.module)
                ):
                    out.add(fn.full)
                    break
        return out

    def _upward_closure(
        self,
        start: FunctionInfo,
        callers: Dict[str, List[FunctionInfo]],
    ) -> List[FunctionInfo]:
        """Every function from which ``start`` is reachable (including
        ``start``), over the caller map — gate-bearing or not."""
        out: List[FunctionInfo] = []
        seen: Set[str] = set()
        work = [start]
        while work:
            fn = work.pop()
            if fn.full in seen:
                continue
            seen.add(fn.full)
            out.append(fn)
            work.extend(callers.get(fn.full, ()))
        return out

    # -- GL1001: fallback-gate integrity ----------------------------------

    def _check_gates(
        self,
        sites: List[_Site],
        callers: Dict[str, List[FunctionInfo]],
        gated: Set[str],
    ) -> List[Finding]:
        findings: List[Finding] = []
        for site in sites:
            if site.fn is None:
                findings.append(
                    Finding(
                        code="GL1001",
                        path=site.mod.relpath,
                        line=site.call.lineno,
                        symbol="<module>",
                        detail="<module>",
                        message="module-level `pallas_call` runs at import "
                        "time with no fallback gate — wrap it in an entry "
                        "function that consults "
                        "`pallas_utils.has_pallas_tpu()` (docs/"
                        "STATIC_ANALYSIS.md, kernel discipline contract)",
                    )
                )
                continue
            # BFS up the caller graph; a branch crossing a gate-bearing
            # function is safe, a root reached with no gate on the path
            # is an ungated entry
            ungated: Set[str] = set()
            seen: Set[str] = set()
            work = [site.fn]
            while work:
                fn = work.pop()
                if fn.full in seen:
                    continue
                seen.add(fn.full)
                if fn.full in gated:
                    continue
                ups = callers.get(fn.full, ())
                if not ups:
                    ungated.add(fn.qualname)
                    continue
                work.extend(ups)
            for entry in sorted(ungated):
                findings.append(
                    Finding(
                        code="GL1001",
                        path=site.mod.relpath,
                        line=site.call.lineno,
                        symbol=site.fn.qualname,
                        detail=entry,
                        message=f"`pallas_call` in `{site.fn.qualname}` is "
                        f"reachable from entry `{entry}` without crossing "
                        "the shared fallback gate (`pallas_utils."
                        "has_pallas_tpu()` / `resolve_interpret()`): a "
                        "Mosaic-less build takes this path straight into a "
                        "TPU-only lowering — route the kernel-selecting "
                        "branch through the gate, or gate the entry itself",
                    )
                )
        return findings

    # -- GL1002: gauge-stamp discipline -----------------------------------

    def _check_gauge_stamps(self, graph: CallGraph) -> List[Finding]:
        findings: List[Finding] = []
        for mod in graph.ctx.modules:
            for node in ast.walk(mod.tree):
                for gauge, value in self._pallas_stamps(node):
                    if _literal_stamp(value) is not True:
                        continue
                    scope = graph.enclosing_function(mod, value)
                    findings.append(
                        Finding(
                            code="GL1002",
                            path=mod.relpath,
                            line=value.lineno,
                            symbol=scope.qualname if scope else "<module>",
                            detail=gauge,
                            message=f"`{gauge}` is stamped from a truthy "
                            "literal: a build without the Mosaic backend "
                            "would still claim kernel=1 in the artifact "
                            "(the twice-shipped fallback-gauge bug) — "
                            "derive the value from `pallas_utils."
                            "has_pallas_tpu()` instead",
                        )
                    )
        return findings

    def _pallas_stamps(
        self, node: ast.AST
    ) -> List[Tuple[str, ast.AST]]:
        """(gauge name, value expr) for every ``*_pallas`` store in
        ``node``: subscript stores with a literal string key, attribute
        assignments, dict-literal entries, and keyword arguments.
        ``AnnAssign`` field declarations are exempt — a dataclass default
        is the pre-gate placeholder, not a stamp (and must be falsy to
        pass the literal check anyway)."""
        out: List[Tuple[str, ast.AST]] = []
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.slice, ast.Constant)
                    and isinstance(t.slice.value, str)
                    and t.slice.value.endswith("_pallas")
                ):
                    out.append((t.slice.value, node.value))
                elif isinstance(t, ast.Attribute) and t.attr.endswith(
                    "_pallas"
                ):
                    out.append((t.attr, node.value))
        elif isinstance(node, ast.Dict):
            for key, value in zip(node.keys, node.values):
                if (
                    isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    and key.value.endswith("_pallas")
                ):
                    out.append((key.value, value))
        elif isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg and kw.arg.endswith("_pallas"):
                    out.append((kw.arg, kw.value))
        return out

    # -- GL1003: kernel-body purity ---------------------------------------

    def _check_purity(
        self, graph: CallGraph, sites: List[_Site]
    ) -> List[Finding]:
        findings: List[Finding] = []
        checked: Set[str] = set()

        def check(fn: FunctionInfo, kind: str) -> None:
            if fn.full in checked:
                return
            checked.add(fn.full)
            findings.extend(self._purity_of(graph, fn, kind))

        for site in sites:
            if not site.call.args:
                continue
            for fn in graph.resolve_callable_deep(
                site.call.args[0], site.fn, site.mod
            ):
                check(fn, "kernel")
        # index maps: the 2nd positional arg / index_map= of every
        # BlockSpec in the tree (grid-spec factories build them far from
        # the pallas_call site, so scope is package-wide)
        for mod in graph.ctx.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                scope = graph.enclosing_function(mod, node)
                if not _is_block_spec_name(
                    graph.external_name(node.func, scope, mod)
                ):
                    continue
                exprs: List[ast.AST] = []
                if len(node.args) >= 2:
                    exprs.append(node.args[1])
                for kw in node.keywords:
                    if kw.arg == "index_map":
                        exprs.append(kw.value)
                for expr in exprs:
                    if isinstance(expr, ast.Lambda):
                        for fn in graph.functions:
                            if fn.module is mod and fn.node is expr:
                                check(fn, "index map")
                    else:
                        for fn in graph.resolve_callable_deep(
                            expr, scope, mod
                        ):
                            check(fn, "index map")
        return findings

    def _purity_of(
        self, graph: CallGraph, fn: FunctionInfo, kind: str
    ) -> List[Finding]:
        findings: List[Finding] = []
        seen: Set[str] = set()

        def emit(line: int, detail: str, what: str) -> None:
            if detail in seen:
                return
            seen.add(detail)
            findings.append(
                Finding(
                    code="GL1003",
                    path=fn.module.relpath,
                    line=line,
                    symbol=fn.qualname,
                    detail=detail,
                    message=f"{kind} `{fn.qualname}` {what} — the body is "
                    "traced once at lowering time, so host state bakes "
                    "into the program as a constant (lowering landmine: "
                    "constant folding fakes parity; docs/STATIC_ANALYSIS"
                    ".md, kernel discipline contract)",
                )
            )

        for node in fn.body_nodes():
            if isinstance(node, ast.Call):
                name = graph.external_name(node.func, fn, fn.module)
                if name in _IMPURE_CALLS or (
                    name
                    and name.startswith(_IMPURE_PREFIXES)
                ):
                    emit(node.lineno, name, f"calls host primitive `{name}()`")
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _IMPURE_METHODS
                ):
                    emit(
                        node.lineno,
                        f".{node.func.attr}",
                        f"calls host-sync method `.{node.func.attr}()`",
                    )
            elif isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load
            ):
                binding = self._ndarray_binding(graph, fn, node.id)
                if binding is not None:
                    emit(
                        node.lineno,
                        node.id,
                        f"closes over `{node.id}`, bound to a concrete "
                        f"ndarray (`{binding}`)",
                    )
        return findings

    def _ndarray_binding(
        self, graph: CallGraph, fn: FunctionInfo, name: str
    ) -> Optional[str]:
        """Canonical constructor name when free-variable ``name``, looked
        up through the enclosing scopes then module level, is bound to an
        array-constructor call in the same module; None otherwise
        (locals, params, scalars, imported names)."""
        if name in fn.bound:
            return None  # a local/param of the kernel itself

        def ctor_of(stmts, scope) -> Optional[str]:
            hit = None
            for node in stmts:
                if not isinstance(node, ast.Assign):
                    continue
                if not any(
                    isinstance(t, ast.Name) and t.id == name
                    for t in node.targets
                ):
                    continue
                value = node.value
                cname = (
                    graph.external_name(value.func, scope, fn.module)
                    if isinstance(value, ast.Call)
                    else None
                )
                # every binding must be an array ctor: a rebind to a
                # scalar (or anything else) clears the verdict
                hit = cname if cname in _ARRAY_CONSTRUCTORS else None
                if hit is None:
                    return None
            return hit

        look = fn.parent
        while look is not None:
            if name in look.bound:
                return ctor_of(look.body_nodes(), look)
            look = look.parent
        if name in graph.imports.get(fn.module.modname, {}):
            return None  # imported name: resolved elsewhere, not a capture
        return ctor_of(fn.module.tree.body, None)

    # -- GL1004: parity-coverage registry ---------------------------------

    def _check_registry(
        self,
        ctx: AnalysisContext,
        graph: CallGraph,
        sites: List[_Site],
        callers: Dict[str, List[FunctionInfo]],
    ) -> List[Finding]:
        findings: List[Finding] = []
        entries = {entry for _, entry, _, _ in KERNEL_PARITY}
        # (a) every pallas_call site reaches a registered entry upward
        for site in sites:
            covered = False
            if site.fn is not None:
                for fn in self._upward_closure(site.fn, callers):
                    if fn.qualname.rsplit(".", 1)[-1] in entries:
                        covered = True
                        break
            if not covered:
                symbol = site.fn.qualname if site.fn else "<module>"
                findings.append(
                    Finding(
                        code="GL1004",
                        path=site.mod.relpath,
                        line=site.call.lineno,
                        symbol=symbol,
                        detail=symbol,
                        message=f"`pallas_call` in `{symbol}` reaches no "
                        "entry registered in KERNEL_PARITY (analysis/"
                        "kernels.py): a kernel flavor without a pinned "
                        "XLA reference has no bit-parity story — add the "
                        "flavor (entry, reference, parity test) to the "
                        "registry AND the parity suite in the same PR",
                    )
                )
        # (b) registered flavors present in this tree keep their
        # reference and their parity test. Entries that do not resolve
        # here are someone else's tree (fixture mini-packages, the
        # scripts/ root) — vacuous by design, like DeterminismPass roots.
        for flavor, entry, reference, test_path in KERNEL_PARITY:
            entry_fns = graph.resolve_root_names([entry])
            if not entry_fns:
                continue
            fn = entry_fns[0]
            if not graph.resolve_root_names([reference]):
                findings.append(
                    Finding(
                        code="GL1004",
                        path=fn.module.relpath,
                        line=fn.node.lineno,
                        symbol=fn.qualname,
                        detail=f"{flavor}:reference:{reference}",
                        message=f"KERNEL_PARITY flavor `{flavor}` names "
                        f"reference `{reference}`, which no longer "
                        "resolves in the tree — the kernel lost its XLA "
                        "oracle; restore the reference or re-register "
                        "the flavor",
                    )
                )
            if not os.path.exists(os.path.join(ctx.base, test_path)):
                findings.append(
                    Finding(
                        code="GL1004",
                        path=fn.module.relpath,
                        line=fn.node.lineno,
                        symbol=fn.qualname,
                        detail=f"{flavor}:test:{test_path}",
                        message=f"KERNEL_PARITY flavor `{flavor}` pins "
                        f"bit-parity in `{test_path}`, which does not "
                        "exist — the flavor lost its parity test root; "
                        "restore the test or re-register the flavor",
                    )
                )
        return findings
