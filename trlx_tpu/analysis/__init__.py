"""graftlint: JAX-aware whole-program static analysis for trlx_tpu.

CLI: ``python -m trlx_tpu.analysis [trlx_tpu/]`` (or ``scripts/graftlint.py``
/ ``scripts/lint.py`` — all three are the same entry point: the scripts are
thin wrappers over this package's ``main``). Passes: host-sync,
recompile-hazard, donation-safety, lock-discipline, thread-escape,
collective-discipline, ownership, determinism, metric-names, span-names,
config-keys — catalog and baseline workflow in docs/STATIC_ANALYSIS.md.

Pure stdlib + AST: the linter parses source text and never *executes* the
code it lints (no jax backend is initialized), so it runs in CI before any
accelerator exists.
"""

from trlx_tpu.analysis.baseline import Baseline, BaselineEntry, BaselineError
from trlx_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    LintPass,
    all_passes,
    get_pass,
    main,
    register_pass,
    run_analysis,
)

__all__ = [
    "AnalysisContext",
    "Baseline",
    "BaselineEntry",
    "BaselineError",
    "Finding",
    "LintPass",
    "all_passes",
    "get_pass",
    "main",
    "register_pass",
    "run_analysis",
]
